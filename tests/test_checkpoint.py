"""Checkpoint/resume: a killed-and-resumed run must reproduce the
uninterrupted run bit-for-bit (every randomness source — data shuffle,
commit permutations, dropout rngs — is keyed by saved state)."""

import jax
import numpy as np
import pytest

from distkeras_tpu.checkpoint import load_checkpoint, save_checkpoint
from distkeras_tpu.data import datasets
from distkeras_tpu.models import model_config
from distkeras_tpu.trainers import ADAG, EnsembleTrainer, SingleTrainer

MLP = model_config("mlp", (8,), num_classes=4, hidden=(16,))
DATA = datasets.synthetic_classification(1024, (8,), 4, seed=0)


def _leaves(variables):
    return [np.asarray(x) for x in
            jax.tree_util.tree_leaves(variables["params"])]


def test_save_load_roundtrip_with_prng_keys(tmp_path):
    state = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
             "key": jax.random.key(7)}
    save_checkpoint(tmp_path, state, {"epoch": 3})
    template = {"w": np.zeros((2, 3), np.float32),
                "key": jax.random.key(0)}
    loaded, cursor = load_checkpoint(tmp_path, template)
    assert cursor == {"epoch": 3}
    np.testing.assert_array_equal(loaded["w"], state["w"])
    # the restored key must continue the same stream
    a = jax.random.normal(jax.random.split(state["key"])[0], (3,))
    b = jax.random.normal(jax.random.split(loaded["key"])[0], (3,))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_ps_snapshot_template_free_roundtrip(tmp_path):
    """``save_ps_snapshot``/``load_ps_snapshot`` restore WITHOUT a
    template (a warm-restarting PS has none — its state died with the
    process): the self-describing msgpack encoding carries nested
    trees, scalars, and dtypes, and the tmp+rename write is atomic
    (no .tmp litter)."""
    from distkeras_tpu.checkpoint import (load_ps_snapshot,
                                          save_ps_snapshot)

    snap = {"center": {"layer": {"w": np.arange(6, dtype=np.float32)}},
            "clock": 7,
            "seqs": {"0": np.uint64(2 ** 63)}}
    path = tmp_path / "ps.snap"
    save_ps_snapshot(path, snap)
    assert not list(tmp_path.glob("*.tmp"))
    loaded = load_ps_snapshot(path)
    np.testing.assert_array_equal(loaded["center"]["layer"]["w"],
                                  snap["center"]["layer"]["w"])
    assert int(loaded["clock"]) == 7
    assert int(loaded["seqs"]["0"]) == 2 ** 63


def test_single_trainer_kill_and_resume_bitwise(tmp_path):
    kwargs = dict(worker_optimizer="adam", learning_rate=3e-3,
                  batch_size=64, num_epoch=3, seed=1)
    ref = SingleTrainer(MLP, **kwargs)
    ref.train(DATA)

    part = SingleTrainer(MLP, checkpoint_dir=str(tmp_path),
                         **{**kwargs, "num_epoch": 2})  # "killed" at 2/3
    part.train(DATA)
    resumed = SingleTrainer(MLP, **kwargs)
    resumed.train(DATA, resume_from=str(tmp_path))

    for a, b in zip(_leaves(ref.trained_variables),
                    _leaves(resumed.trained_variables)):
        np.testing.assert_array_equal(a, b)
    assert resumed.history["epoch_loss"] == ref.history["epoch_loss"]


def test_adag_kill_and_resume_bitwise(tmp_path):
    kwargs = dict(num_workers=4, communication_window=2, batch_size=16,
                  num_epoch=2, learning_rate=0.05, seed=2)
    ref = ADAG(MLP, **kwargs)
    ref.train(DATA)

    part = ADAG(MLP, checkpoint_dir=str(tmp_path),
                **{**kwargs, "num_epoch": 1})
    part.train(DATA)
    resumed = ADAG(MLP, **kwargs)
    resumed.train(DATA, resume_from=str(tmp_path))

    for a, b in zip(_leaves(ref.trained_variables),
                    _leaves(resumed.trained_variables)):
        np.testing.assert_array_equal(a, b)
    assert (resumed.history["round_loss"] == ref.history["round_loss"])


def test_adag_mid_epoch_round_resume(tmp_path):
    """checkpoint_every_rounds: resuming from a mid-epoch round cursor
    reproduces the uninterrupted center exactly."""
    kwargs = dict(num_workers=4, communication_window=2, batch_size=16,
                  num_epoch=1, learning_rate=0.05, seed=3)
    ref = ADAG(MLP, **kwargs)
    ref.train(DATA)  # 1024/(4*16)=16 batches/worker -> 8 rounds

    class StopAfter(Exception):
        pass

    part = ADAG(MLP, checkpoint_dir=str(tmp_path),
                checkpoint_every_rounds=3, **kwargs)
    # simulate a crash: stop the run right after round 3's save
    orig = part._maybe_save
    calls = []

    def saving(state, cursor):
        orig(state, cursor)
        calls.append(cursor)
        if cursor.get("round") == 3:
            raise StopAfter

    part._maybe_save = saving
    with pytest.raises(StopAfter):
        part.train(DATA)
    assert calls[-1]["round"] == 3

    resumed = ADAG(MLP, **kwargs)
    resumed.train(DATA, resume_from=str(tmp_path))
    for a, b in zip(_leaves(ref.trained_variables),
                    _leaves(resumed.trained_variables)):
        np.testing.assert_array_equal(a, b)
    # history must also match the uninterrupted run (epoch_loss seeded
    # from restored pre-kill rounds; no duplicate tail-batch entries)
    assert resumed.history["round_loss"] == ref.history["round_loss"]
    assert resumed.history["epoch_loss"] == ref.history["epoch_loss"]
    assert (resumed.history["dropped_tail_batches"]
            == ref.history["dropped_tail_batches"])


def test_ensemble_rejects_resume_and_checkpoint_dir(tmp_path):
    t = EnsembleTrainer(MLP, num_models=2, batch_size=32, num_epoch=1)
    with pytest.raises(ValueError):
        t.train(DATA, resume_from=str(tmp_path))
    t2 = EnsembleTrainer(MLP, num_models=2, batch_size=32, num_epoch=1,
                         checkpoint_dir=str(tmp_path))
    with pytest.raises(ValueError):
        t2.train(DATA)


def test_save_load_dotted_dir_and_explicit_file(tmp_path):
    """Dotted directory names (runs/v1.5) are directories, not files;
    explicit .msgpack file paths get their parents created."""
    state = {"w": np.ones(3, np.float32)}
    dotted = tmp_path / "runs" / "v1.5"
    dotted.mkdir(parents=True)
    written = save_checkpoint(dotted, state, {"epoch": 1})
    assert written.endswith("ckpt_latest.msgpack")
    loaded, cursor = load_checkpoint(dotted, {"w": np.zeros(3, np.float32)})
    assert cursor == {"epoch": 1}
    np.testing.assert_array_equal(loaded["w"], state["w"])

    explicit = tmp_path / "out" / "model.msgpack"  # parent doesn't exist
    written = save_checkpoint(explicit, state, {"epoch": 2})
    assert written == str(explicit)
    _, cursor = load_checkpoint(explicit, {"w": np.zeros(3, np.float32)})
    assert cursor == {"epoch": 2}


def test_sync_tp_kill_and_resume(tmp_path):
    """Single-host tensor-parallel state checkpoints and resumes:
    the (workers, model)-sharded TrainState is fully addressable, so
    save/load round-trips and continuation matches the uninterrupted
    run."""
    from distkeras_tpu.trainers import SyncTrainer

    kwargs = dict(worker_optimizer="adam", learning_rate=3e-3,
                  batch_size=16, num_epoch=3, seed=2, num_workers=2,
                  model_parallel=2)
    ref = SyncTrainer(MLP, **kwargs)
    ref.train(DATA)

    part = SyncTrainer(MLP, checkpoint_dir=str(tmp_path),
                       **{**kwargs, "num_epoch": 2})
    part.train(DATA)
    resumed = SyncTrainer(MLP, **kwargs)
    resumed.train(DATA, resume_from=str(tmp_path))

    for a, b in zip(_leaves(ref.trained_variables),
                    _leaves(resumed.trained_variables)):
        np.testing.assert_array_equal(a, b)
    assert resumed.history["epoch_loss"] == ref.history["epoch_loss"]


def test_sharded_roundtrip_bitwise(tmp_path, devices):
    """orbax-backed sharded checkpoint: TP-sharded TrainState saves
    shard-wise and restores INTO the mesh shardings, bitwise."""
    from distkeras_tpu import mesh as mesh_lib
    from distkeras_tpu.checkpoint import (has_sharded, load_sharded,
                                          save_sharded)
    from distkeras_tpu.models import ModelSpec
    from distkeras_tpu.parallel import tensor_parallel as tp
    from distkeras_tpu.workers import TrainState, resolve_optimizer

    spec = ModelSpec.from_config(MLP)
    variables = spec.build().init(jax.random.key(0),
                                  np.zeros((2, 8), np.float32))
    state = TrainState.create(variables, resolve_optimizer("adam", 1e-3),
                              jax.random.key(1))
    mesh = mesh_lib.create_mesh(4, model_parallel=2)
    shardings = tp.tree_shardings(mesh, state, tp.rules_for("mlp"))
    state = jax.device_put(state, shardings)

    assert not has_sharded(tmp_path)
    save_sharded(tmp_path, state, {"epoch": 2})
    assert has_sharded(tmp_path)
    loaded, cursor = load_sharded(tmp_path, state)
    assert cursor == {"epoch": 2}
    for a, b in zip(jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, state.params)),
            jax.tree_util.tree_leaves(
                jax.tree_util.tree_map(np.asarray, loaded.params))):
        np.testing.assert_array_equal(a, b)
    # shardings restored, not just values
    flat_s = jax.tree_util.tree_leaves(
        state, is_leaf=lambda x: hasattr(x, "sharding"))
    flat_l = jax.tree_util.tree_leaves(
        loaded, is_leaf=lambda x: hasattr(x, "sharding"))
    for a, b in zip(flat_s, flat_l):
        assert a.sharding == b.sharding


def test_sync_tp_resume_from_sharded_checkpoint(tmp_path, devices):
    """SyncTrainer resumes from a sharded (orbax) checkpoint dir: the
    continuation matches the uninterrupted run."""
    from distkeras_tpu import mesh as mesh_lib
    from distkeras_tpu.checkpoint import (load_checkpoint, save_sharded)
    from distkeras_tpu.models import ModelSpec
    from distkeras_tpu.parallel import tensor_parallel as tp
    from distkeras_tpu.trainers import SyncTrainer
    from distkeras_tpu.workers import TrainState, resolve_optimizer

    kwargs = dict(worker_optimizer="adam", learning_rate=3e-3,
                  batch_size=16, num_epoch=3, seed=2, num_workers=2,
                  model_parallel=2)
    ref = SyncTrainer(MLP, **kwargs)
    ref.train(DATA)

    msgpack_dir = tmp_path / "msgpack"
    part = SyncTrainer(MLP, checkpoint_dir=str(msgpack_dir),
                       **{**kwargs, "num_epoch": 2})
    part.train(DATA)

    # convert the killed-at-2/3 checkpoint to the sharded layout (what
    # a multi-host TP run writes) and resume from it
    spec = ModelSpec.from_config(MLP)
    variables = spec.build().init(jax.random.key(0),
                                  np.zeros((2, 8), np.float32))
    template = TrainState.create(
        variables, resolve_optimizer("adam", 3e-3), jax.random.key(0))
    host_state, cursor = load_checkpoint(msgpack_dir, template)
    mesh = mesh_lib.create_mesh(2, model_parallel=2)
    sharded_state = jax.device_put(
        host_state, tp.tree_shardings(mesh, host_state,
                                      tp.rules_for("mlp")))
    sharded_dir = tmp_path / "sharded"
    save_sharded(sharded_dir, sharded_state, cursor)

    resumed = SyncTrainer(MLP, **kwargs)
    resumed.train(DATA, resume_from=str(sharded_dir))
    for a, b in zip(_leaves(ref.trained_variables),
                    _leaves(resumed.trained_variables)):
        np.testing.assert_array_equal(a, b)
    assert resumed.history["epoch_loss"] == ref.history["epoch_loss"]


def test_msgpack_save_clears_stale_sharded_layout(tmp_path, devices):
    """One layout per dir: a later msgpack save (single-host run)
    removes a stale sharded checkpoint so resume can't silently restore
    old state."""
    from distkeras_tpu.checkpoint import has_sharded, save_sharded
    from distkeras_tpu.models import ModelSpec
    from distkeras_tpu.trainers import SingleTrainer
    from distkeras_tpu.workers import TrainState, resolve_optimizer

    spec = ModelSpec.from_config(MLP)
    variables = spec.build().init(jax.random.key(0),
                                  np.zeros((2, 8), np.float32))
    state = TrainState.create(variables,
                              resolve_optimizer("adam", 1e-3),
                              jax.random.key(1))
    save_sharded(tmp_path, state, {"epoch": 9})
    assert has_sharded(tmp_path)

    t = SingleTrainer(MLP, checkpoint_dir=str(tmp_path),
                      worker_optimizer="adam", learning_rate=3e-3,
                      batch_size=64, num_epoch=1)
    t.train(DATA)
    assert not has_sharded(tmp_path)  # stale layout gone


def test_incomplete_sharded_save_is_invisible(tmp_path):
    """has_sharded requires a complete save: a pointer to a missing
    save point (crash mid-write) reads as no checkpoint."""
    from distkeras_tpu.checkpoint import SHARDED, has_sharded

    root = tmp_path / SHARDED
    root.mkdir(parents=True)
    assert not has_sharded(tmp_path)  # no pointer
    (root / "LATEST").write_text("state_epoch3")
    assert not has_sharded(tmp_path)  # pointer to nothing


def test_adag_tensor_parallel_kill_and_resume_bitwise(tmp_path):
    """msgpack kill/resume also covers the TP-sharded PS state (the
    template is sharded; restored host arrays re-place via the jit
    contract)."""
    kwargs = dict(num_workers=4, model_parallel=2,
                  communication_window=2, batch_size=16, num_epoch=2,
                  learning_rate=0.05, seed=2)
    ref = ADAG(MLP, **kwargs)
    ref.train(DATA)

    part = ADAG(MLP, checkpoint_dir=str(tmp_path),
                **{**kwargs, "num_epoch": 1})
    part.train(DATA)
    resumed = ADAG(MLP, **kwargs)
    resumed.train(DATA, resume_from=str(tmp_path))

    for a, b in zip(_leaves(ref.trained_variables),
                    _leaves(resumed.trained_variables)):
        np.testing.assert_array_equal(a, b)
    assert resumed.history["round_loss"] == ref.history["round_loss"]


def test_ps_snapshot_center_resolves_file_and_dict(tmp_path):
    """ISSUE 7 satellite: ``ps_snapshot_center`` lifts just the center
    tree out of a PS snapshot (file or dict) — the serving gateway's
    rolling-update source — for both the unsharded and sharded
    formats, and rejects non-snapshot payloads."""
    from distkeras_tpu.checkpoint import (ps_snapshot_center,
                                          save_ps_snapshot)
    from distkeras_tpu.parallel.host_ps import HostParameterServer
    from distkeras_tpu.parallel.sharded_ps import (
        ShardedParameterServer)
    from distkeras_tpu.parallel.update_rules import DownpourRule

    center = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
              "b": np.zeros((3,), np.float32)}

    snap = HostParameterServer(DownpourRule(), center).snapshot()
    path = save_ps_snapshot(tmp_path / "ps.msgpack", snap)
    for got in (ps_snapshot_center(path), ps_snapshot_center(snap)):
        assert set(got) == {"w", "b"}
        np.testing.assert_array_equal(got["w"], center["w"])

    sharded = ShardedParameterServer(DownpourRule(), center,
                                     num_shards=2).snapshot()
    spath = save_ps_snapshot(tmp_path / "sps.msgpack", sharded)
    got = ps_snapshot_center(spath)
    np.testing.assert_array_equal(got["w"], center["w"])

    with pytest.raises(ValueError, match="no 'center' key"):
        ps_snapshot_center({"state": 1})

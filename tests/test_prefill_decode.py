"""Disaggregated prefill/decode (ISSUE 19): the KV page interchange
(``export_prefix``/``import_prefix`` + the ``"kv"`` wire codec), the
two-stage ``PrefillDecodeRouter``, the page-headroom routing fix in
``ServingGateway``, the ``prefill_heavy`` trace tenant, and the new
tail-latency SLO signals.

The correctness bar everywhere is the engine's own: a request admitted
on a decode replica with imported KV blocks must produce the same
greedy tokens as a solo ``DecodeEngine`` / ``models.generate`` run —
byte-identical, exactly once, through kills and requeues."""

import socket
import time

import jax
import numpy as np
import pytest

from distkeras_tpu import flight_recorder, telemetry
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.gateway import (EngineReplica, PrefillDecodeRouter,
                                   RemoteReplica, ReplicaServer,
                                   ServingGateway)
from distkeras_tpu.models import ModelSpec, generate, model_config
from distkeras_tpu.parallel import transport
from distkeras_tpu.parallel.faults import ChaosTransport
from distkeras_tpu.serving import (DecodeEngine, pack_kv_blocks,
                                   unpack_kv_blocks)

jax.config.update("jax_platforms", "cpu")


@pytest.fixture(autouse=True)
def _racecheck():
    racecheck.enable()
    yield
    reports = racecheck.disable()
    assert not reports, "\n".join(str(r) for r in reports)


MAXLEN, VOCAB, ALIGN = 32, 37, 4


@pytest.fixture(scope="module")
def mv():
    spec = model_config("transformer_lm", (MAXLEN,),
                        input_dtype="int32", vocab_size=VOCAB,
                        num_layers=1, d_model=32, num_heads=2,
                        max_len=MAXLEN, dtype="float32")
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           np.zeros((2, MAXLEN), np.int32))
    return model, variables


def _prompts(lengths, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, (t,)).astype(np.int32)
            for t in lengths]


def _want(mv, prompt, n_new):
    model, variables = mv
    return np.asarray(generate(model, variables, prompt[None, :],
                               max_new_tokens=n_new))[0, len(prompt):]


def _engine(mv, **kw):
    model, variables = mv
    kw.setdefault("slots", 2)
    kw.setdefault("prefill_align", ALIGN)
    kw.setdefault("max_new_tokens", 5)
    kw.setdefault("prefix_cache_bytes", 1 << 22)
    return DecodeEngine(model, variables, **kw)


# ---- the KV page-block wire codec -------------------------------------


def test_kv_codec_socket_roundtrip():
    """``pack_kv_blocks`` gather-sent over a REAL socket and received
    with ``recv_msg_into`` reproduces every leaf byte-for-byte —
    shapes, dtypes (including an ml_dtypes one), block structure."""
    import ml_dtypes

    rng = np.random.default_rng(0)
    leaves = [
        lambda: rng.normal(size=(1, 2, ALIGN, 8)).astype(np.float32),
        lambda: rng.normal(size=(1, 2, ALIGN, 8)).astype(
            ml_dtypes.bfloat16),
        lambda: rng.integers(0, 99, (1, ALIGN)).astype(np.int32),
    ]
    export = {"prompt": np.arange(3 * ALIGN, dtype=np.int32),
              "n_blocks": 3, "weights_ver": 7,
              "blocks": [[mk() for mk in leaves] for _ in range(3)]}
    a, b = socket.socketpair()
    try:
        transport.send_msg_gather(a, *pack_kv_blocks(export))
        got = unpack_kv_blocks(transport.recv_msg_into(b))
    finally:
        a.close()
        b.close()
    np.testing.assert_array_equal(got["prompt"], export["prompt"])
    assert got["n_blocks"] == 3 and got["weights_ver"] == 7
    for want_blk, got_blk in zip(export["blocks"], got["blocks"]):
        for w, g in zip(want_blk, got_blk):
            assert g.shape == w.shape and g.dtype == w.dtype
            np.testing.assert_array_equal(
                np.asarray(g).view(np.uint8),
                np.asarray(w).view(np.uint8))


def test_kv_codec_rejects_garbage():
    with pytest.raises(ValueError):
        unpack_kv_blocks(memoryview(b"Xjunk"))


# ---- export -> import -> byte-identical admission ---------------------


@pytest.mark.parametrize("paged", [False, True],
                         ids=["envelope", "paged"])
def test_export_import_parity(mv, paged):
    """Blocks exported from a prefill-role engine and imported into a
    fresh decode-role engine admit the request through the prefix-hit
    path: tokens byte-identical to ``models.generate``, on BOTH the
    envelope and the paged engine."""
    prompt = _prompts([13])[0]
    src = _engine(mv, prefill_chunk=ALIGN)
    list(src.run([{"prompt": prompt, "max_new_tokens": 4}]))
    export = src.export_prefix(prompt)
    assert export is not None
    assert export["n_blocks"] == len(prompt) // ALIGN

    kw = dict(kv_pages=32, page_size=ALIGN) if paged else {}
    dst = _engine(mv, **kw)
    assert dst.match_blocks(prompt) == 0
    installed = dst.import_prefix(prompt, export["blocks"],
                                  export["weights_ver"])
    assert installed == export["n_blocks"]
    assert dst.match_blocks(prompt) == export["n_blocks"]
    [res] = list(dst.run([{"prompt": prompt, "max_new_tokens": 4}]))
    np.testing.assert_array_equal(res["tokens"], _want(mv, prompt, 4))


def test_export_import_parity_through_wire_codec(mv):
    """Same parity bar with the blocks round-tripped through the wire
    codec bytes (what actually crosses the socket)."""
    prompt = _prompts([9], seed=5)[0]
    src = _engine(mv)
    list(src.run([{"prompt": prompt, "max_new_tokens": 5}]))
    export = src.export_prefix(prompt)
    body = b"".join(bytes(p) for p in pack_kv_blocks(export))
    got = unpack_kv_blocks(memoryview(body))
    dst = _engine(mv)
    assert dst.import_prefix(got["prompt"], got["blocks"],
                             got["weights_ver"]) == got["n_blocks"]
    [res] = list(dst.run([{"prompt": prompt, "max_new_tokens": 5}]))
    np.testing.assert_array_equal(res["tokens"], _want(mv, prompt, 5))


def test_import_prefix_guards(mv):
    """Stale-weights imports are refused; re-imports of blocks the
    store already holds install nothing (the cluster-tier probe's
    contract: ``match_blocks`` says what shipping would add)."""
    prompt = _prompts([8], seed=7)[0]
    src = _engine(mv)
    list(src.run([{"prompt": prompt, "max_new_tokens": 3}]))
    export = src.export_prefix(prompt)
    dst = _engine(mv)
    assert dst.import_prefix(prompt, export["blocks"],
                             weights_ver=export["weights_ver"] + 1) == 0
    assert dst.match_blocks(prompt) == 0
    assert dst.import_prefix(prompt, export["blocks"],
                             export["weights_ver"]) == 2
    # second ship: everything already local, nothing installed
    assert dst.import_prefix(prompt, export["blocks"],
                             export["weights_ver"]) == 0


# ---- the two-stage router ---------------------------------------------


def test_router_end_to_end_parity_and_counters(mv, tmp_path):
    """Mixed short/long prompts through 1 prefill + 2 decode replicas
    (one paged, one envelope): every result byte-identical, pages
    shipped counted, zero requeues, healthz ok."""
    tel = telemetry.enable()
    try:
        router = PrefillDecodeRouter(
            [EngineReplica(_engine(mv, prefill_chunk=ALIGN),
                           name="p0")],
            [EngineReplica(_engine(mv, kv_pages=32, page_size=ALIGN),
                           name="d0"),
             EngineReplica(_engine(mv), name="d1")],
            block_size=ALIGN)
        with router:
            work = [(p, 3 + i % 3) for i, p in enumerate(
                _prompts([3, 12, 7, 13, 2, 9], seed=11))]
            rids = [router.submit(p, max_new_tokens=n)
                    for p, n in work]
            results = [router.result(r, timeout=120) for r in rids]
            hz = router.healthz()
        # compile stalls on these UNWARMED engines legitimately land
        # in the inter-token histogram and can trip the SLO rollup, so
        # pin pool liveness, not the SLO verdict
        assert hz["alive"] == {"prefill": 1, "decode": 2}, hz
        assert len({r["request_id"] for r in results}) == len(work)
        for (p, n), r in zip(work, results):
            assert r.get("error") is None, r
            np.testing.assert_array_equal(r["tokens"],
                                          _want(mv, p, n))
        counters = tel.metrics.snapshot()["counters"]
        assert counters["serving_kv_pages_shipped_total"] > 0
        assert counters["serving_handoff_requeue_total"] == 0
    finally:
        telemetry.disable()


def test_router_survives_dead_prefill_pool(mv):
    """A dead prefill pool degrades to decode-side recompute — same
    tokens, no lost request."""
    prefill = EngineReplica(_engine(mv), name="p0")
    router = PrefillDecodeRouter(
        [prefill], [EngineReplica(_engine(mv), name="d0")],
        block_size=ALIGN, retries=1, backoff_base=0.001)
    with router:
        prefill.kill()
        p = _prompts([10], seed=2)[0]
        res = router.result(router.submit(p, max_new_tokens=4),
                            timeout=120)
        assert res.get("error") is None, res
        np.testing.assert_array_equal(res["tokens"], _want(mv, p, 4))
        hz = router.healthz()
        assert hz["alive"]["prefill"] == 0, hz
        assert hz["state"] in ("degraded", "critical"), hz


def test_chaos_kill_decode_mid_handoff_exactly_once(mv, tmp_path):
    """The ISSUE 19 chaos bar: socket decode replicas under seeded
    ``ChaosTransport``, one killed with handoffs in flight.  Every
    request completes exactly once with byte-identical tokens, and the
    requeue path fired (counter + flight events)."""
    tel = telemetry.enable()
    flight_recorder.start(tmp_path / "fdr")
    servers = [ReplicaServer(EngineReplica(
        _engine(mv, slots=1), name=f"s{i}")).start() for i in range(3)]
    try:
        remotes = [RemoteReplica("127.0.0.1", s.address[1],
                                 name=f"s{i}")
                   for i, s in enumerate(servers)]
        ports = {servers[1].address[1], servers[2].address[1]}
        work = [(p, 3) for p in _prompts([12, 9, 13, 8, 11, 10],
                                         seed=13)]
        with ChaosTransport(seed=11, reset_rate=0.1,
                            max_injections=3, skip_ops=4,
                            target_ports=ports):
            router = PrefillDecodeRouter(
                [remotes[0]], [remotes[1], remotes[2]],
                block_size=ALIGN, retries=8, backoff_base=0.005)
            with router:
                rids = [router.submit(p, max_new_tokens=n)
                        for p, n in work]
                time.sleep(0.05)  # let handoffs reach the victim
                servers[1].kill()
                results = [router.result(r, timeout=300)
                           for r in rids]
        assert len({r["request_id"] for r in results}) == len(work)
        for (p, n), r in zip(work, results):
            assert r.get("error") is None, r
            np.testing.assert_array_equal(r["tokens"],
                                          _want(mv, p, n))
        counters = tel.metrics.snapshot()["counters"]
        assert counters["serving_handoff_requeue_total"] >= 1, counters
        events = flight_recorder.active().read_events()
        assert any(e["kind"] == "handoff_requeue" for e in events)
    finally:
        for s in servers:
            s.stop()
        flight_recorder.stop()
        telemetry.disable()


# ---- page-headroom routing (the satellite bugfix) ---------------------


class _PagedStub:
    """Replica stub with a page pool: records what it served."""

    def __init__(self, name, free, load=0):
        self.name = name
        self._free = free
        self._load = load
        self.alive = True
        self.dispatched: list = []

    def start(self):
        return self

    def load(self):
        return self._load

    def free_pages(self):
        return self._free

    def dispatch(self, spec, on_result):
        self.dispatched.append(spec)
        on_result({"request_id": spec["request_id"],
                   "tokens": np.asarray([1], np.int32)})

    def health(self):
        return {"alive": True, "state": "ok", "load": self._load}


def test_gateway_skips_page_exhausted_replicas():
    """``free_pages() == 0`` makes a replica ineligible for fresh
    paged admissions even when it is the least loaded..."""
    empty = _PagedStub("empty", free=0, load=0)
    roomy = _PagedStub("roomy", free=64, load=5)
    with ServingGateway([empty, roomy], policy="least_loaded") as gw:
        for _ in range(4):
            gw.result(gw.submit([1, 2, 3]), timeout=5)
    assert len(roomy.dispatched) == 4 and not empty.dispatched


def test_gateway_handoff_still_lands_on_exhausted_replica():
    """...but a decode-only handoff is exempt (its pages were already
    accounted by the KV import), and when EVERY replica is exhausted
    fresh admissions fall through to the engine's own back-pressure
    instead of erroring."""
    empty = _PagedStub("empty", free=0, load=0)
    roomy = _PagedStub("roomy", free=64, load=5)
    with ServingGateway([empty, roomy], policy="least_loaded") as gw:
        gw.result(gw.submit([1, 2, 3], handoff=True), timeout=5)
    assert len(empty.dispatched) == 1
    # the routing flag rides to the replica (EngineReplica._exec
    # drops it before the engine's submit — stubs see it verbatim)
    assert empty.dispatched[0].get("handoff") is True

    both_empty = [_PagedStub("a", free=0), _PagedStub("b", free=0)]
    with ServingGateway(both_empty, policy="least_loaded") as gw:
        assert gw.result(gw.submit([1, 2]),
                         timeout=5).get("error") is None
    assert sum(len(s.dispatched) for s in both_empty) == 1


# ---- simulator: the prefill_heavy tenant ------------------------------


def test_trace_prefill_heavy_tenant_shape():
    from distkeras_tpu.simulator import TraceSpec, generate_trace

    spec = TraceSpec(duration_s=60.0, mean_qps=4.0, seed=5,
                     prompt_median=8.0, prompt_sigma=0.3,
                     prompt_min=3, prompt_max=400,
                     output_alpha=2.0, output_min=4, output_max=64,
                     heavy_prompt_median=128.0,
                     heavy_prompt_sigma=0.25, heavy_output_max=8,
                     tenants=(("steady", 1.0, 1),
                              ("flood", 1.0, 1, "prefill_heavy")))
    arrivals = generate_trace(spec).arrivals
    heavy = [a for a in arrivals if a.tenant == "flood"]
    plain = [a for a in arrivals if a.tenant == "steady"]
    assert len(heavy) > 10 and len(plain) > 10
    # long lognormal prompts, short clipped outputs
    assert (np.median([len(a.prompt) for a in heavy])
            > 4 * np.median([len(a.prompt) for a in plain]))
    assert all(a.max_new <= 8 for a in heavy)
    assert any(a.max_new > 8 for a in plain)


def test_trace_heavy_class_preserves_seed_purity():
    """A quad tenant with the DEFAULT class draws nothing extra: the
    trace is byte-identical to the plain-triple spec's."""
    import dataclasses

    from distkeras_tpu.simulator import TraceSpec, generate_trace

    base = TraceSpec(duration_s=30.0, mean_qps=5.0, seed=9,
                     tenants=(("t0", 2.0, 1), ("t1", 1.0, 2)))
    quad = dataclasses.replace(
        base, tenants=(("t0", 2.0, 1, "default"), ("t1", 1.0, 2)))
    a, b = generate_trace(base).arrivals, generate_trace(quad).arrivals
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert (x.t, x.max_new, x.session, x.tenant, x.priority) == \
            (y.t, y.max_new, y.session, y.tenant, y.priority)
        np.testing.assert_array_equal(x.prompt, y.prompt)

    with pytest.raises(ValueError, match="unknown tenant class"):
        dataclasses.replace(
            base, tenants=(("t0", 1.0, 1, "decode_heavy"),))


# ---- SLO signals ------------------------------------------------------


def test_tail_latency_slo_signals():
    """``ttft_p99`` and ``inter_token_p99`` surface through the
    watchdog once their histograms see traffic, with default
    thresholds registered."""
    for sig in ("ttft_p99", "inter_token_p99"):
        assert sig in telemetry.DEFAULT_SLO_THRESHOLDS
    reg = telemetry.MetricsRegistry()
    w = telemetry.SLOWatchdog(reg)
    assert "inter_token_p99" not in w.evaluate()["signals"]
    for _ in range(100):
        reg.histogram("serving_ttft_seconds").observe(0.008)
        reg.histogram("serving_inter_token_seconds").observe(0.5)
    v = w.evaluate()
    assert 0 < v["signals"]["ttft_p99"] < 2.0
    assert v["signals"]["inter_token_p99"] >= 0.5
    # 0.5s cadence >= the degraded_at threshold (0.25)
    assert "inter_token_p99" in v["breaches"]
    assert v["state"] in ("degraded", "critical")

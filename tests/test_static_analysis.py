"""Concurrency & protocol static-analysis suite (ISSUE 9): every lint
rule is proven on a seeded-violation fixture (a snippet that MUST
fire), the suppression machinery (in-source ``allow`` + committed
baseline) is exercised both ways, the runtime lockset detector
catches a deliberately seeded data race and a 2-lock deadlock cycle,
and the repo itself is pinned clean — ``scripts/lint_static.py`` must
exit 0 over the package forever."""

import importlib.util
import pathlib
import sys
import textwrap
import threading

import pytest

from distkeras_tpu.analysis import (
    RULE_DEAD,
    Finding,
    allowed_rules,
    dead_suppressions,
    filter_suppressed,
    load_baseline,
    lockcheck,
    racecheck,
    surfaces,
)
from distkeras_tpu.parallel.transport import (
    WIRE_OPS,
    WireOpCollision,
    WireOps,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def _lint(src: str) -> list[Finding]:
    return lockcheck.analyze_source(textwrap.dedent(src))


def _rules(findings) -> set[str]:
    return {f.rule for f in findings}


# -- lock-discipline lint: seeded violations ---------------------------


def test_blocking_call_under_lock_fires():
    fs = _lint("""\
        import threading, time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    time.sleep(0.1)
        """)
    assert _rules(fs) == {lockcheck.RULE_BLOCKING}
    assert "W._lock" in fs[0].message and "time.sleep" in fs[0].message


def test_socket_send_under_lock_fires():
    fs = _lint("""\
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def push(self, sock, data):
                with self._lock:
                    sock.sendall(data)
        """)
    assert _rules(fs) == {lockcheck.RULE_BLOCKING}


def test_blocking_call_outside_lock_is_clean():
    assert _lint("""\
        import threading, time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    x = 1
                time.sleep(0.1)
        """) == []


def test_try_finally_release_tracks_held_region():
    """An explicit acquire/try/finally-release balances: the sleep
    inside the try is under lock (fires), after the finally is not."""
    fs = _lint("""\
        import threading, time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                self._lock.acquire()
                try:
                    time.sleep(0.1)
                finally:
                    self._lock.release()
                time.sleep(0.2)
        """)
    assert len(fs) == 1 and fs[0].rule == lockcheck.RULE_BLOCKING
    assert fs[0].line == 10  # the sleep inside the held region


def test_wait_for_on_foreign_lock_fires():
    """``Condition.wait_for`` blocks exactly like ``wait``: calling it
    on anything other than the HELD lock sleeps inside someone else's
    critical section."""
    fs = _lint("""\
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition()

            def poke(self):
                with self._lock:
                    self._cv.wait_for(lambda: True)
        """)
    assert _rules(fs) == {lockcheck.RULE_BLOCKING}
    assert "wait_for" in fs[0].message


def test_wait_for_on_held_condition_is_clean():
    """``wait_for`` on the held condition RELEASES it while sleeping —
    the one blocking call that is correct under its own lock."""
    fs = _lint("""\
        import threading

        class W:
            def __init__(self):
                self._cv = threading.Condition()

            def poke(self):
                with self._cv:
                    self._cv.wait_for(lambda: True)
        """)
    assert fs == []


def test_future_result_under_lock_fires():
    """``.result()`` parks the thread until another thread completes
    the future — a classic lock-held stall (and deadlock, if the
    completing thread needs the lock)."""
    fs = _lint("""\
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self, fut):
                with self._lock:
                    return fut.result()
        """)
    assert _rules(fs) == {lockcheck.RULE_BLOCKING}
    assert ".result()" in fs[0].message


def test_future_result_outside_lock_is_clean():
    fs = _lint("""\
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self, fut):
                with self._lock:
                    pass
                return fut.result()
        """)
    assert fs == []


def test_lock_order_inversion_fires():
    fs = _lint("""\
        import threading

        class W:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
        """)
    assert lockcheck.RULE_ORDER in _rules(fs)
    assert any("inversion" in f.message for f in fs)


def test_consistent_lock_order_is_clean():
    assert _lint("""\
        import threading

        class W:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
        """) == []


def test_guarded_write_annotation_fires():
    """The seeded guarded-write mutation: a field declared
    ``# guarded-by: _lock`` written without the lock."""
    fs = _lint("""\
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0  # guarded-by: _lock

            def good(self):
                with self._lock:
                    self._x += 1

            def bad(self):
                self._x = 5
        """)
    assert len(fs) == 1 and fs[0].rule == lockcheck.RULE_GUARDED
    assert fs[0].line == 13 and "W._x" in fs[0].message


def test_guarded_write_majority_inference_fires():
    """No annotation: two guarded writes + one naked write -> the
    naked one is flagged against the inferred majority guard."""
    fs = _lint("""\
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def a(self):
                with self._lock:
                    self._n += 1

            def b(self):
                with self._lock:
                    self._n = 0

            def c(self):
                self._n = 9
        """)
    assert len(fs) == 1 and fs[0].rule == lockcheck.RULE_GUARDED
    assert "majority" in fs[0].message


def test_locked_suffix_helper_is_exempt():
    """Writes inside ``*_locked`` helpers run under the caller's lock
    by convention — never flagged, and they count as guarded."""
    assert _lint("""\
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0  # guarded-by: _lock

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def _bump_locked(self):
                self._x += 1
        """) == []


# -- suppression machinery ---------------------------------------------


def test_allow_comment_on_line_suppresses():
    src = textwrap.dedent("""\
        import threading, time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    time.sleep(0.1)  # lint: allow(blocking-call-under-lock)
        """)
    fs = lockcheck.analyze_source(src)
    assert len(fs) == 1  # the lint itself still sees it
    kept, dropped = filter_suppressed(
        fs, {"<fixture>": src.splitlines()})
    assert kept == [] and dropped == 1


def test_allow_comment_block_above_suppresses():
    src = textwrap.dedent("""\
        import threading, time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    # lint: allow(blocking-call-under-lock): the pause
                    # is deliberate — justification wraps over two
                    # comment lines and still counts
                    time.sleep(0.1)
        """)
    kept, dropped = filter_suppressed(
        lockcheck.analyze_source(src), {"<fixture>": src.splitlines()})
    assert kept == [] and dropped == 1


def test_allow_for_a_different_rule_does_not_suppress():
    src = textwrap.dedent("""\
        import threading, time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    time.sleep(0.1)  # lint: allow(lock-order)
        """)
    kept, dropped = filter_suppressed(
        lockcheck.analyze_source(src), {"<fixture>": src.splitlines()})
    assert len(kept) == 1 and dropped == 0


def test_allowed_rules_parses_comma_list():
    lines = ["x = 1  # lint: allow(lock-order, guarded-write)"]
    assert allowed_rules(lines, 1) == {"lock-order", "guarded-write"}


def test_baseline_roundtrip(tmp_path):
    f = Finding("lock-order", "pkg/mod.py", 42, "a -> b inverted")
    base = tmp_path / "baseline.txt"
    base.write_text("# comment lines and blanks are ignored\n\n"
                    + f.baseline_key() + "\n")
    keys = load_baseline(base)
    assert f.baseline_key() in keys
    # the key is line-number-free: the same finding at another line
    # stays baselined
    f2 = Finding("lock-order", "pkg/mod.py", 99, "a -> b inverted")
    assert f2.baseline_key() in keys
    assert load_baseline(tmp_path / "missing.txt") == set()


def test_finding_str_is_clickable():
    f = Finding("lock-order", "pkg/mod.py", 42, "boom")
    assert str(f) == "pkg/mod.py:42: [lock-order] boom"


# -- dead-suppression lint ---------------------------------------------


def test_dead_baseline_entry_is_flagged():
    f = Finding("lock-order", "pkg/mod.py", 3, "live")
    dead = dead_suppressions(
        [f], {}, {f.baseline_key(), "lock-order|pkg/gone.py|fixed"})
    assert [d.rule for d in dead] == [RULE_DEAD]
    assert "pkg/gone.py" in dead[0].message
    assert dead[0].path == "pkg/gone.py"  # clickable at the dead entry


def test_dead_allow_comment_is_flagged_live_is_not():
    src = ("x = 1  # lint: allow(lock-order)\n"       # dead: no finding
           "y = 2  # lint: allow(guarded-write)\n")   # live
    dead = dead_suppressions(
        [Finding("guarded-write", "pkg/mod.py", 2, "m")],
        {"pkg/mod.py": src.splitlines()}, set())
    assert len(dead) == 1
    assert dead[0].line == 1 and "allow(lock-order)" in dead[0].message


def test_dead_allow_block_comment_covers_code_line_below():
    """A comment-block allow covers the first code line below it —
    live when that line has the finding, dead otherwise."""
    src = ("# justification wraps over\n"
           "# lint: allow(lock-order)\n"
           "x = blocking()\n")
    lines = src.splitlines()
    live = dead_suppressions(
        [Finding("lock-order", "p.py", 3, "m")], {"p.py": lines}, set())
    assert live == []
    dead = dead_suppressions([], {"p.py": lines}, set())
    assert len(dead) == 1 and dead[0].line == 2


def test_docstring_allow_placeholder_is_not_a_suppression():
    """Prose discussing the ``allow(<rule>)`` syntax must not be
    treated as a suppression (rule names are [a-z0-9-] words)."""
    src = ('"""Suppress with # lint: allow(<rule>) or allow(...)."""\n'
           "x = 1\n")
    assert dead_suppressions([], {"d.py": src.splitlines()}, set()) == []


# -- surface-drift lints: seeded violations ----------------------------

_DOCS_EMPTY = "(no docs)"


def test_undocumented_metric_and_span_fire():
    s = surfaces.extract_source(textwrap.dedent("""\
        from distkeras_tpu import telemetry

        def f(reg):
            reg.counter("bogus_metric_zzz").inc()
            telemetry.instant("bogus_span_zzz")
        """), "fix.py")
    fs = surfaces.check_docs(s, _DOCS_EMPTY)
    assert _rules(fs) == {surfaces.RULE_METRIC, surfaces.RULE_SPAN}
    docs = "... `bogus_metric_zzz` and `bogus_span_zzz` exist ..."
    assert surfaces.check_docs(s, docs) == []


def test_metric_name_needs_a_whole_word_match():
    s = surfaces.extract_source(
        'def f(reg):\n    reg.counter("rows_total").inc()\n', "fix.py")
    # a superstring in docs must NOT satisfy the lint
    fs = surfaces.check_docs(s, "see `streaming_rows_total`")
    assert _rules(fs) == {surfaces.RULE_METRIC}
    assert surfaces.check_docs(s, "see `rows_total`") == []


def test_undocumented_flight_kind_needs_a_table_row():
    s = surfaces.extract_source(
        'from distkeras_tpu import flight_recorder\n'
        'def f():\n    flight_recorder.record("bogus_kind", x=1)\n',
        "fix.py")
    # a loose mention is NOT enough — kinds need a docs table row
    fs = surfaces.check_docs(s, "the bogus_kind event")
    assert _rules(fs) == {surfaces.RULE_FLIGHT}
    assert surfaces.check_docs(
        s, "| `bogus_kind` | something |") == []


def test_undocumented_slo_signal_fires():
    s = surfaces.extract_source(textwrap.dedent("""\
        DEFAULT_SLO_THRESHOLDS = {"bogus_signal": 0.5}
        """), "fix.py")
    fs = surfaces.check_docs(s, _DOCS_EMPTY)
    assert _rules(fs) == {surfaces.RULE_SLO}


def test_undocumented_history_key_fires():
    s = surfaces.extract_source(textwrap.dedent("""\
        class T:
            def step(self):
                self._record(bogus_key=1.0, epoch_loss=0.5)
        """), "fix.py")
    docs = ("### Trainer history keys\n\n"
            "| `epoch_loss` | mean loss |\n")
    fs = surfaces.check_docs(s, docs)
    assert _rules(fs) == {surfaces.RULE_HISTORY}
    assert [f for f in fs if "bogus_key" in f.message]


def test_undocumented_tier_needs_a_table_row():
    s = surfaces.extract_source(
        'TIERS = {"bogus_tier": None}\n', "fix.py")
    # a loose mention is NOT enough — tiers need a Lowering-tiers row
    fs = surfaces.check_docs(s, "the bogus_tier lowering")
    assert _rules(fs) == {surfaces.RULE_TIER}
    docs = ("### Lowering tiers\n\n"
            "| `bogus_tier` | emulated | yes | all |\n")
    assert surfaces.check_docs(s, docs) == []


def test_unregistered_opcode_fires():
    s = surfaces.extract_source(
        'def f(sock):\n    sock.sendall(b"Z")\n',
        "fix.py", wire_scope="ps")
    fs = surfaces.check_opcodes(s, WIRE_OPS)
    assert _rules(fs) == {surfaces.RULE_OPCODE}
    # a registered byte in the same scope is clean
    s2 = surfaces.extract_source(
        'def f(sock):\n    sock.sendall(b"p")\n',
        "fix.py", wire_scope="ps")
    assert surfaces.check_opcodes(s2, WIRE_OPS) == []


def test_registration_literals_are_exempt_from_opcode_scan():
    """The registry's own ``WIRE_OPS.register(...)`` byte arguments are
    definitions, not uses — they never count as unregistered."""
    s = surfaces.extract_source(
        'WIRE_OPS.register("ps", b"Z", "zap")\n',
        "fix.py", wire_scope="ps")
    assert s.wire_ops.get("ps", {}) == {}


def test_multibyte_literals_are_not_opcodes():
    s = surfaces.extract_source(
        'MAGIC = b"zz"\nEMPTY = b""\n', "fix.py", wire_scope="ps")
    assert s.wire_ops.get("ps", {}) == {}


# -- the wire-op registry itself ---------------------------------------


def test_wire_ops_same_scope_collision_raises():
    reg = WireOps()
    reg.register("ps", b"p", "pull")
    with pytest.raises(WireOpCollision):
        reg.register("ps", b"p", "push")
    # idempotent re-registration of the same meaning is fine
    reg.register("ps", b"p", "pull")


def test_wire_ops_frame_scope_collides_globally():
    reg = WireOps()
    reg.register("frame", b"t", "trace_header")
    with pytest.raises(WireOpCollision):
        reg.register("ps", b"t", "tickle")
    # ...but two NON-frame scopes may share a byte (different servers)
    reg.register("ps", b"s", "stop")
    reg.register("replica", b"s", "stop")


def test_wire_ops_rejects_multibyte():
    with pytest.raises(ValueError):
        WireOps().register("ps", b"pp", "pull")


def test_repo_registry_covers_every_protocol():
    assert set(WIRE_OPS.scopes()) == {"frame", "ps", "replica",
                                      "repl", "elastic", "kv", "hier"}
    assert WIRE_OPS.ops("ps")[b"p"] == "pull"
    assert WIRE_OPS.ops("replica")[b"g"] == "generate"
    assert WIRE_OPS.ops("repl")[b"a"] == "append"
    assert WIRE_OPS.ops("elastic")[b"F"] == "migrate_finalize"
    assert WIRE_OPS.ops("kv")[b"K"] == "page_blocks"
    assert WIRE_OPS.ops("hier")[b"u"] == "upstream_commit"


# -- runtime lockset race + deadlock detector --------------------------


@pytest.fixture
def rc():
    racecheck.enable()
    yield racecheck
    racecheck.disable()


def test_disabled_factories_return_plain_primitives():
    assert not racecheck.enabled()
    assert type(racecheck.lock("x")) is type(threading.Lock())
    assert type(racecheck.rlock("x")) is type(threading.RLock())
    assert isinstance(racecheck.condition("x"), threading.Condition)


def test_seeded_data_race_is_caught_with_both_stacks(rc):
    """The Eraser lockset refinement: one thread writes a Guarded
    object under a lock, another writes it naked -> candidate lockset
    empties -> race report carrying BOTH access stacks."""
    lk = rc.lock("race.demo")
    shared = rc.Guarded(type("S", (), {"n": 0})(), name="shared")
    # the two writers' lifetimes OVERLAP (events, not sequential
    # joins): a joined thread's ident can be reused by the next one,
    # which would make the two accesses look same-thread
    wrote = threading.Event()
    done = threading.Event()

    def locked_writer():
        with lk:
            shared.n = 1
        wrote.set()
        done.wait(5)

    def naked_writer():
        wrote.wait(5)
        shared.n = 2
        done.set()

    t1 = threading.Thread(target=locked_writer)
    t2 = threading.Thread(target=naked_writer)
    t1.start(); t2.start()
    t1.join(5); t2.join(5)
    reports = rc.disable()
    races = [r for r in reports if r.kind == "race"]
    assert races, [str(r) for r in reports]
    assert "shared" in races[0].detail
    assert len(races[0].stacks) == 2 and all(races[0].stacks)


def test_consistent_locking_is_clean(rc):
    lk = rc.lock("clean.demo")
    shared = rc.Guarded(type("S", (), {"n": 0})(), lock=lk,
                        name="shared")

    def writer():
        for _ in range(20):
            with lk:
                shared.n += 1

    ts = [threading.Thread(target=writer) for _ in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert rc.disable() == []


def test_guarded_reports_access_without_declared_lock(rc):
    lk = rc.lock("g.demo")
    shared = rc.Guarded({}, lock=lk, name="table")
    shared["k"] = 1  # not holding lk
    reports = rc.disable()
    assert any(r.kind == "unguarded" and "table" in r.detail
               for r in reports)


def test_guarded_intercepts_delitem_and_pop(rc):
    """Regression: special-method lookup goes to the TYPE, so ``del
    d[k]`` never reached ``__getattr__`` — deletions escaped the
    lockset machinery entirely (and ``pop`` recorded a READ of the
    method name, not a write of the popped key).  Both must now report
    unguarded writes of the KEY when the declared lock is not held."""
    lk = rc.lock("del.demo")
    with lk:  # populate under the lock: setup itself stays clean
        backing = {"a": 1, "b": 2, "c": 3}
        shared = rc.Guarded(backing, lock=lk, name="table")
    del shared["a"]       # naked deletion
    assert shared.pop("b") == 2   # naked pop
    assert "a" not in backing and "b" not in backing  # still functional
    reports = rc.disable()
    naked = [r for r in reports if r.kind == "unguarded"]
    assert any("['a']" in r.detail for r in naked), \
        [str(r) for r in reports]
    assert any("['b']" in r.detail for r in naked), \
        [str(r) for r in reports]
    # the same operations under the declared lock are clean
    rc.enable()
    with lk:
        del shared["c"]
        shared.pop("missing", None)
    assert rc.disable() == []


def test_seeded_two_lock_deadlock_raises_not_hangs(rc):
    """The acceptance scenario: AB/BA across two threads.  The
    wait-for-graph check fires DeadlockError inside at least one
    thread — deterministically, instead of hanging the suite."""
    a, b = rc.lock("dl.a"), rc.lock("dl.b")
    barrier = threading.Barrier(2, timeout=5)
    errors = []

    def grab(first, second):
        try:
            with first:
                barrier.wait()
                with second:
                    pass
        except racecheck.DeadlockError as e:
            errors.append(e)

    t1 = threading.Thread(target=grab, args=(a, b))
    t2 = threading.Thread(target=grab, args=(b, a))
    t1.start(); t2.start()
    t1.join(timeout=10); t2.join(timeout=10)
    assert not t1.is_alive() and not t2.is_alive()
    assert errors, "neither thread saw the deadlock"
    kinds = {r.kind for r in rc.disable()}
    assert "deadlock" in kinds


def test_lock_order_cycle_detected_single_threaded(rc):
    """AB then BA nesting on ONE thread never deadlocks by itself but
    is the order violation that deadlocks two -> reported eagerly."""
    a, b = rc.lock("oc.a"), rc.lock("oc.b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    reports = rc.disable()
    cycles = [r for r in reports if r.kind == "lock-order-cycle"]
    assert cycles and len(cycles[0].stacks) == 2


def test_self_deadlock_on_nonreentrant_lock_raises(rc):
    lk = rc.lock("self.dl")
    lk.acquire()
    try:
        with pytest.raises(racecheck.DeadlockError):
            lk.acquire()
    finally:
        lk.release()
    rc.disable()


def test_rlock_reentrancy_and_condition_protocol_run_clean(rc):
    """An instrumented RLock recurses without a false self-deadlock,
    and a Condition over it round-trips wait/notify (the detector's
    ``_release_save``/``_acquire_restore`` keep the held set honest)."""
    r = rc.rlock("re.demo")
    with r:
        with r:
            pass
    cv = rc.condition("cv.demo")
    box = []

    def consumer():
        with cv:
            while not box:
                cv.wait(timeout=5)

    t = threading.Thread(target=consumer)
    t.start()
    with cv:
        box.append(1)
        cv.notify()
    t.join(timeout=10)
    assert not t.is_alive()
    assert rc.disable() == []


def test_locks_made_while_enabled_degrade_after_disable(rc):
    lk = rc.lock("late")
    rc.disable()
    # the instrumented lock still works as a plain mutex afterwards
    with lk:
        pass
    assert racecheck.held_locks() == ()


# -- the repo itself is pinned clean -----------------------------------


def _load_lint_static():
    spec = importlib.util.spec_from_file_location(
        "lint_static", REPO / "scripts" / "lint_static.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_is_lint_clean():
    """``python scripts/lint_static.py`` must exit 0: no unsuppressed
    finding anywhere in the package.  New violations either get fixed
    or arrive with an explicit allow()/baseline justification."""
    mod = _load_lint_static()
    final, counts, stats = mod.run_lint()
    assert final == [], "\n".join(str(f) for f in final)
    assert counts == {}
    assert stats["files"] > 40  # the whole package was actually walked


def test_self_check_fixtures_all_fire():
    mod = _load_lint_static()
    assert mod.self_check() == []


def test_lint_metrics_flow_through_registry():
    mod = _load_lint_static()
    reg = mod.emit_metrics({"lock-order": 2, "guarded-write": 1})
    counters = reg.snapshot()["counters"]
    assert counters["lint_findings_total"] == 3
    assert counters['lint_findings_total{rule="lock-order"}'] == 2

"""Speculative decoding (``speculative`` + the engine's verify path,
ISSUE 15): proposers only ever SUGGEST tokens — the greedy acceptance
rule makes every output byte-identical to the non-speculative baseline
on BOTH the envelope and paged engines, across admission orders,
eos/max_new stops inside an accepted window, rollbacks, preemption,
deadline expiry, and weight swaps — while the compile guard pins a
bounded program set and the acceptance telemetry feeds the
``spec_accept_rate`` SLO signal."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import speculative, telemetry
from distkeras_tpu.gateway import EngineReplica, ServingGateway
from distkeras_tpu.models import ModelSpec, generate, model_config
from distkeras_tpu.serving import DecodeEngine

jax.config.update("jax_platforms", "cpu")

MAXLEN, VOCAB = 32, 37


def _model(seed=0, num_layers=1, vocab_size=VOCAB, **kw):
    spec = model_config("transformer_lm", (MAXLEN,),
                        input_dtype="int32", vocab_size=vocab_size,
                        num_layers=num_layers, d_model=32, num_heads=2,
                        max_len=MAXLEN, dtype="float32", **kw)
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(seed),
                           jnp.zeros((2, MAXLEN), jnp.int32))
    return model, variables


def _prompts(lengths, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, (t,)).astype(np.int32)
            for t in lengths]


def _want(model, variables, prompt, n_new, **kw):
    return np.asarray(generate(model, variables, prompt[None, :],
                               max_new_tokens=n_new, **kw)
                      )[0, len(prompt):]


def _self_draft(model, variables, k=3):
    # draft == target: every proposal is the target's own greedy
    # token, so acceptance is total and every commit is k+1 wide —
    # the hardest exercise of the multi-token commit path
    return {"proposer": "draft", "k": k, "draft_model": model,
            "draft_variables": variables}


# ---------------------------------------------------------------------
# unit: proposers and the acceptance rule


def test_ngram_propose_matches_most_recent_occurrence():
    led = np.array([5, 1, 2, 9, 4, 5, 1, 2], np.int32)
    # tail [1, 2] matched at s=1 -> proposes what followed: [9, 4, 5]
    np.testing.assert_array_equal(
        speculative.ngram_propose(led, 3, 2), [9, 4, 5])
    # recency wins: a later duplicate of the tail shadows s=1
    led2 = np.array([1, 2, 7, 3, 1, 2, 8, 1, 2], np.int32)
    np.testing.assert_array_equal(
        speculative.ngram_propose(led2, 2, 2), [8, 1])
    # no earlier occurrence / ledger shorter than the pattern: empty
    assert len(speculative.ngram_propose(
        np.array([1, 2, 3, 4], np.int32), 3, 2)) == 0
    assert len(speculative.ngram_propose(
        np.array([1, 2], np.int32), 3, 2)) == 0


def test_accept_length_is_longest_matching_prefix():
    g = np.array([4, 5, 6, 7], np.int32)
    assert speculative.accept_length(np.array([4, 5, 6]), g) == 3
    assert speculative.accept_length(np.array([4, 5, 9]), g) == 2
    assert speculative.accept_length(np.array([9, 5, 6]), g) == 0
    assert speculative.accept_length(np.empty((0,), np.int32), g) == 0


def test_config_validation():
    model, variables = _model()
    with pytest.raises(ValueError, match="unknown keys"):
        speculative.normalize({"proposer": "ngram", "nope": 1},
                              vocab_size=VOCAB, max_len=MAXLEN)
    with pytest.raises(ValueError, match="proposer"):
        speculative.normalize({"proposer": "medusa"},
                              vocab_size=VOCAB, max_len=MAXLEN)
    with pytest.raises(ValueError, match="k must be"):
        speculative.normalize({"k": 0}, vocab_size=VOCAB,
                              max_len=MAXLEN)
    with pytest.raises(ValueError, match="draft_model"):
        speculative.normalize({"proposer": "draft"},
                              vocab_size=VOCAB, max_len=MAXLEN)
    with pytest.raises(ValueError, match="vocab_size"):
        speculative.normalize(
            {"proposer": "draft", "draft_model": _model(
                vocab_size=VOCAB + 1)[0], "draft_variables": variables},
            vocab_size=VOCAB, max_len=MAXLEN)
    # engine knob coupling: greedy-only, one-token sync quantum
    with pytest.raises(ValueError, match="temperature"):
        DecodeEngine(model, variables, slots=2, buckets=[MAXLEN],
                     temperature=0.7,
                     speculative={"proposer": "ngram"})
    with pytest.raises(ValueError, match="steps_per_sync"):
        DecodeEngine(model, variables, slots=2, buckets=[MAXLEN],
                     steps_per_sync=2,
                     speculative={"proposer": "ngram"})
    # per-request opt-IN needs an engine-level config to opt into
    eng = DecodeEngine(model, variables, slots=2, buckets=[MAXLEN])
    with pytest.raises(ValueError, match="speculative"):
        eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=2,
                   speculative=True)
    eng.close()


# ---------------------------------------------------------------------
# parity: byte-identical to the baseline on both engine arms


def test_envelope_ngram_parity_any_admission_order():
    model, variables = _model()
    rng = np.random.default_rng(7)
    prompts = []
    for i in range(6):
        base = rng.integers(0, VOCAB, (4,)).astype(np.int32)
        prompts.append(np.tile(base, 3)[:10].astype(np.int32))
    reqs = [{"prompt": p, "max_new_tokens": 12, "i": i}
            for i, p in enumerate(prompts)]
    eng = DecodeEngine(model, variables, slots=3, buckets=[MAXLEN],
                       prefill_align=4,
                       speculative={"proposer": "ngram", "k": 3})
    fwd = {r["i"]: r["tokens"] for r in eng.run(reqs)}
    rev = {r["i"]: r["tokens"] for r in eng.run(list(reversed(reqs)),
                                                ordered=False)}
    for i, p in enumerate(prompts):
        want = _want(model, variables, p, 12)
        np.testing.assert_array_equal(fwd[i], want)
        np.testing.assert_array_equal(rev[i], want)
    eng.close()


def test_envelope_draft_parity_full_and_partial_acceptance():
    model, variables = _model()
    dmodel, dvars = _model(seed=1)  # disagreeing draft: rollbacks
    prompts = _prompts([5, 9, 3, 7, 6, 11])
    reqs = [{"prompt": p, "max_new_tokens": 8, "i": i}
            for i, p in enumerate(prompts)]
    for draft, full in [(_self_draft(model, variables), True),
                        ({"proposer": "draft", "k": 3,
                          "draft_model": dmodel,
                          "draft_variables": dvars}, False)]:
        eng = DecodeEngine(model, variables, slots=3, buckets=[MAXLEN],
                           prefill_align=4, speculative=draft)
        got = {r["i"]: r["tokens"] for r in eng.run(reqs)}
        for i, p in enumerate(prompts):
            np.testing.assert_array_equal(
                got[i], _want(model, variables, p, 8))
        st = eng.spec_stats()
        assert st["proposed"] > 0
        if full:
            assert st["accept_rate"] == 1.0
        eng.close()


def test_paged_parity_and_page_accounting():
    model, variables = _model()
    prompts = _prompts([5, 9, 3, 7, 6, 11])
    reqs = [{"prompt": p, "max_new_tokens": 8, "i": i}
            for i, p in enumerate(prompts)]
    for spec in ({"proposer": "ngram", "k": 3},
                 _self_draft(model, variables)):
        eng = DecodeEngine(model, variables, slots=3, buckets=[MAXLEN],
                           prefill_align=4, kv_pages=24,
                           speculative=spec)
        got = {r["i"]: r["tokens"] for r in eng.run(reqs)}
        for i, p in enumerate(prompts):
            np.testing.assert_array_equal(
                got[i], _want(model, variables, p, 8))
        # every page earned by speculative growth came back
        assert eng.free_pages() == 24
        eng.close()


def test_eos_inside_accepted_window_stops_mid_window():
    model, variables = _model()
    p = _prompts([9], seed=7)[0]
    free = _want(model, variables, p, 8)
    eos = int(free[3])  # fires mid-window under a k=3 proposal
    stop = int(np.argwhere(free == eos)[0][0])
    for kw in ({}, {"kv_pages": 24}):
        eng = DecodeEngine(model, variables, slots=2, buckets=[MAXLEN],
                           prefill_align=4,
                           speculative=_self_draft(model, variables),
                           **kw)
        r = list(eng.run([{"prompt": p, "max_new_tokens": 8,
                           "eos_id": eos}]))[0]
        # the accepted tail PAST the eos is discarded, tokens end AT it
        np.testing.assert_array_equal(r["tokens"], free[:stop + 1])
        eng.close()


def test_max_new_clamp_stops_mid_window():
    model, variables = _model()
    p = _prompts([9], seed=7)[0]
    free = _want(model, variables, p, 8)
    for kw in ({}, {"kv_pages": 24}):
        eng = DecodeEngine(model, variables, slots=2, buckets=[MAXLEN],
                           prefill_align=4,
                           speculative=_self_draft(model, variables),
                           **kw)
        # 3 new tokens with k+1 = 4-wide commits: the clamp lands
        # inside the first accepted window
        r = list(eng.run([{"prompt": p, "max_new_tokens": 3}]))[0]
        np.testing.assert_array_equal(r["tokens"], free[:3])
        assert len(r["tokens"]) == 3
        eng.close()


# ---------------------------------------------------------------------
# composition: scheduling, deadlines, swaps, preemption


def test_per_request_opt_out_is_baseline():
    model, variables = _model()
    prompts = _prompts([5, 9, 3])
    eng = DecodeEngine(model, variables, slots=3, buckets=[MAXLEN],
                       prefill_align=4,
                       speculative=_self_draft(model, variables))
    got = {r["i"]: r["tokens"]
           for r in eng.run([{"prompt": p, "max_new_tokens": 6,
                              "speculative": False, "i": i}
                             for i, p in enumerate(prompts)])}
    for i, p in enumerate(prompts):
        np.testing.assert_array_equal(
            got[i], _want(model, variables, p, 6))
    assert eng.spec_stats()["proposed"] == 0  # everyone opted out
    eng.close()


def test_deadline_expiry_mid_flight_frees_the_slot():
    model, variables = _model()
    prompts = _prompts([5, 9])
    eng = DecodeEngine(model, variables, slots=2, buckets=[MAXLEN],
                       prefill_align=4,
                       speculative=_self_draft(model, variables))
    eng.submit(prompts[0], max_new_tokens=24, deadline=0.02,
               meta={"i": 0})
    eng.submit(prompts[1], max_new_tokens=6, meta={"i": 1})
    out = list(eng.step())
    time.sleep(0.05)  # expires while speculation is mid-stream
    while eng.has_work():
        out.extend(eng.step())
    res = {r["i"]: r for r in out}
    assert res[0]["error"] == "deadline_exceeded"
    assert "error" not in res[1]
    np.testing.assert_array_equal(
        res[1]["tokens"], _want(model, variables, prompts[1], 6))
    eng.close()


def test_weight_swap_invalidates_in_flight_drafts():
    """Swap weights while a draft is mid-stream: the spec arm must
    match a baseline arm that swaps at the SAME committed-token
    boundary — the stale draft is invalidated, never verified against
    the new weights' cache."""
    model, variables = _model()
    _, variables2 = _model(seed=2)
    p = _prompts([7], seed=5)[0]

    eng = DecodeEngine(model, variables, slots=1, buckets=[MAXLEN],
                       prefill_align=4,
                       speculative=_self_draft(model, variables))
    eng.submit(p, max_new_tokens=12, meta={"i": 0})
    out = list(eng.step())  # prefill: first token
    out.extend(eng.step())  # one speculative quantum (k+1 commits)
    c = len(eng._pools[0].reqs[0].tokens)
    assert c > 1  # the draft really was mid-stream
    eng.swap_variables(variables2)
    while eng.has_work():
        out.extend(eng.step())
    got = out[0]["tokens"]

    base = DecodeEngine(model, variables, slots=1, buckets=[MAXLEN],
                        prefill_align=4)
    base.submit(p, max_new_tokens=12, meta={"i": 0})
    bout = []
    while True:  # one committed token per step: lands exactly on c
        bout.extend(base.step())
        req = base._pools[0].reqs[0]
        if req is not None and len(req.tokens) >= c:
            break
    base.swap_variables(variables2)
    while base.has_work():
        bout.extend(base.step())
    np.testing.assert_array_equal(got, bout[0]["tokens"])
    eng.close()
    base.close()


def test_paged_preemption_with_speculation_is_byte_identical():
    """The seeded preemption drill under speculation: the victim's
    draft state is recompute-class, so preempt -> readmit -> re-draft
    still lands the envelope-identical tokens."""
    model, variables = _model()
    pl = _prompts([9, 9, 5])
    tel = telemetry.enable()
    try:
        eng = DecodeEngine(model, variables, slots=3, buckets=[32],
                           prefill_align=4, kv_pages=8,
                           speculative=_self_draft(model, variables))
        eng.submit(pl[0], max_new_tokens=12, priority=0,
                   meta={"i": 0})
        eng.submit(pl[1], max_new_tokens=12, priority=0,
                   meta={"i": 1})
        out = list(eng.step())
        eng.submit(pl[2], max_new_tokens=10, priority=2,
                   meta={"i": 2})
        while eng.has_work():
            out.extend(eng.step())
        res = {r["i"]: r for r in out}
        for i, n in [(0, 12), (1, 12), (2, 10)]:
            assert "error" not in res[i]
            np.testing.assert_array_equal(
                res[i]["tokens"], _want(model, variables, pl[i], n))
        snap = tel.metrics.snapshot()["counters"]
        assert sum(v for k, v in snap.items()
                   if k.startswith("serving_preemptions_total")) >= 1
        assert (snap["serving_pages_allocated_total"]
                == snap["serving_pages_freed_total"])
        assert eng.free_pages() == 8
    finally:
        telemetry.disable()
        eng.close()


# ---------------------------------------------------------------------
# guard rails: compile pin + telemetry surfaces


def test_compile_guard_pins_speculative_program_set():
    model, variables = _model()
    prompts = _prompts([5, 9, 3, 7, 6, 11])
    reqs = [{"prompt": p, "max_new_tokens": 8, "i": i}
            for i, p in enumerate(prompts)]
    eng = DecodeEngine(model, variables, slots=3, buckets=[MAXLEN],
                       prefill_align=4,
                       speculative=_self_draft(model, variables))
    list(eng.run(reqs))
    counts = dict(eng.compile_counts)
    # the spec program set is exactly {verify x 2 widths, draft}
    assert ("verify", MAXLEN, 1) in counts
    assert ("verify", MAXLEN, 4) in counts
    assert ("draft_step", MAXLEN) in counts
    list(eng.run(list(reversed(reqs)), ordered=False))
    assert dict(eng.compile_counts) == counts  # steady state: no new
    eng.close()


def test_spec_telemetry_counters_and_slo_signal():
    model, variables = _model()
    prompts = _prompts([5, 9, 3])
    tel = telemetry.enable()
    try:
        eng = DecodeEngine(model, variables, slots=3, buckets=[MAXLEN],
                           prefill_align=4,
                           speculative=_self_draft(model, variables))
        list(eng.run([{"prompt": p, "max_new_tokens": 8}
                      for p in prompts]))
        eng.close()
        reg = tel.metrics
        prop = reg.sum_counter("serving_spec_proposed_total")
        acc = reg.sum_counter("serving_spec_accepted_total")
        assert prop > 0 and acc == prop  # draft == target
        snap = reg.snapshot()
        assert any(k.startswith("serving_spec_accept_len")
                   for k in snap["histograms"])
        w = telemetry.SLOWatchdog(reg)
        v = w.evaluate()
        assert v["signals"]["spec_accept_rate"] == pytest.approx(1.0)
        assert "spec_accept_rate" not in v["breaches"]
    finally:
        telemetry.disable()


def test_spec_accept_rate_slo_breaches_low():
    reg = telemetry.MetricsRegistry()
    reg.counter("serving_spec_proposed_total", bucket=32).inc(100)
    reg.counter("serving_spec_accepted_total", bucket=32).inc(3)
    v = telemetry.SLOWatchdog(reg).evaluate()
    assert v["signals"]["spec_accept_rate"] == pytest.approx(0.03)
    # 0.03 <= critical_at 0.05 on an INVERTED signal
    assert v["breaches"]["spec_accept_rate"]["level"] == "critical"


def test_gateway_forwards_speculative_only_when_set():
    model, variables = _model()
    eng = DecodeEngine(model, variables, slots=2, buckets=[MAXLEN],
                       prefill_align=4,
                       speculative=_self_draft(model, variables))
    prompts = _prompts([5, 9])
    with ServingGateway([EngineReplica(eng)]) as gw:
        rid = gw.submit(prompts[0], max_new_tokens=6,
                        speculative=False)
        r = gw.result(rid, timeout=60)
        np.testing.assert_array_equal(
            r["tokens"], _want(model, variables, prompts[0], 6))
        assert eng.spec_stats()["proposed"] == 0  # opt-out forwarded
        # unset: engine default (on); the key never rides into meta
        out = list(gw.run([{"prompt": prompts[1],
                            "max_new_tokens": 6, "i": 1}]))
        np.testing.assert_array_equal(
            out[0]["tokens"], _want(model, variables, prompts[1], 6))
        assert eng.spec_stats()["proposed"] > 0
    eng.close()

"""Sharded parameter server (``parallel.sharded_ps``): byte-balanced
plan determinism, K-vs-unsharded center parity for the delta family
under fixed seeded schedules (including through a kill/warm-restart
cycle), the shard-addressed zero-copy wire (version-delta pulls,
per-shard commit dedupe), the satellite regressions (read-only pulls,
bounded staleness log, packed-bytes reply cache + gauge), and the
trainer integration (``ps_shards=``, host-arm ``commit_overlap``)."""

import threading

import jax
import numpy as np
import pytest

from distkeras_tpu import telemetry
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.data import datasets
from distkeras_tpu.models import model_config
from distkeras_tpu.parallel.host_ps import (
    HostParameterServer,
    PSClient,
    PSServer,
    ResilientPSClient,
    pack_params,
)
from distkeras_tpu.parallel.sharded_ps import (
    NEVER_PULLED,
    ShardedParameterServer,
    ShardedPSClient,
    leaf_nbytes,
    plan_shards,
)
from distkeras_tpu.parallel.update_rules import (
    AdagRule,
    DownpourRule,
    DynSGDRule,
    ElasticRule,
)
from distkeras_tpu.trainers import AEASGD, DOWNPOUR, DynSGD

MLP = model_config("mlp", (8,), num_classes=4, hidden=(16,))
DATA = datasets.synthetic_classification(1536, (8,), 4, seed=0)

DELTA_RULES = [DownpourRule(), AdagRule(), DynSGDRule()]


@pytest.fixture(autouse=True)
def _racecheck():
    """Shard/seen locks are racecheck factories: run the whole suite
    instrumented and fail on any race/order/deadlock report."""
    racecheck.enable()
    yield
    reports = racecheck.disable()
    assert not reports, "\n".join(str(r) for r in reports)


def _params(seed=0, shapes=((3, 4), (4,), (8, 2), (5,), (2, 2, 2))):
    rng = np.random.default_rng(seed)
    return {f"w{i}": rng.normal(size=s).astype(np.float32)
            for i, s in enumerate(shapes)}


def _schedule(n_workers=3, n_commits=12, seed=7):
    """A fixed seeded commit schedule: (worker, delta, seq) tuples."""
    rng = np.random.default_rng(seed)
    seqs = {w: 0 for w in range(n_workers)}
    out = []
    for i in range(n_commits):
        w = int(rng.integers(n_workers))
        d = {k: rng.normal(size=v.shape).astype(np.float32) * 1e-2
             for k, v in _params(0).items()}
        out.append((w, d, seqs[w]))
        seqs[w] += 1
    return out


def test_plan_shards_byte_balanced_and_deterministic():
    p = _params(0)
    plan = plan_shards(p, 3)
    assert plan == plan_shards(p, 3)  # pure function of the template
    leaves = jax.tree_util.tree_leaves(p)
    # every leaf exactly once, canonical order within each shard
    flat = sorted(i for idx in plan for i in idx)
    assert flat == list(range(len(leaves)))
    assert all(idx == sorted(idx) for idx in plan)
    # byte balance: no shard above twice the mean (greedy largest-first
    # bound at these shapes)
    sizes = [sum(leaves[i].nbytes for i in idx) for idx in plan]
    assert max(sizes) <= 2 * (sum(sizes) / len(sizes))
    # K above the leaf count clamps (every shard owns >= 1 leaf)
    assert len(plan_shards(p, 99)) == len(leaves)


@pytest.mark.parametrize("rule", DELTA_RULES,
                         ids=lambda r: type(r).__name__)
@pytest.mark.parametrize("k", [2, 4])
def test_sharded_center_byte_identical_to_unsharded(rule, k):
    """ISSUE 4 acceptance: under a fixed seeded commit schedule the
    K-sharded final center is byte-identical to K=1 and to the
    unsharded ``HostParameterServer`` for every delta rule (per-leaf
    additive laws shard exactly; DynSGD's per-shard staleness equals
    the global staleness under any serial full-tree schedule)."""
    center = _params(0)
    servers = [HostParameterServer(rule, center),
               ShardedParameterServer(rule, center, 1),
               ShardedParameterServer(rule, center, k)]
    for ps in servers:
        for w in range(3):
            ps.pull(w)
        for w, d, seq in _schedule():
            ps.commit(w, d, seq=seq)
    packed = [pack_params(ps.center) for ps in servers]
    assert packed[0] == packed[1] == packed[2]
    assert (servers[0].staleness_log == servers[1].staleness_log
            == servers[2].staleness_log)
    assert servers[0].num_commits == servers[2].num_commits


@pytest.mark.parametrize("rule", DELTA_RULES,
                         ids=lambda r: type(r).__name__)
def test_sharded_parity_through_kill_restart(rule, tmp_path):
    """The same schedule split by a kill/``restart_from`` cycle lands
    on the same bytes: snapshot at the cut, restart, finish."""
    center = _params(0)
    sched = _schedule()
    ref = HostParameterServer(rule, center)
    sha = ShardedParameterServer(rule, center, 4)
    for ps in (ref, sha):
        for w in range(3):
            ps.pull(w)
        for w, d, seq in sched[:6]:
            ps.commit(w, d, seq=seq)
    path = sha.save_snapshot(tmp_path / "ps.snap")
    sha2 = ShardedParameterServer.from_snapshot(rule, path)
    for w, d, seq in sched[6:]:
        ref.commit(w, d, seq=seq)
        sha2.commit(w, d, seq=seq)
    assert pack_params(ref.center) == pack_params(sha2.center)
    # the dedupe caches survived: replaying the cut's last commit is a
    # no-op on both
    w, d, seq = sched[5]
    n = sha2.num_commits
    sha2.commit(w, d, seq=seq)
    assert sha2.num_commits == n


def test_elastic_family_shards_byte_identically():
    """The old K=1 gate is lifted (ISSUE 14): the elastic family's
    per-leaf lerp shards exactly like the delta family — a serial
    schedule against K=4 lands on the same bytes as the unsharded
    server, local tree and all."""
    rule = ElasticRule(alpha=0.3)
    center = _params(0)
    ref = HostParameterServer(rule, center)
    sha = ShardedParameterServer(rule, center, 4)
    rng = np.random.default_rng(7)
    locals_ = {ps: {w: ps.pull(w) for w in range(3)}
               for ps in (ref, sha)}
    for i in range(8):
        w = int(rng.integers(3))
        step = jax.tree_util.tree_map(
            lambda x: np.asarray(
                x + rng.normal(size=x.shape).astype(x.dtype) * 0.1),
            locals_[ref][w])
        for ps in (ref, sha):
            locals_[ps][w] = ps.commit(w, step, step, seq=i)
    assert pack_params(ref.center) == pack_params(sha.center)
    for w in range(3):
        assert (pack_params(locals_[ref][w])
                == pack_params(locals_[sha][w]))


def test_elastic_family_trains_sharded():
    """End-to-end: AEASGD at ps_shards=2 (the configuration the old
    gate rejected) trains to a finite loss on the host arm."""
    t = AEASGD(MLP, fidelity="host", ps_shards=2, num_workers=2,
               communication_window=2, batch_size=16, num_epoch=1)
    t.train(DATA)
    assert np.isfinite(t.history["round_loss"][-1])


def test_pull_returns_readonly_views_no_alias():
    """Satellite regression: the in-process arm must not be able to
    mutate server state through a pulled tree (``pull`` used to hand
    out the live ``_center``)."""
    for ps in (HostParameterServer(AdagRule(), _params(0)),
               ShardedParameterServer(AdagRule(), _params(0), 2)):
        pulled = ps.pull(0)
        before = {k: np.array(v) for k, v in ps.center.items()}
        with pytest.raises(ValueError):
            pulled["w0"][...] = 99.0
        d = jax.tree_util.tree_map(np.ones_like, _params(0))
        replied = ps.commit(0, d)
        with pytest.raises(ValueError):
            replied["w0"][...] = 99.0
        for k, v in before.items():
            np.testing.assert_array_equal(np.asarray(ps.center[k]),
                                          v + 1.0)


def test_staleness_log_bounded():
    """Satellite: the log keeps a documented window instead of one int
    per commit forever; the telemetry histogram stays the unbounded-
    horizon record."""
    ps = HostParameterServer(AdagRule(), _params(0))
    ps.STALENESS_LOG_WINDOW = 8
    d = jax.tree_util.tree_map(np.zeros_like, _params(0))
    ps.pull(0)
    for i in range(40):
        ps.commit(0, d)
    assert len(ps.staleness_log) <= 8 * 5 // 4
    assert ps.num_commits == 40  # the full count is not windowed
    sps = ShardedParameterServer(AdagRule(), _params(0), 2)
    sps.STALENESS_LOG_WINDOW = 8
    sps.pull(0)
    for i in range(40):
        sps.commit(0, d)
    assert len(sps.staleness_log) <= 8 * 5 // 4


def test_reply_cache_stores_packed_bytes_with_gauge():
    """Satellite: the dedupe cache holds packed bytes (explicit,
    measurable footprint) and reports it as a gauge; dedupe hits
    still reconstruct the exact reply."""
    tel = telemetry.enable()
    try:
        ps = HostParameterServer(AdagRule(), _params(0))
        ps.pull(0)
        d = jax.tree_util.tree_map(np.ones_like, _params(0))
        reply = ps.commit(0, d, seq=0)
        seq0, packed = ps._last_reply[0]
        assert isinstance(packed, bytes) and seq0 == 0
        nbytes = leaf_nbytes(jax.tree_util.tree_leaves(reply))
        assert len(packed) == nbytes
        assert tel.metrics.gauge("ps_reply_cache_bytes").value \
            == nbytes
        again = ps.commit(0, d, seq=0)  # dedupe hit
        for k in reply:
            np.testing.assert_array_equal(np.asarray(reply[k]),
                                          np.asarray(again[k]))
        ps.retire(0)
        assert tel.metrics.gauge("ps_reply_cache_bytes").value == 0
    finally:
        telemetry.disable()


def test_version_delta_pull_skips_unchanged_shards():
    """The server ships only shards whose clock advanced past the
    client's last-seen clocks; skipped shards are served from the
    client cache and the assembled tree still equals the center."""
    center = _params(0)
    ps = ShardedParameterServer(DownpourRule(), center, 3)
    server = PSServer(ps, center).start()
    host, port = server.address
    try:
        stats = {}
        c = ShardedPSClient(host, port, 0, center, num_shards=3,
                            stats=stats)
        c.pull()  # full (all clocks NEVER_PULLED)
        assert stats["pull_shards_skipped"] == 0
        t = c.pull()  # nothing advanced: every shard skipped
        assert stats["pull_shards_skipped"] == 3
        assert stats["pull_bytes_saved"] == leaf_nbytes(
            jax.tree_util.tree_leaves(center))
        for k in center:
            np.testing.assert_array_equal(t[k],
                                          np.asarray(ps.center[k]))
        # another client's commit advances every shard: full ship again
        d = jax.tree_util.tree_map(np.ones_like, center)
        c2 = ShardedPSClient(host, port, 1, center, num_shards=3)
        c2.commit(d, seq=0)
        t2 = c.pull()
        assert stats["pull_shards_skipped"] == 3  # unchanged
        for k in center:
            np.testing.assert_array_equal(t2[k],
                                          np.asarray(ps.center[k]))
        c.close()
        c2.close()
    finally:
        server.stop()


def test_sharded_wire_commit_dedupes_per_shard():
    """A retried logical commit (same seq) is deduped shard by shard —
    the reply is byte-identical and nothing applies twice."""
    center = _params(0)
    ps = ShardedParameterServer(AdagRule(), center, 4)
    server = PSServer(ps, center).start()
    host, port = server.address
    try:
        c = ShardedPSClient(host, port, 0, center, num_shards=4)
        c.pull()
        d = jax.tree_util.tree_map(np.ones_like, center)
        r1 = c.commit(d, seq=0)
        assert ps.num_commits == 1
        r2 = c.commit(d, seq=0)  # the lost-ack retry shape
        assert ps.num_commits == 1
        for k in center:
            np.testing.assert_array_equal(r1[k], r2[k])
        c.commit(d, seq=1)
        assert ps.num_commits == 2
        c.close()
    finally:
        server.stop()


def test_resilient_client_reconnects_sharded_wire():
    """``ResilientPSClient.for_address(shards=K)`` rebuilds a
    ``ShardedPSClient`` after a connection failure; the stats dict
    accumulates across the rebuild and at-most-once holds."""
    center = _params(0)
    ps = ShardedParameterServer(AdagRule(), center, 2)
    server = PSServer(ps, center).start()
    host, port = server.address
    try:
        stats = {}
        c = ResilientPSClient.for_address(
            host, port, worker_id=0, template=center, shards=2,
            shard_stats=stats, retries=2, backoff_base=1e-4)
        c.pull()
        d = jax.tree_util.tree_map(np.ones_like, center)
        c.commit(d)
        # sever the live connection; the next op must reconnect
        c._raw._sock.close()
        c.commit(d)
        assert ps.num_commits == 2
        assert c.retry_count >= 1
        c.close()
    finally:
        server.stop()


def test_concurrent_sharded_commits_land_exactly():
    """Racing workers against per-shard locks: every commit lands on
    every shard exactly once and the center stays finite."""
    center = _params(0)
    ps = ShardedParameterServer(AdagRule(), center, 4)
    n_threads, n_commits = 4, 8

    def run(w):
        ps.pull(w)
        rng = np.random.default_rng(w)
        for i in range(n_commits):
            d = {k: rng.normal(size=v.shape).astype(np.float32) * 1e-3
                 for k, v in center.items()}
            ps.commit(w, d, seq=i)

    threads = [threading.Thread(target=run, args=(w,))
               for w in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert ps.num_commits == n_threads * n_commits
    for s in ps._shards:
        assert s.num_commits == n_threads * n_commits
    assert all(np.isfinite(np.asarray(v)).all()
               for v in ps.center.values())


def test_mismatched_shard_plan_rejected():
    center = _params(0)
    ps = ShardedParameterServer(DownpourRule(), center, 2)
    with pytest.raises(ValueError, match="clocks|shards"):
        ps.pull_since(0, [NEVER_PULLED] * 3)


@pytest.mark.parametrize("transport", ["inprocess", "socket"])
def test_trainer_sharded_host_arm_trains(transport):
    """DOWNPOUR over the sharded PS (both transports) converges and
    emits the sharded history keys on the socket arm."""
    t = DOWNPOUR(MLP, fidelity="host", transport=transport,
                 ps_shards=2, num_workers=3, communication_window=2,
                 batch_size=16, num_epoch=2, learning_rate=0.01,
                 seed=0)
    t.train(DATA)
    losses = t.history["epoch_loss"]
    assert np.isfinite(losses).all() and losses[-1] < losses[0] + 0.1
    if transport == "socket":
        assert "pull_shards_skipped" in t.history
        assert "pull_bytes_saved" in t.history


def test_trainer_sharded_snapshot_restartable(tmp_path):
    """``ps_snapshot_every`` through a sharded run writes snapshots a
    sharded server restarts from."""
    path = tmp_path / "ps.snap"
    t = DOWNPOUR(MLP, fidelity="host", transport="socket", ps_shards=2,
                 num_workers=2, communication_window=2, batch_size=16,
                 num_epoch=1, learning_rate=0.01,
                 ps_snapshot_path=str(path), ps_snapshot_every=4)
    t.train(DATA)
    assert t.history["ps_snapshots"][-1] > 0
    restored = ShardedParameterServer.from_snapshot(DownpourRule(),
                                                    path)
    assert restored.num_shards == 2 and restored.num_commits > 0
    from distkeras_tpu.checkpoint import ps_snapshot_info

    info = ps_snapshot_info(path)
    assert info["sharded"] == 2
    assert info["num_commits"] == restored.num_commits


def test_commit_overlap_host_arm_trains_and_overlaps():
    """Host-arm ``commit_overlap`` double-buffers the worker loop (the
    exchange for window n runs under window n+1's compute): same data
    budget must converge on par with the in-order loop, and every
    commit must land (clock == recorded rounds)."""
    common = dict(fidelity="host", num_workers=2,
                  communication_window=2, batch_size=16, num_epoch=2,
                  learning_rate=0.01, seed=0)
    base = DOWNPOUR(MLP, **common)
    base.train(DATA)
    over = DOWNPOUR(MLP, commit_overlap=True, **common)
    over.train(DATA)
    assert over.parameter_server_state.num_commits == \
        len(over.history["round_loss"])
    assert over.history["epoch_loss"][-1] <= \
        base.history["epoch_loss"][-1] + 0.15
    # staleness-aware rule through the overlap path too
    dyn = DynSGD(MLP, commit_overlap=True, **common)
    dyn.train(DATA)
    assert np.isfinite(dyn.history["epoch_loss"]).all()


def test_commit_overlap_with_sharded_socket_and_retries():
    """The full composition: sharded wire + double-buffered loop +
    compute-level chaos retry — at-most-once must hold (commits ==
    recorded rounds) and training completes."""
    state = {"armed": True}

    def injector(w, epoch, r):
        if w == 0 and r == 1 and state.pop("armed", False):
            raise RuntimeError("chaos")

    t = DOWNPOUR(MLP, fidelity="host", transport="socket", ps_shards=2,
                 commit_overlap=True, num_workers=2,
                 communication_window=2, batch_size=16, num_epoch=1,
                 learning_rate=0.01, worker_retries=1,
                 fault_injector=injector)
    t.train(DATA)
    assert t.parameter_server_state.num_commits == \
        len(t.history["round_loss"])
    assert t.history["worker_round_retries"]


def test_sharded_elastic_k1_still_exact():
    """The pinned K=1 elastic server matches the unsharded one (same
    lerp law, one lock)."""
    center = _params(0)
    rule = ElasticRule(alpha=0.3)
    ref = HostParameterServer(rule, center)
    sha = ShardedParameterServer(rule, center, 1)
    local = jax.tree_util.tree_map(lambda x: x + 1.0, center)
    for ps in (ref, sha):
        ps.pull(0)
        ps.commit(0, local, local)
    assert pack_params(ref.center) == pack_params(sha.center)

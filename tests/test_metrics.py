"""ops.metrics (previously untested — VERDICT.md round-1 Weak #8) and
their wiring into evaluate_model + the in-training eval hook."""

import numpy as np

from distkeras_tpu.data import datasets
from distkeras_tpu.evaluators import evaluate_model
from distkeras_tpu.models import model_config
from distkeras_tpu.ops import metrics as M
from distkeras_tpu.trainers import ADAG, SingleTrainer


def test_accuracy():
    logits = np.array([[2.0, 1.0, 0.0],
                       [0.0, 3.0, 1.0],
                       [1.0, 0.0, 5.0],
                       [9.0, 0.0, 1.0]])
    labels = np.array([0, 1, 2, 1])
    assert float(M.accuracy(logits, labels)) == 0.75


def test_binary_accuracy_squeezes_single_logit():
    logits = np.array([[2.0], [-1.0], [0.5], [-0.2]])
    labels = np.array([1, 0, 0, 0])
    assert float(M.binary_accuracy(logits, labels)) == 0.75


def test_top_k_accuracy():
    logits = np.array([[5.0, 4.0, 0.0, 0.0],
                       [0.0, 1.0, 2.0, 3.0],
                       [1.0, 0.0, 0.0, 2.0]])
    labels = np.array([1, 0, 2])  # in top-2: yes, no, no
    np.testing.assert_allclose(
        float(M.top_k_accuracy(logits, labels, k=2)), 1.0 / 3.0,
        rtol=1e-6)
    assert float(M.top_k_accuracy(logits, labels, k=4)) == 1.0


def test_evaluate_model_reports_top_k():
    data = datasets.mnist_synth(512, seed=0)
    cfg = model_config("mlp", (28, 28, 1), num_classes=10, hidden=(32,))
    t = SingleTrainer(cfg, worker_optimizer="adam", learning_rate=3e-3,
                      batch_size=64, num_epoch=2)
    variables = t.train(data)
    m = evaluate_model(t.model, variables, data, batch_size=256,
                       top_k=5)
    assert set(m) == {"accuracy", "top5_accuracy"}
    assert m["top5_accuracy"] >= m["accuracy"]


def test_eval_dataset_records_accuracy_per_epoch():
    # a true holdout split (same generator => same class centers)
    rows = datasets.synthetic_classification(1280, (8,), 4, seed=0)
    data, holdout = rows.shard(5, 0).concat(rows.shard(5, 1)).concat(
        rows.shard(5, 2)).concat(rows.shard(5, 3)), rows.shard(5, 4)
    cfg = model_config("mlp", (8,), num_classes=4, hidden=(16,))

    t = SingleTrainer(cfg, worker_optimizer="adam", learning_rate=5e-3,
                      batch_size=32, num_epoch=3)
    t.train(data, eval_dataset=holdout)
    accs = t.history["eval_accuracy"]
    assert len(accs) == 3
    assert accs[-1] > 0.5, accs  # real generalization on a true holdout

    a = ADAG(cfg, num_workers=4, communication_window=2, batch_size=16,
             num_epoch=2, learning_rate=5e-3, worker_optimizer="adam")
    a.train(data, eval_dataset=holdout)
    assert len(a.history["eval_accuracy"]) == 2

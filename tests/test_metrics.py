"""ops.metrics (previously untested — VERDICT.md round-1 Weak #8) and
their wiring into evaluate_model + the in-training eval hook."""

import numpy as np
import pytest

from distkeras_tpu.data import datasets
from distkeras_tpu.evaluators import evaluate_model
from distkeras_tpu.models import model_config
from distkeras_tpu.ops import metrics as M
from distkeras_tpu.trainers import ADAG, SingleTrainer


def test_accuracy():
    logits = np.array([[2.0, 1.0, 0.0],
                       [0.0, 3.0, 1.0],
                       [1.0, 0.0, 5.0],
                       [9.0, 0.0, 1.0]])
    labels = np.array([0, 1, 2, 1])
    assert float(M.accuracy(logits, labels)) == 0.75


def test_binary_accuracy_squeezes_single_logit():
    logits = np.array([[2.0], [-1.0], [0.5], [-0.2]])
    labels = np.array([1, 0, 0, 0])
    assert float(M.binary_accuracy(logits, labels)) == 0.75


def test_top_k_accuracy():
    logits = np.array([[5.0, 4.0, 0.0, 0.0],
                       [0.0, 1.0, 2.0, 3.0],
                       [1.0, 0.0, 0.0, 2.0]])
    labels = np.array([1, 0, 2])  # in top-2: yes, no, no
    np.testing.assert_allclose(
        float(M.top_k_accuracy(logits, labels, k=2)), 1.0 / 3.0,
        rtol=1e-6)
    assert float(M.top_k_accuracy(logits, labels, k=4)) == 1.0


def test_evaluate_model_reports_top_k():
    data = datasets.mnist_synth(512, seed=0)
    cfg = model_config("mlp", (28, 28, 1), num_classes=10, hidden=(32,))
    t = SingleTrainer(cfg, worker_optimizer="adam", learning_rate=3e-3,
                      batch_size=64, num_epoch=2)
    variables = t.train(data)
    m = evaluate_model(t.model, variables, data, batch_size=256,
                       top_k=5)
    assert set(m) == {"accuracy", "top5_accuracy"}
    assert m["top5_accuracy"] >= m["accuracy"]


def test_eval_dataset_records_accuracy_per_epoch():
    # a true holdout split (same generator => same class centers)
    rows = datasets.synthetic_classification(1280, (8,), 4, seed=0)
    data, holdout = rows.shard(5, 0).concat(rows.shard(5, 1)).concat(
        rows.shard(5, 2)).concat(rows.shard(5, 3)), rows.shard(5, 4)
    cfg = model_config("mlp", (8,), num_classes=4, hidden=(16,))

    t = SingleTrainer(cfg, worker_optimizer="adam", learning_rate=5e-3,
                      batch_size=32, num_epoch=3)
    t.train(data, eval_dataset=holdout)
    accs = t.history["eval_accuracy"]
    assert len(accs) == 3
    assert accs[-1] > 0.5, accs  # real generalization on a true holdout

    a = ADAG(cfg, num_workers=4, communication_window=2, batch_size=16,
             num_epoch=2, learning_rate=5e-3, worker_optimizer="adam")
    a.train(data, eval_dataset=holdout)
    assert len(a.history["eval_accuracy"]) == 2


def test_confusion_matrix_and_prf_hand_checked():
    from distkeras_tpu.ops.metrics import (confusion_matrix,
                                           precision_recall_f1)

    # true:  0 0 1 1 2 2 ; pred: 0 1 1 1 2 0
    labels = np.array([0, 0, 1, 1, 2, 2])
    pred = np.array([0, 1, 1, 1, 2, 0])
    cm = np.asarray(confusion_matrix(pred, labels, 3))
    np.testing.assert_array_equal(
        cm, [[1, 1, 0], [0, 2, 0], [1, 0, 1]])
    m = precision_recall_f1(pred, labels, 3, average="macro")
    # per-class precision: 1/2, 2/3, 1; recall: 1/2, 1, 1/2
    prec = (0.5 + 2 / 3 + 1.0) / 3
    rec = (0.5 + 1.0 + 0.5) / 3
    f1 = (0.5 + 0.8 + 2 / 3) / 3
    np.testing.assert_allclose(float(m["precision"]), prec, rtol=1e-6)
    np.testing.assert_allclose(float(m["recall"]), rec, rtol=1e-6)
    np.testing.assert_allclose(float(m["f1"]), f1, rtol=1e-6)
    # weighted: uniform class counts here => equals macro
    w = precision_recall_f1(pred, labels, 3, average="weighted")
    np.testing.assert_allclose(float(w["f1"]), f1, rtol=1e-6)
    # micro == accuracy for single-label classification
    mi = precision_recall_f1(pred, labels, 3, average="micro")
    np.testing.assert_allclose(float(mi["f1"]), np.mean(pred == labels),
                               rtol=1e-6)


def test_prf_zero_division_convention():
    from distkeras_tpu.ops.metrics import precision_recall_f1

    # class 2 never predicted AND never true -> contributes 0, no nan
    labels = np.array([0, 0, 1])
    pred = np.array([0, 1, 1])
    m = precision_recall_f1(pred, labels, 3, average="macro")
    for v in m.values():
        assert np.isfinite(float(v))


def test_classification_evaluator_on_scored_dataset():
    from distkeras_tpu.evaluators import ClassificationEvaluator
    from distkeras_tpu.data.dataset import Dataset

    # logits predictions + one-hot labels (the OneHot workflow)
    logits = np.array([[2.0, 0.1, 0.0], [0.0, 3.0, 0.1],
                       [0.1, 0.0, 1.0], [5.0, 0.0, 0.0]])
    onehot = np.eye(3)[[0, 1, 2, 1]]
    ds = Dataset({"prediction": logits, "label": onehot})
    acc = ClassificationEvaluator(metric="accuracy").evaluate(ds)
    assert acc == 0.75
    f1 = ClassificationEvaluator(metric="f1").evaluate(ds)
    prec = ClassificationEvaluator(metric="precision").evaluate(ds)
    rec = ClassificationEvaluator(metric="recall").evaluate(ds)
    assert 0 < f1 <= 1 and 0 < prec <= 1 and 0 < rec <= 1
    # micro-averaged f1 equals accuracy
    mi = ClassificationEvaluator(metric="f1",
                                 average="micro").evaluate(ds)
    np.testing.assert_allclose(mi, acc, rtol=1e-6)
    # 'auc' is one-vs-rest macro only; the default weighted average
    # fails at construction
    with pytest.raises(ValueError, match="macro"):
        ClassificationEvaluator(metric="auc")


def test_prf_guards_and_column_vector_predictions():
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.evaluators import ClassificationEvaluator
    from distkeras_tpu.ops.metrics import confusion_matrix

    # out-of-range ids raise instead of silently dropping rows
    with pytest.raises(ValueError, match="out of range"):
        confusion_matrix(np.array([0, 1, 1]), np.array([0, 1, 5]), 3)
    # [N,1] column-vector predictions are squeezed, not argmax'd
    ds = Dataset({"prediction": np.array([[1], [2], [0]]),
                  "label": np.array([1, 2, 0])})
    assert ClassificationEvaluator(metric="accuracy").evaluate(ds) == 1.0
    assert ClassificationEvaluator(metric="f1").evaluate(ds) == 1.0
    # average typo fails at construction, not at evaluate time
    with pytest.raises(ValueError, match="average"):
        ClassificationEvaluator(average="marco")


def _auc_pairwise(scores, labels):
    """O(n^2) reference: P(score_pos > score_neg) + 0.5 P(tie)."""
    pos = scores[labels == 1]
    neg = scores[labels == 0]
    wins = (pos[:, None] > neg[None, :]).sum()
    ties = (pos[:, None] == neg[None, :]).sum()
    return (wins + 0.5 * ties) / (len(pos) * len(neg))


def test_auc_roc_matches_pairwise_reference():
    from distkeras_tpu.ops.metrics import auc_roc

    rng = np.random.default_rng(0)
    scores = rng.normal(size=200)
    labels = (rng.uniform(size=200) < 0.3).astype(np.int32)
    np.testing.assert_allclose(float(auc_roc(scores, labels)),
                               _auc_pairwise(scores, labels), rtol=1e-6)
    # ties (quantized scores) use average ranks
    q = np.round(scores * 2) / 2
    np.testing.assert_allclose(float(auc_roc(q, labels)),
                               _auc_pairwise(q, labels), rtol=1e-6)
    # perfect / inverted / random-identical sanity
    s = np.array([0.1, 0.2, 0.8, 0.9])
    l = np.array([0, 0, 1, 1])
    assert float(auc_roc(s, l)) == 1.0
    assert float(auc_roc(-s, l)) == 0.0
    with pytest.raises(ValueError, match="both classes"):
        auc_roc(s, np.ones(4))


def test_binary_classification_evaluator():
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.evaluators import BinaryClassificationEvaluator

    logits = np.array([[-2.0], [-0.5], [0.7], [1.5]])
    labels = np.array([0, 1, 0, 1])
    ds = Dataset({"prediction": logits, "label": labels})
    auc = BinaryClassificationEvaluator().evaluate(ds)
    np.testing.assert_allclose(
        auc, _auc_pairwise(logits.reshape(-1), labels), rtol=1e-6)
    acc = BinaryClassificationEvaluator(metric="accuracy").evaluate(ds)
    assert acc == 0.5  # thresh 0: pred = [0,0,1,1] vs [0,1,0,1]
    # probability scores with threshold 0.5
    probs = 1 / (1 + np.exp(-logits))
    ds2 = Dataset({"prediction": probs, "label": labels})
    np.testing.assert_allclose(
        BinaryClassificationEvaluator().evaluate(ds2), auc, rtol=1e-6)
    acc2 = BinaryClassificationEvaluator(
        metric="accuracy", threshold=0.5).evaluate(ds2)
    assert acc2 == acc
    with pytest.raises(ValueError, match="one score per row"):
        BinaryClassificationEvaluator().evaluate(
            Dataset({"prediction": np.zeros((4, 2)), "label": labels}))


def test_auc_and_macro_guards():
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.evaluators import ClassificationEvaluator
    from distkeras_tpu.ops.metrics import auc_roc
    import jax

    # non-{0,1} labels raise on concrete inputs...
    with pytest.raises(ValueError, match="labels in"):
        auc_roc(np.array([0.1, 0.2]), np.array([1, 2]))
    # ...and a single-class batch under jit is NaN, not 0.0
    out = jax.jit(auc_roc)(np.array([0.1, 0.2, 0.3]), np.ones(3))
    assert np.isnan(float(out))
    # macro averaging without an explicit class count fails fast
    with pytest.raises(ValueError, match="explicit num_classes"):
        ClassificationEvaluator(metric="f1", average="macro")
    ev = ClassificationEvaluator(metric="f1", average="macro",
                                 num_classes=4)
    ds = Dataset({"prediction": np.array([0, 1]),
                  "label": np.array([0, 1])})
    # 2 perfect classes out of 4 -> macro f1 = 0.5
    np.testing.assert_allclose(ev.evaluate(ds), 0.5, rtol=1e-6)
    with pytest.raises(ValueError, match="empty"):
        ClassificationEvaluator(metric="f1").evaluate(
            Dataset({"prediction": np.zeros((0,)),
                     "label": np.zeros((0,))}))


def test_macro_auc_matches_per_class_pairwise_reference():
    from distkeras_tpu.ops.metrics import auc_roc, macro_auc_roc

    rng = np.random.default_rng(1)
    n, c = 120, 4
    labels = rng.integers(0, c, size=n)
    # scores correlated with the true class so AUCs are informative
    scores = rng.normal(size=(n, c)) + 1.5 * np.eye(c)[labels]
    expect = np.mean([_auc_pairwise(scores[:, k],
                                    (labels == k).astype(np.int32))
                      for k in range(c)])
    np.testing.assert_allclose(float(macro_auc_roc(scores, labels)),
                               expect, rtol=1e-6)
    # consistency: binary [N,2] softmax-style scores, class-1 column ==
    # the plain binary AUC (class-0 column is its mirror)
    s2 = np.stack([-scores[:, 1], scores[:, 1]], axis=1)
    l2 = (labels == 1).astype(np.int32)
    np.testing.assert_allclose(
        float(macro_auc_roc(s2, l2)),
        float(auc_roc(s2[:, 1], l2)), rtol=1e-6)


def test_macro_auc_guards():
    from distkeras_tpu.ops.metrics import macro_auc_roc

    with pytest.raises(ValueError, match=r"\[N, C\]"):
        macro_auc_roc(np.zeros(8), np.zeros(8))
    with pytest.raises(ValueError, match="does not match"):
        macro_auc_roc(np.zeros((8, 3)), np.zeros(8), num_classes=5)
    # a class absent from the split has undefined one-vs-rest AUC
    with pytest.raises(ValueError, match="classes \\[2\\]"):
        macro_auc_roc(np.zeros((4, 3)), np.array([0, 0, 1, 1]))
    # label ids outside the score width raise, not silently rank as
    # all-negative for every class
    with pytest.raises(ValueError, match="out of range"):
        macro_auc_roc(np.zeros((4, 3)), np.array([0, 1, 2, 3]))
    with pytest.raises(ValueError, match="out of range"):
        macro_auc_roc(np.zeros((4, 3)), np.array([0, 1, 2, -1]))


def test_classification_evaluator_macro_auc():
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.evaluators import ClassificationEvaluator
    from distkeras_tpu.ops.metrics import macro_auc_roc

    rng = np.random.default_rng(2)
    labels = rng.integers(0, 3, size=60)
    logits = rng.normal(size=(60, 3)) + 2.0 * np.eye(3)[labels]
    ds = Dataset({"prediction": logits, "label": labels})
    ev = ClassificationEvaluator(metric="auc", average="macro")
    np.testing.assert_allclose(
        ev.evaluate(ds), float(macro_auc_roc(logits, labels)),
        rtol=1e-6)
    # one-hot labels work too (the OneHotTransformer workflow)
    ds_oh = Dataset({"prediction": logits,
                     "label": np.eye(3)[labels]})
    np.testing.assert_allclose(ev.evaluate(ds_oh), ev.evaluate(ds),
                               rtol=1e-6)
    # class-id predictions (argmax'd already) can't be ranked
    with pytest.raises(ValueError, match="per-class scores"):
        ev.evaluate(Dataset({"prediction": labels, "label": labels}))


def test_float_score_predictions_fail_loudly():
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.evaluators import (AccuracyEvaluator,
                                          ClassificationEvaluator)

    # a single-logit binary model's float scores must not be compared
    # raw against class ids (would silently return ~0 accuracy)
    ds = Dataset({"prediction": np.array([[0.9], [-1.2], [0.3]]),
                  "label": np.array([1, 0, 1])})
    with pytest.raises(ValueError, match="BinaryClassification"):
        AccuracyEvaluator().evaluate(ds)
    with pytest.raises(ValueError, match="BinaryClassification"):
        ClassificationEvaluator(metric="f1").evaluate(ds)
    # integral float class ids (e.g. argmax cast to float) still work
    ds_ok = Dataset({"prediction": np.array([1.0, 0.0, 1.0]),
                     "label": np.array([1, 0, 1])})
    assert AccuracyEvaluator().evaluate(ds_ok) == 1.0


def test_binary_accuracy_demands_threshold_for_probabilities():
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.evaluators import BinaryClassificationEvaluator

    probs = np.array([0.1, 0.4, 0.6, 0.9])
    labels = np.array([0, 0, 1, 1])
    ds = Dataset({"prediction": probs, "label": labels})
    # default threshold 0.0 on probability-shaped scores would score
    # everything class 1 -> demand an explicit threshold
    with pytest.raises(ValueError, match="threshold"):
        BinaryClassificationEvaluator(metric="accuracy").evaluate(ds)
    acc = BinaryClassificationEvaluator(
        metric="accuracy", threshold=0.5).evaluate(ds)
    assert acc == 1.0
    # an explicit 0.0 is honored without complaint
    acc0 = BinaryClassificationEvaluator(
        metric="accuracy", threshold=0.0).evaluate(ds)
    assert acc0 == 0.5
    # logit-shaped scores (outside [0,1]) keep the 0.0 default
    ds_logit = Dataset({"prediction": np.array([-2.0, -0.5, 0.7, 1.5]),
                        "label": labels})
    assert BinaryClassificationEvaluator(
        metric="accuracy").evaluate(ds_logit) == 1.0


def test_binary_evaluator_rejects_empty():
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.evaluators import BinaryClassificationEvaluator

    with pytest.raises(ValueError, match="empty"):
        BinaryClassificationEvaluator().evaluate(
            Dataset({"prediction": np.zeros((0,)),
                     "label": np.zeros((0,))}))


def test_perplexity():
    from distkeras_tpu.ops.metrics import perplexity

    rng = np.random.default_rng(0)
    # uniform logits -> exactly V
    v = 13
    logits = np.zeros((4, 6, v), np.float32)
    labels = rng.integers(0, v, (4, 6))
    np.testing.assert_allclose(float(perplexity(logits, labels)), v,
                               rtol=1e-5)
    # a (nearly) perfect model -> ppl ~ 1
    sharp = np.full((4, 6, v), -30.0, np.float32)
    for i in range(4):
        for t in range(6):
            sharp[i, t, labels[i, t]] = 30.0
    assert float(perplexity(sharp, labels)) < 1.0001
    # matches manual mean-CE exponential on random logits
    logits = rng.normal(size=(3, 5, v)).astype(np.float32)
    labels2 = rng.integers(0, v, (3, 5))
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    nll = -np.log(p[np.arange(3)[:, None], np.arange(5)[None], labels2])
    np.testing.assert_allclose(float(perplexity(logits, labels2)),
                               np.exp(nll.mean()), rtol=1e-5)

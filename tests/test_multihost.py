"""Multi-host substrate (SURVEY.md §7 L0): jax.distributed cluster
formation, per-process data sharding, and trainers running over a mesh
that spans processes — validated with a real 2-process CPU cluster
(Gloo collectives) launched through the deploy module."""

import json
import pathlib
import sys

import numpy as np
import pytest

import distkeras_tpu.deploy as deploy
from distkeras_tpu import mesh as mesh_lib
from distkeras_tpu.data import datasets

CHILD = str(pathlib.Path(__file__).with_name("_multihost_child.py"))
REPO = str(pathlib.Path(__file__).resolve().parent.parent)


def test_initialize_cluster_single_process_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    mesh_lib.initialize_cluster()  # must not raise or block
    mesh_lib.initialize_cluster(num_processes=1)


def test_process_shard_single_process_identity():
    ds = datasets.synthetic_classification(64, (4,), 2, seed=0)
    assert mesh_lib.process_shard(ds) is ds


def test_tpu_pod_job_builds_gcloud_command():
    job = deploy.TPUPodJob("my-pod", "us-central2-b",
                           ["python", "train.py", "--epochs", "3"],
                           project="p")
    cmd = job.submit(dry_run=True)
    assert cmd[:2] == ["gcloud", "--project=p"]
    assert "--worker=all" in cmd
    assert any("train.py" in c for c in cmd)


def test_tpu_pod_job_submit_executes_gcloud(tmp_path, monkeypatch):
    """submit(dry_run=False) really execs gcloud with the built argv —
    exercised against a recording stub on PATH (round-2 Weak #4: the
    dry-run test asserted substrings but executed nothing)."""
    import json as _json
    import os
    import stat
    import subprocess

    record = tmp_path / "argv.json"
    stub = tmp_path / "gcloud"
    stub.write_text(
        "#!/usr/bin/env python3\n"
        "import json, sys\n"
        f"json.dump(sys.argv[1:], open({str(record)!r}, 'w'))\n")
    stub.chmod(stub.stat().st_mode | stat.S_IXUSR)
    monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")

    job = deploy.TPUPodJob("pod-7", "us-central2-b",
                           ["python", "-m", "train", "--lr", "0.1"])
    result = job.submit(dry_run=False)
    assert result.returncode == 0
    argv = _json.loads(record.read_text())
    assert argv == job.build_command()[1:]
    # a failing gcloud surfaces as CalledProcessError (check=True)
    stub.write_text("#!/bin/sh\nexit 3\n")
    with pytest.raises(subprocess.CalledProcessError):
        job.submit(dry_run=False)


@pytest.mark.parametrize("num_processes", [2])
def test_two_process_cluster_trains_and_agrees(num_processes,
                                               tmp_path):
    """Sync + async-PS training over a mesh spanning 2 real processes:
    both processes must converge and report identical global losses."""
    results = deploy.run_multiprocess(
        CHILD, num_processes,
        env={"PYTHONPATH": REPO,
             "DKT_CKPT_DIR": str(tmp_path / "tp_ckpt")},
        timeout_s=600.0)
    assert len(results) == num_processes
    payloads = []
    for r in results:
        line = [ln for ln in r.stdout.splitlines()
                if ln.startswith("{")][-1]
        payloads.append(json.loads(line))
    a, b = sorted(payloads, key=lambda p: p["process"])
    assert [a["process"], b["process"]] == [0, 1]
    # identical global telemetry on every host
    assert a["sync_epoch_loss"] == b["sync_epoch_loss"]
    assert a["adag_round_loss"] == b["adag_round_loss"]
    assert a["small_sync_loss"] == b["small_sync_loss"]
    assert a["tp_sync_loss"] == b["tp_sync_loss"]
    # TP is a layout change, not an algorithm change: same losses as
    # the dp-only run of the same configuration
    np.testing.assert_allclose(a["tp_sync_loss"], a["small_sync_loss"],
                               rtol=2e-4, atol=2e-5)
    # multi-host sharded (orbax) checkpoint: kill-at-1/2 + resume
    # reproduced the uninterrupted run on both processes
    assert a["tp_resume_match"] is True
    assert b["tp_resume_match"] is True
    # ...and the same for the async PS family's sharded worker states
    assert a["ps_resume_match"] is True
    assert b["ps_resume_match"] is True
    # async PS with tensor-parallel workers spanning both processes:
    # identical telemetry everywhere, full staleness spread, learning
    assert a["ps_tp_round_loss"] == b["ps_tp_round_loss"]
    assert a["ps_tp_staleness"] == b["ps_tp_staleness"] == [0, 1, 2, 3]
    tp_curve = a["ps_tp_round_loss"]
    assert tp_curve[-1] < tp_curve[0], tp_curve
    # cross-host faithful PS (socket transport, PS on process 0):
    # identical global telemetry and final center on both processes,
    # every worker's commits landed, training made progress
    assert a["host_ps_epoch_loss"] == b["host_ps_epoch_loss"]
    assert a["host_ps_center_sum"] == b["host_ps_center_sum"]
    assert a["host_ps_commits"] == b["host_ps_commits"]
    # 1024 rows / 4 workers / batch 8 = 32 batches -> 16 rounds/worker
    assert a["host_ps_commits"] == 64
    assert a["host_ps_local_rounds"] == b["host_ps_local_rounds"] == 32
    assert a["host_ps_epoch_loss"][-1] < 1.6  # 4-class xent from ~1.61
    # and real training signal
    sync = a["sync_epoch_loss"]
    assert sync[-1] < sync[0], sync
    adag = a["adag_round_loss"]
    assert adag[-1] < adag[0] * 1.1, adag
    assert sorted(a["adag_staleness"]) == list(range(8))

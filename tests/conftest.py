"""Test harness: 8 virtual CPU devices (the reference's ``local[N]`` mode,
SURVEY.md §4).

The container's sitecustomize pins the platform list to the real TPU
(``axon``) at interpreter startup and ignores later env-var changes, so
the reliable override is ``jax.config.update`` after import — plus
``XLA_FLAGS`` set in-process before any backend initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8 and devs[0].platform == "cpu", devs
    return devs

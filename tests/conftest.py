"""Test harness: 8 virtual CPU devices (the reference's ``local[N]`` mode).

Must set flags before jax initializes (SURVEY.md §4: multi-device CPU mesh
via ``--xla_force_host_platform_device_count`` is the Spark ``local[N]``
analogue).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()

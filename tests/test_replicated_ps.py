"""Replicated parameter server (ISSUE 10): commit-log shipping to hot
standbys, deterministic election, epoch fencing of deposed primaries,
exactly-once across failover via the replicated dedupe table, and the
acceptance drill — chaos-kill the primary mid-training and land on a
final center byte-identical to the uninterrupted run (K in {1, 4}
shards)."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import telemetry
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.data import datasets
from distkeras_tpu.models import ModelSpec, model_config
from distkeras_tpu.parallel.faults import ChaosTransport
from distkeras_tpu.parallel.host_ps import (PSClient, PSFencedError,
                                            ResilientPSClient)
from distkeras_tpu.parallel.replicated_ps import (PSReplica, elect,
                                                  make_replica_group,
                                                  mint_epoch,
                                                  query_status)
from distkeras_tpu.parallel.update_rules import DownpourRule
from distkeras_tpu.trainers import DOWNPOUR

jax.config.update("jax_platforms", "cpu")

MLP = model_config("mlp", (8,), num_classes=4, hidden=(16,))
DATA = datasets.synthetic_classification(1024, (8,), 4, seed=0)


@pytest.fixture(autouse=True)
def _racecheck():
    """The whole replication suite runs under the lockset race +
    deadlock detector; any report fails the test."""
    racecheck.enable()
    yield
    reports = racecheck.disable()
    assert not reports, "\n".join(str(r) for r in reports)


def _params(seed=0, shapes=((3, 4), (4,))):
    rng = np.random.default_rng(seed)
    return {f"w{i}": rng.normal(size=s).astype(np.float32)
            for i, s in enumerate(shapes)}


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


def _stop_all(nodes):
    for n in nodes:
        n.stop()


# ---- election ----------------------------------------------------------

def test_election_is_deterministic():
    """Highest (epoch, last_applied_seq) wins; ties break by ADDRESS
    ORDER (lowest index), so every replica evaluating the same
    candidate set picks the same winner."""
    assert elect([(1, 5, 0), (1, 7, 1)]) == 1   # longer log wins
    assert elect([(1, 99, 0), (2, 0, 1)]) == 1  # epoch dominates seq
    assert elect([(1, 5, 2), (1, 5, 0), (1, 5, 1)]) == 0  # tie: order
    assert elect([(3, 4, 1)]) == 1
    with pytest.raises(ValueError, match="at least one"):
        elect([])


def test_election_invariant_under_candidate_order():
    """``elect()`` is the agreement point of the whole failover
    protocol: every standby runs it over whatever candidate subset it
    probed, in whatever order replies arrived.  Exhaustively: for every
    2-node and 3-node (epoch, last_applied_seq) tie pattern and EVERY
    permutation of the candidate list, the winner is identical — and it
    is the max by (epoch, last_applied, lowest index), i.e. the same
    pure function the protocol model checker imports
    (analysis/protomodel)."""
    import itertools

    # every tie pattern over {distinct-low, distinct-high, tied}: 3
    # values per axis cover all equality relations among <=3 nodes
    axis = (0, 1, 1)  # includes a duplicated value -> true ties
    for n in (2, 3):
        for epochs in itertools.product(axis, repeat=n):
            for seqs in itertools.product(axis, repeat=n):
                cands = [(epochs[i], seqs[i], i) for i in range(n)]
                expected = max(
                    cands, key=lambda c: (c[0], c[1], -c[2]))[2]
                for perm in itertools.permutations(cands):
                    got = elect(list(perm))
                    assert got == expected, (
                        f"elect{tuple(perm)} = {got}, "
                        f"want {expected} (from {cands})")


def test_mint_epoch_residue_unique_and_monotone():
    """``mint_epoch`` is pure: for every (current, floor) pair each
    index mints in its own residue class (epoch % N == index), strictly
    above both inputs — so concurrent elections on both sides of a
    partition can never collide, whatever each side last saw."""
    for group in (2, 3, 5):
        for current in range(0, 12):
            for floor in range(0, 12):
                minted = [mint_epoch(current, floor, i, group)
                          for i in range(group)]
                assert len(set(minted)) == group  # pairwise distinct
                for i, e in enumerate(minted):
                    assert e % group == i
                    assert e > current and e > floor
                    # re-minting from the result stays monotone
                    assert mint_epoch(e, floor, i, group) > e


def test_epoch_minting_is_globally_unique():
    """Concurrent elections on both sides of a partition must never
    mint the SAME epoch — the split-brain hole plain epoch fencing
    cannot close.  Every node mints in its own residue class
    (epoch % N == index), so successive promotions, whoever wins
    them, produce strictly increasing and never-colliding epochs; and
    a primary refuses a peer's stream AT its own epoch outright."""
    center = _params(6)
    delta = {k: np.ones_like(v) for k, v in center.items()}
    nodes = make_replica_group(DownpourRule(), center, replicas=3,
                               failover_timeout=30.0)
    try:
        assert nodes[0].epoch == 3  # bootstrap: residue 0 (mod 3)
        # one commit ships the bootstrap epoch to every standby
        cli = PSClient(*nodes[0].worker_address, 0, center)
        cli.pull()
        cli.commit(delta, seq=0)
        cli.close()
        assert [n.epoch for n in nodes] == [3, 3, 3]
        nodes[1].promote(reason="manual")
        assert nodes[1].epoch == 4  # residue 1 (mod 3), above 3
        nodes[2].promote(reason="manual")
        assert nodes[2].epoch == 5  # residue 2 (mod 3), above 4
        # defensive depth: even a (protocol-impossible) equal-epoch
        # stream is refused while this node believes itself primary
        frame = (b"h" + nodes[2].epoch.to_bytes(8, "big")
                 + (0).to_bytes(8, "big") + (0).to_bytes(8, "big"))
        reply, _ = nodes[2]._dispatch_repl(frame)
        assert reply[:1] == b"f"
    finally:
        _stop_all(nodes)


# ---- replication + failover --------------------------------------------

def test_kill_primary_fails_over_exactly_once():
    """Commits replicate to the standby in sync mode; killing the
    primary promotes the standby (epoch 3 — node 1's first mint above
    the bootstrap epoch 2) and the resilient client
    walks onto it; the replicated dedupe table keeps the total applied
    commits exactly-once, and the surviving center equals the same
    delta schedule applied to a plain single server."""
    center = _params(0)
    delta = {k: np.full_like(v, 0.01) for k, v in center.items()}
    nodes = make_replica_group(DownpourRule(), center, replicas=2,
                               failover_timeout=0.4)
    try:
        cli = ResilientPSClient.for_replicas(
            [n.worker_address for n in nodes], worker_id=0,
            template=center, retries=20, backoff_base=0.05, seed=0)
        try:
            cli.pull()
            for _ in range(3):
                cli.commit(delta)
            assert nodes[1].last_applied == 3  # sync mode: shipped
            nodes[0].kill()
            for _ in range(2):
                cli.commit(delta)  # rides the failover
            cli.done()
        finally:
            cli.close()
        assert cli.replicas.failovers >= 1
        assert nodes[1].role == "primary"
        assert nodes[1].epoch == 3
        assert nodes[1].ps.num_commits == 5  # exactly-once held
        from distkeras_tpu.parallel.host_ps import HostParameterServer
        ref = HostParameterServer(DownpourRule(), center)
        ref.pull(0)
        for s in range(5):
            ref.commit(0, delta, seq=s)
        for a, b in zip(jax.tree_util.tree_leaves(nodes[1].ps.center),
                        jax.tree_util.tree_leaves(ref.center)):
            np.testing.assert_array_equal(a, b)
    finally:
        _stop_all(nodes)


def test_lost_ack_retry_dedupes_across_failover():
    """The exactly-once acceptance in miniature: a commit whose ACK
    was lost is retried — with the identical seq — against the NEWLY
    PROMOTED node, whose replicated dedupe table recognizes it and
    replies from cache instead of applying twice."""
    center = _params(1)
    delta = {k: np.full_like(v, 0.5) for k, v in center.items()}
    nodes = make_replica_group(DownpourRule(), center, replicas=2,
                               failover_timeout=0.4)
    try:
        c1 = PSClient(*nodes[0].worker_address, 0, center)
        c1.pull()
        c1.commit(delta, seq=0)  # applied + replicated; "ack lost"
        c1.close()
        nodes[0].kill()
        _wait(lambda: nodes[1].role == "primary", msg="promotion")
        c2 = PSClient(*nodes[1].worker_address, 0, center)
        c2.pull()
        c2.commit(delta, seq=0)  # the retry: MUST dedupe
        c2.close()
        assert nodes[1].ps.num_commits == 1
        np.testing.assert_array_equal(
            nodes[1].ps.center["w0"], center["w0"] + 0.5)
    finally:
        _stop_all(nodes)


def test_deposed_primary_is_fenced_and_demotes():
    """Epoch fencing: promoting the standby while the old primary is
    still alive bumps the epoch; the old primary's replication stream
    is rejected with the newer epoch, its late commits fail instead of
    forking history, and it demotes itself to standby."""
    center = _params(2)
    delta = {k: np.ones_like(v) for k, v in center.items()}
    # lazy election timeout: nothing promotes on its own here
    nodes = make_replica_group(DownpourRule(), center, replicas=2,
                               failover_timeout=30.0)
    try:
        c0 = PSClient(*nodes[0].worker_address, 0, center)
        c0.pull()
        c0.commit(delta, seq=0)
        nodes[1].promote(reason="manual")  # split brain, on purpose
        assert nodes[1].epoch == 3
        # the deposed primary notices the fence and steps down
        _wait(lambda: nodes[0].role == "standby", msg="demotion")
        assert nodes[0].epoch == 3
        # its worker port is back to reserved: late writers are turned
        # away at the door (refused), or fenced if they raced the
        # demotion window — either way the commit DOES NOT apply
        with pytest.raises((ConnectionError, OSError)):
            c0.commit(delta, seq=1)
            c0.close()
            c_late = PSClient(*nodes[0].worker_address, 0, center)
            c_late.commit(delta, seq=1)
        assert nodes[1].ps.num_commits == 1
        status = query_status(nodes[1].repl_address)
        assert status["role"] == "primary" and status["epoch"] == 3
    finally:
        _stop_all(nodes)


def test_standby_snapshot_restart_resumes_position():
    """A standby's snapshot carries the inner PS state (dedupe table
    included), the fencing epoch, and its replication position;
    ``from_snapshot`` rejoins at ``last_applied`` so the primary only
    ships what was missed."""
    from distkeras_tpu import checkpoint

    center = _params(3)
    delta = {k: np.ones_like(v) for k, v in center.items()}
    nodes = make_replica_group(DownpourRule(), center, replicas=2,
                               failover_timeout=30.0)
    restored = None
    try:
        cli = PSClient(*nodes[0].worker_address, 0, center)
        cli.pull()
        for s in range(4):
            cli.commit(delta, seq=s)
        cli.close()
        assert nodes[1].last_applied == 4
        snap = nodes[1].snapshot()
        assert snap["repl_last_applied"] == 4
        restored = PSReplica.from_snapshot(DownpourRule(), snap)
        assert restored.last_applied == 4
        assert restored.ps.num_commits == 4
        assert restored.ps.epoch == 2
        assert restored.role == "standby"
        np.testing.assert_array_equal(restored.ps.center["w0"],
                                      nodes[1].ps.center["w0"])
        # the durable form feeds the postmortem's epoch cross-check
        info_path = None
        import tempfile
        with tempfile.NamedTemporaryFile(suffix=".snap",
                                         delete=False) as f:
            info_path = f.name
        checkpoint.save_ps_snapshot(info_path, snap)
        info = checkpoint.ps_snapshot_info(info_path)
        assert info["epoch"] == 2
        assert info["last_acked"] == {"0": 3}
    finally:
        if restored is not None:
            restored.stop()
        _stop_all(nodes)


def test_sharded_replicated_composition():
    """K=4 shards under replication: every non-empty shard's commit
    ships as its own log entry, the standby reassembles the identical
    sharded state, and failover preserves it."""
    center = _params(4, shapes=((3, 4), (4,), (4, 2), (2,)))
    delta = {k: np.full_like(v, 0.25) for k, v in center.items()}
    nodes = make_replica_group(DownpourRule(), center, replicas=2,
                               num_shards=4, failover_timeout=0.4)
    try:
        cli = ResilientPSClient.for_replicas(
            [n.worker_address for n in nodes], worker_id=0,
            template=center, shards=4, retries=20,
            backoff_base=0.05, seed=0)
        try:
            cli.pull()
            for _ in range(3):
                cli.commit(delta)
            nodes[0].kill()
            cli.commit(delta)
            cli.done()
        finally:
            cli.close()
        assert nodes[1].role == "primary" and nodes[1].epoch == 3
        ps = nodes[1].ps
        assert ps.num_commits == 4
        assert [s.num_commits for s in ps._shards] == \
            [4] * ps.num_shards
        np.testing.assert_allclose(ps.center["w0"],
                                   center["w0"] + 4 * 0.25, rtol=1e-6)
    finally:
        _stop_all(nodes)


def test_no_quorum_blocks_isolated_standby_election(monkeypatch):
    """A standby that cannot reach ANY peer must not usurp the
    primary: probes that TIME OUT (a partition) leave the majority
    unaccounted, so the election stands down every cycle.  Once the
    probe sees the dead primary's host actively REFUSE the connection
    (a crash, not a partition), the peer counts as accounted, quorum
    is met, and the standby promotes."""
    from distkeras_tpu.parallel import replicated_ps as rps

    center = _params(5)
    tel = telemetry.enable()
    nodes = make_replica_group(DownpourRule(), center, replicas=2,
                               failover_timeout=0.3)
    try:
        ctr = tel.metrics.counter("ps_election_no_quorum_total")
        pre = ctr.value
        # every probe "times out": unreachable, but NOT confirmed dead
        monkeypatch.setattr(rps, "probe_replica",
                            lambda addr, timeout=0.5: (None, False))
        nodes[0].kill()
        time.sleep(1.5)  # several election timeouts' worth
        assert nodes[1].role == "standby"  # stood down, every cycle
        assert ctr.value > pre
        # the partition "heals": the real probe now sees the killed
        # primary's host refuse — confirmed death, quorum, promotion
        monkeypatch.undo()
        _wait(lambda: nodes[1].role == "primary",
              msg="promotion after quorum")
    finally:
        _stop_all(nodes)
        telemetry.disable()


def test_standby_ahead_of_new_primary_is_rewound():
    """A standby AHEAD of a newly elected primary (unreachable during
    the election) must not ack the new primary's lower seqs as
    duplicates — its tail holds old-epoch entries the new primary
    will rewrite under its own epoch.  The promotion base stamped on
    append/heartbeat frames exposes the mismatch: the standby demands
    a full resync and converges byte-identically instead of silently
    diverging."""
    center = _params(7)
    delta = {k: np.full_like(v, 0.125) for k, v in center.items()}
    nodes = make_replica_group(DownpourRule(), center, replicas=3,
                               failover_timeout=30.0,
                               heartbeat_s=0.1)
    try:
        cli = PSClient(*nodes[0].worker_address, 0, center)
        cli.pull()
        cli.commit(delta, seq=0)
        cli.commit(delta, seq=1)
        assert [n.last_applied for n in nodes[1:]] == [2, 2]
        # hold node 2 back: freeze the primary's maintenance thread
        # (no revive) and down its link, then commit two more — node 1
        # runs ahead to seq 4 while node 2 stays at 2
        repl = nodes[0].replicator
        repl._stop_evt.set()
        repl._wake.set()
        with repl._lock:
            link = next(l for l in repl._links
                        if l.addr == tuple(nodes[2].repl_address))
            repl._mark_down_locked(link, ConnectionError("held back"))
        cli.commit(delta, seq=2)
        cli.commit(delta, seq=3)
        cli.close()
        assert nodes[1].last_applied == 4
        assert nodes[2].last_applied == 2
        nodes[0].kill()
        # the election node 1 was unreachable for: node 2 wins anyway
        nodes[2].promote(reason="failover")
        _wait(lambda: (nodes[1].epoch == nodes[2].epoch
                       and nodes[1].last_applied == 2
                       and not nodes[1]._diverged),
              msg="bootstrap rewind onto the new primary")
        assert nodes[1].ps.num_commits == 2  # seqs 3, 4 are GONE
        # and the rewound standby chains cleanly on the new epoch
        c2 = PSClient(*nodes[2].worker_address, 0, center)
        c2.pull()
        c2.commit(delta, seq=4)
        c2.close()
        _wait(lambda: nodes[1].ps.num_commits == 3,
              msg="catch-up after rewind")
        for a, b in zip(jax.tree_util.tree_leaves(nodes[1].ps.center),
                        jax.tree_util.tree_leaves(nodes[2].ps.center)):
            np.testing.assert_array_equal(a, b)
    finally:
        _stop_all(nodes)


def test_sync_commit_with_all_standbys_down_is_flagged():
    """Sync mode's "acked means replicated" promise lapses when every
    standby is down; the commit still acks (halting the lone survivor
    would be worse) but every such commit is counted, so a postmortem
    can attribute a later rewind to the lapse window."""
    center = _params(8)
    delta = {k: np.ones_like(v) for k, v in center.items()}
    tel = telemetry.enable()
    nodes = make_replica_group(DownpourRule(), center, replicas=2,
                               failover_timeout=30.0)
    try:
        cli = PSClient(*nodes[0].worker_address, 0, center)
        cli.pull()
        cli.commit(delta, seq=0)  # replicated: not flagged
        ctr = tel.metrics.counter("ps_sync_unreplicated_total")
        pre = ctr.value
        nodes[1].kill()
        cli.commit(delta, seq=1)  # acks, but NO standby holds it
        cli.commit(delta, seq=2)
        cli.close()
        assert nodes[0].ps.num_commits == 3
        assert ctr.value >= pre + 2
    finally:
        _stop_all(nodes)
        telemetry.disable()


# ---- the acceptance drill ----------------------------------------------

@pytest.mark.parametrize("shards", [1, 4])
def test_chaos_kill_primary_byte_identical_center(shards, tmp_path):
    """THE ISSUE 10 acceptance: async SOCKET training against a
    2-node replica group, seeded chaos on the wire, primary killed
    mid-training.  The standby self-promotes, the worker fails over,
    and the final center is BYTE-IDENTICAL to the same run against an
    unmolested group — the replicated dedupe table absorbed every
    lost-ack retry exactly-once (K in {1, 4} shards)."""
    model = ModelSpec.from_config(MLP).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, 8), jnp.float32))
    center = jax.tree_util.tree_map(np.asarray, variables["params"])
    kwargs = dict(fidelity="host", transport="socket", num_workers=1,
                  communication_window=2, batch_size=16, num_epoch=1,
                  learning_rate=0.01, worker_optimizer="adam",
                  worker_retries=14, ps_shards=shards)

    # uninterrupted baseline against a healthy replica group
    base_nodes = make_replica_group(DownpourRule(), center,
                                    replicas=2, num_shards=shards,
                                    failover_timeout=30.0)
    try:
        base = DOWNPOUR(MLP, ps_replicas=[n.worker_address
                                          for n in base_nodes],
                        **kwargs)
        base.train(DATA, initial_variables=variables)
        n_rounds = len(base.history["round_loss"])
        assert base_nodes[0].ps.num_commits == n_rounds
        assert base.history["ps_epoch"][-1] == 2
        base_center = jax.tree_util.tree_map(
            np.copy, base_nodes[0].ps.center)
    finally:
        _stop_all(base_nodes)

    # the drill: same schedule, chaos on the wire, primary killed
    nodes = make_replica_group(DownpourRule(), center, replicas=2,
                               num_shards=shards,
                               failover_timeout=0.5)
    try:
        def killer():
            while nodes[0].ps.num_commits < 5:
                time.sleep(0.002)
            nodes[0].kill()

        k = threading.Thread(target=killer)
        k.start()
        with ChaosTransport(seed=11, delay_rate=0.1, delay_s=0.01,
                            reset_rate=0.05, max_injections=3,
                            skip_ops=8) as ct:
            t = DOWNPOUR(MLP, ps_replicas=[n.worker_address
                                           for n in nodes], **kwargs)
            t.train(DATA, initial_variables=variables)
        k.join()
        assert ct.total_injected > 0  # the chaos really fired
        assert t.history.get("worker_round_retries"), (
            "the kill was invisible to the worker — test proved "
            "nothing")
        assert t.history["ps_failovers"][-1] >= 1
        assert t.history["ps_epoch"][-1] == 3
        ps = nodes[1].ps
        # exactly-once across kill + chaos: applied == rounds
        assert len(t.history["round_loss"]) == n_rounds
        assert ps.num_commits == n_rounds
        # byte-identical final center vs. the unmolested run
        for a, b in zip(jax.tree_util.tree_leaves(ps.center),
                        jax.tree_util.tree_leaves(base_center)):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(
                jax.tree_util.tree_leaves(base.trained_variables),
                jax.tree_util.tree_leaves(t.trained_variables)):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b))
    finally:
        _stop_all(nodes)

"""Round attribution (ISSUE 17): roofline math against hand-computed
numbers, the per-program XLA cost ledger, the sampled step-time
decomposition's byte-identity + telemetry surface, and the
disabled-path overhead guard."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import attrib, telemetry
from distkeras_tpu import mesh as mesh_lib
from distkeras_tpu.parallel import ps_dataplane
from distkeras_tpu.parallel.ps_emulator import commit_permutation
from distkeras_tpu.parallel.update_rules import RULES
from distkeras_tpu.workers import (
    TrainState,
    make_train_step,
    resolve_optimizer,
)


# ---- pure math ---------------------------------------------------------

def test_roofline_hand_numbers_comm_bound():
    r = attrib.roofline(2e9, 1e9, peak_flops=1e12,
                        peak_bytes_per_sec=1e11)
    assert r["t_compute_s"] == pytest.approx(2e-3)
    assert r["t_comm_s"] == pytest.approx(1e-2)
    assert r["t_roofline_s"] == pytest.approx(1e-2)
    assert r["bound"] == "comm"
    assert r["arithmetic_intensity"] == pytest.approx(2.0)
    assert r["machine_balance"] == pytest.approx(10.0)


def test_roofline_hand_numbers_compute_bound():
    r = attrib.roofline(2e9, 1e8, peak_flops=1e12,
                        peak_bytes_per_sec=1e11)
    assert r["t_compute_s"] == pytest.approx(2e-3)
    assert r["t_comm_s"] == pytest.approx(1e-3)
    assert r["t_roofline_s"] == pytest.approx(2e-3)
    assert r["bound"] == "compute"
    # intensity 20 flops/byte > balance 10 => compute-bound, agreeing
    # with the time comparison
    assert r["arithmetic_intensity"] > r["machine_balance"]


def test_roofline_degenerate_peaks_zero_not_raise():
    for pf, pb in ((0.0, 0.0), (float("nan"), float("nan")),
                   (None, None), (-1.0, 1e11)):
        r = attrib.roofline(1e9, 1e9, pf, pb)
        assert r["t_compute_s"] == 0.0 or pb == 1e11
        assert r["t_roofline_s"] >= 0.0
    r = attrib.roofline(0.0, 0.0, 1e12, 1e11)
    assert r["t_roofline_s"] == 0.0
    assert r["arithmetic_intensity"] == float("inf")


def test_mfu_hand_numbers_and_degenerates():
    assert attrib.mfu(1e12, 1.0, 1e12) == pytest.approx(1.0)
    assert attrib.mfu(5e11, 1.0, 1e12) == pytest.approx(0.5)
    assert attrib.mfu(1e12, 1.0, 1e12, n_chips=2) == pytest.approx(0.5)
    assert attrib.mfu(0.0, 1.0, 1e12) is None
    assert attrib.mfu(1e9, 0.0, 1e12) is None
    assert attrib.mfu(1e9, 1.0, float("nan")) is None
    assert attrib.mfu(1e9, 1.0, None) is None


def test_extract_cost_on_tiny_compiled():
    compiled = jax.jit(lambda x: x @ x).lower(
        jnp.ones((8, 8), jnp.float32)).compile()
    c = attrib.extract_cost(compiled)
    # 8x8x8 MACs = 1024 flops at 2/MAC; XLA counts >= the matmul
    assert c["flops"] is not None and c["flops"] >= 1024
    assert c["bytes_accessed"] is not None and c["bytes_accessed"] > 0


def test_extract_cost_never_raises_on_junk():
    class Junk:
        def cost_analysis(self):
            raise RuntimeError("no analysis")

        def memory_analysis(self):
            raise RuntimeError("no analysis")

    c = attrib.extract_cost(Junk())
    assert all(v is None for v in c.values())


# ---- the cost ledger + sampled decomposition on a real dataplane -------

def _mesh_setup(W=4, window=2, batch=4, rounds=3, **dp_kwargs):
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(4)(x)

    model = Tiny()
    tx = resolve_optimizer("momentum", 0.05)
    rule = RULES["downpour"]()
    center = model.init(jax.random.key(0),
                        jnp.ones((2, 8)))["params"]
    step = make_train_step(model, "sparse_categorical_crossentropy",
                           tx)
    placement = mesh_lib.place_workers(W)
    dp = ps_dataplane.MeshDataplane(rule, step, placement.mesh, center,
                                    **dp_kwargs)

    def make_worker(rng):
        return TrainState.create({"params": center}, tx, rng)

    mps, mws = dp.to_device(
        rule.init_state(center),
        jax.vmap(make_worker)(jax.random.split(jax.random.key(1), W)))
    row = mesh_lib.batch_sharding(placement.mesh)
    rep = mesh_lib.replicated_sharding(placement.mesh)
    rngd = np.random.RandomState(0)
    batches = [jax.device_put(
        {"features": jnp.asarray(rngd.randn(W, window, batch, 8),
                                 jnp.float32),
         "label": jnp.asarray(rngd.randint(0, 4, (W, window, batch)),
                              jnp.int32)}, row) for _ in range(rounds)]
    perm = jax.device_put(commit_permutation(jax.random.key(2), W),
                          rep)
    return dp, mps, mws, batches, perm


@pytest.mark.parametrize("kw", [{}, {"comm_dtype": "bfloat16"},
                                {"comm_codec": "int8"}])
def test_cost_ledger_one_record_per_program(kw, devices):
    tel = telemetry.enable()
    try:
        dp, mps, mws, batches, perm = _mesh_setup(**kw)
        drv = ps_dataplane.MeshRoundDriver(dp, mps, mws)
        for b in batches:
            drv.dispatch(b, perm)
        drv.drain()
        report = dp.cost_report()
        assert len(report) == 1  # one shape => ONE ledger record
        rec = report[0]
        assert rec["flops"] and rec["flops"] > 0
        assert rec["bytes_accessed"] and rec["bytes_accessed"] > 0
        assert rec["compile_s"] > 0
        assert rec["collective_bytes"] == dp.comm_bytes_per_round
        assert rec["comm_bytes_saved"] == dp.comm_bytes_saved_per_round
        assert rec["workers"] == 4
        # roofline attached against the device peaks; CPU peaks are
        # nominal, so the ledger must say the peak is NOT known
        assert rec["roofline"]["t_roofline_s"] >= 0
        assert rec["roofline"]["bound"] in ("compute", "comm")
        assert rec["peak_known"] is False
        snap = tel.metrics.snapshot()
        assert snap["counters"][
            'ps_round_compile_seconds_total{fidelity="mesh"}'] > 0
        assert snap["gauges"][
            'ps_round_program_flops{fidelity="mesh"}'] == rec["flops"]
        assert snap["gauges"][
            'ps_round_program_bytes_accessed{fidelity="mesh"}'] == \
            rec["bytes_accessed"]
    finally:
        telemetry.disable()


def test_attrib_sampling_byte_identity_and_surface(devices):
    """attrib_every=N only READS device state: the trained center is
    bitwise-identical to an attrib-off run, while the sampled rounds
    populate the segment counters + mfu gauges."""
    def run(attrib_every):
        dp, mps, mws, batches, perm = _mesh_setup()
        drv = ps_dataplane.MeshRoundDriver(dp, mps, mws,
                                           attrib_every=attrib_every)
        for b in batches:
            drv.dispatch(b, perm)
        drv.drain()
        return jax.device_get(dp.center(drv.mps)), drv

    off_center, _ = run(0)
    tel = telemetry.enable()
    try:
        on_center, drv = run(2)
        snap = tel.metrics.snapshot()
    finally:
        telemetry.disable()

    for la, lb in zip(jax.tree_util.tree_leaves(off_center),
                      jax.tree_util.tree_leaves(on_center)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    for seg in ("host_gap", "dispatch", "device_compute", "ring_fetch"):
        key = f'ps_round_attrib_seconds_total{{segment="{seg}"}}'
        assert key in snap["counters"], snap["counters"].keys()
    assert 0 < snap["gauges"]["mfu_observed"] <= 1.0
    assert 0 < snap["gauges"]["mfu_roofline"] <= 1.0
    a = drv.last_attrib
    assert a is not None
    assert a["dispatch"] >= 0 and a["ring_fetch"] >= 0
    assert a["peak_known"] is False  # CPU: nominal peaks only


def test_attrib_every_validation(devices):
    dp, mps, mws, _, _ = _mesh_setup(rounds=1)
    with pytest.raises(ValueError, match="attrib_every"):
        ps_dataplane.MeshRoundDriver(dp, mps, mws, attrib_every=-1)


def test_trainer_rejects_attrib_on_non_mesh_tier():
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import DOWNPOUR

    cfg = model_config("mlp", (8,), num_classes=4, hidden=(16,))
    with pytest.raises(ValueError, match="attrib_every"):
        DOWNPOUR(cfg, fidelity="fast", num_workers=2, batch_size=8,
                 num_epoch=1, learning_rate=0.01, attrib_every=2)


def test_attrib_disabled_overhead_within_budget():
    """The dispatch fast path's guard: generous CI bound (measured
    ~0.15-0.4 us on an idle box; PERF.md quotes the tight figure)."""
    guard = attrib.attrib_overhead(n=50_000)
    assert guard["disabled_ns"] < 5_000, guard
    assert guard["armed_unsampled_ns"] < 10_000, guard

"""Every baseline config trained through its designated trainer
(BASELINE.md config table; VERDICT.md round-1 Missing — previously only
the MLP pairing had ever executed).  Small shapes, 8-virtual-device CPU
mesh; each test asserts real convergence signal, not just shape checks.
"""

import numpy as np
import pytest

from distkeras_tpu.data import (
    AssembleTransformer,
    HashBucketTransformer,
    MinMaxTransformer,
    Pipeline,
    datasets,
)
from distkeras_tpu.evaluators import AccuracyEvaluator, evaluate_model
from distkeras_tpu.models import model_config
from distkeras_tpu.predictors import ModelPredictor
from distkeras_tpu.trainers import (
    ADAG,
    AEASGD,
    DOWNPOUR,
    DynSGD,
    SingleTrainer,
)


def _loss_drop(history, key="round_loss"):
    h = history[key]
    return h[0], h[-1]


def test_mnist_mlp_single_trainer():
    """MNIST-synth MLP + SingleTrainer (BASELINE.md row 1)."""
    data = datasets.mnist_synth(2048, seed=0)
    cfg = model_config("mlp", (28, 28, 1), num_classes=10, hidden=(64,))
    t = SingleTrainer(cfg, worker_optimizer="adam", learning_rate=3e-3,
                      batch_size=64, num_epoch=3)
    variables = t.train(data)
    first, last = _loss_drop(t.history, "epoch_loss")
    assert last < first * 0.7, t.history
    metrics = evaluate_model(t.model, variables, data, batch_size=256)
    assert metrics["accuracy"] > 0.5, metrics


def test_cifar_convnet_adag():
    """CIFAR-synth ConvNet + ADAG (BASELINE.md row 2)."""
    data = datasets.cifar10_synth(512, seed=1)
    cfg = model_config("convnet", (32, 32, 3), num_classes=10,
                       widths=(8, 16), dense=32)
    t = ADAG(cfg, num_workers=4, communication_window=2, batch_size=16,
             num_epoch=2, learning_rate=0.02, worker_optimizer="adam")
    t.train(data)
    first, last = _loss_drop(t.history)
    assert last < first * 0.9, t.history["round_loss"]


def test_imagenet_resnet18_aeasgd_faithful():
    """ImageNet-synth ResNet-18 + AEASGD, faithful fidelity, 8 workers
    (BASELINE.md row 3; VERDICT.md round-1 Weak #3 memory criterion: the
    real-width ResNet-18 must run the faithful path in CI within memory —
    the elastic rule is the heaviest, since its pull law consumes the
    workers' local params inside the commit scan)."""
    data = datasets.imagenet_synth(128, image_size=32, num_classes=10,
                                   seed=2)
    cfg = model_config("resnet", (32, 32, 3), num_classes=10,
                       stage_sizes=(2, 2, 2, 2), bottleneck=False,
                       dtype="float32")
    t = AEASGD(cfg, num_workers=8, communication_window=2, batch_size=4,
               num_epoch=2, rho=2.5, learning_rate=0.02,
               fidelity="faithful")
    t.train(data)
    first, last = _loss_drop(t.history)
    assert np.isfinite(last)
    assert last < first, t.history["round_loss"]


def test_imdb_bilstm_dynsgd():
    """IMDB-synth BiLSTM + DynSGD (BASELINE.md row 4)."""
    data = datasets.imdb_synth(1024, seq_len=32, vocab_size=200, seed=3)
    cfg = model_config("bilstm", (32,), input_dtype="int32",
                       vocab_size=200, embed_dim=16, hidden_dim=16,
                       num_classes=2)
    t = DynSGD(cfg, num_workers=4, communication_window=2, batch_size=16,
               num_epoch=3, learning_rate=0.01, worker_optimizer="adam")
    t.train(data)
    first, last = _loss_drop(t.history)
    assert last < first * 0.9, t.history["round_loss"]


def test_criteo_widedeep_end_to_end():
    """Criteo-synth Wide&Deep, full pipeline: columnar ETL (hash-bucket
    categoricals, min-max dense) -> assemble features -> DOWNPOUR train ->
    sharded predict -> AccuracyEvaluator (BASELINE.md row 5)."""
    num_cat, buckets = 6, 50
    data = datasets.criteo_synth(2048, num_dense=4,
                                 num_categorical=num_cat,
                                 vocab_size=100, seed=4)
    etl = Pipeline(
        [MinMaxTransformer("dense")]
        + [HashBucketTransformer(f"c{j}", buckets)
           for j in range(num_cat)]
        + [AssembleTransformer(
            ["dense"] + [f"c{j}_bucket" for j in range(num_cat)])])
    table = etl.fit_transform(data)
    assert table["features"].shape == (2048, 4 + num_cat)

    cfg = model_config("wide_deep", (4 + num_cat,), num_dense=4,
                       num_categorical=num_cat, vocab_size=buckets,
                       embed_dim=8, deep=(32, 16), num_classes=2)
    t = DOWNPOUR(cfg, num_workers=4, communication_window=2,
                 batch_size=32, num_epoch=3, learning_rate=0.01,
                 worker_optimizer="adam")
    variables = t.train(table)
    first, last = _loss_drop(t.history)
    assert last < first * 0.9, t.history["round_loss"]

    scored = ModelPredictor(t.model, variables, output="class",
                            batch_size=128).predict(table)
    acc = AccuracyEvaluator("prediction", "label").evaluate(scored)
    assert acc > 0.6, acc

"""Commit-payload compression (parallel/compression.py): codec
roundtrips, error-feedback conservation, and compressed host-PS
training over both transports."""

import jax
import numpy as np
import pytest

from distkeras_tpu.data import datasets
from distkeras_tpu.models import model_config
from distkeras_tpu.parallel.compression import (Bf16Codec, Int8Codec,
                                                TopKCodec, raw_nbytes,
                                                resolve_codec)
from distkeras_tpu.trainers import AEASGD, DOWNPOUR, ADAG

MLP = model_config("mlp", (8,), num_classes=4, hidden=(16,))
DATA = datasets.synthetic_classification(1024, (8,), 4, seed=0)


def _tree(seed=0):
    rng = np.random.default_rng(seed)
    return {"a": {"w": rng.normal(size=(32, 16)).astype(np.float32),
                  "b": rng.normal(size=(16,)).astype(np.float32)},
            "c": rng.normal(size=(16, 4)).astype(np.float32)}


def test_int8_roundtrip_bounded_error_and_size():
    tree = _tree()
    codec = Int8Codec()
    data, back = codec.round_trip(tree)
    for x, y in zip(jax.tree_util.tree_leaves(tree),
                    jax.tree_util.tree_leaves(back)):
        bound = np.abs(x).max() / 127.0  # half-step rounding + clip
        assert np.max(np.abs(x - y)) <= bound + 1e-7
    assert len(data) < raw_nbytes(tree) * 0.30  # ~4x smaller


def test_topk_keeps_largest_entries():
    tree = {"w": np.array([[0.1, -5.0, 0.2], [3.0, 0.0, -0.3]],
                          np.float32)}
    codec = TopKCodec(fraction=2 / 6)
    _, back = codec.round_trip(tree)
    expect = np.array([[0.0, -5.0, 0.0], [3.0, 0.0, 0.0]], np.float32)
    np.testing.assert_array_equal(back["w"], expect)
    big = _tree(1)
    data, _ = TopKCodec(0.01).round_trip(big)
    assert len(data) < raw_nbytes(big) * 0.1


def test_bf16_roundtrip_close():
    tree = _tree(2)
    data, back = Bf16Codec().round_trip(tree)
    for x, y in zip(jax.tree_util.tree_leaves(tree),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_allclose(x, y, rtol=1e-2, atol=1e-2)
    assert len(data) < raw_nbytes(tree) * 0.6


def test_resolve_codec():
    assert resolve_codec(None) is None
    assert resolve_codec("int8").name == "int8"
    assert resolve_codec("bf16").name == "bfloat16"
    assert abs(resolve_codec("topk:0.05").fraction - 0.05) < 1e-12
    c = Int8Codec()
    assert resolve_codec(c) is c
    with pytest.raises(KeyError):
        resolve_codec("zip")
    with pytest.raises(ValueError):
        TopKCodec(0.0)


def test_error_feedback_conserves_total_delta():
    """Transmitted sum + final residual == true delta sum: nothing the
    codec dropped is ever lost, it just arrives later."""
    from distkeras_tpu.utils import tree_add, tree_sub, tree_zeros_like

    codec = TopKCodec(0.1)
    deltas = [_tree(s) for s in range(5)]
    residual = tree_zeros_like(deltas[0])
    transmitted = tree_zeros_like(deltas[0])
    for d in deltas:
        total = tree_add(d, residual)
        _, applied = codec.round_trip(total)
        transmitted = tree_add(transmitted, applied)
        residual = tree_sub(total, applied)
    true_sum = deltas[0]
    for d in deltas[1:]:
        true_sum = tree_add(true_sum, d)
    recovered = tree_add(transmitted, residual)
    for a, b in zip(jax.tree_util.tree_leaves(true_sum),
                    jax.tree_util.tree_leaves(recovered)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("transport,codec", [
    ("inprocess", "int8"),
    ("socket", "topk:0.25"),
])
def test_compressed_host_training_converges(transport, codec):
    t = DOWNPOUR(MLP, fidelity="host", transport=transport,
                 num_workers=4, communication_window=2, batch_size=16,
                 num_epoch=3, learning_rate=0.05, compression=codec)
    t.train(DATA)
    losses = t.history["epoch_loss"]
    assert losses[-1] < losses[0] * 0.8, losses
    wire, raw = (t.history["commit_wire_bytes"][-1],
                 t.history["commit_raw_bytes"][-1])
    assert raw > 0 and wire < raw * 0.6, (wire, raw)


def test_compressed_matches_uncompressed_closely():
    """int8 + error feedback lands near the uncompressed optimum on
    the same data/budget (not bitwise — staleness also races)."""
    kwargs = dict(fidelity="host", num_workers=2,
                  communication_window=2, batch_size=16, num_epoch=3,
                  learning_rate=0.05, seed=7)
    plain = ADAG(MLP, **kwargs)
    plain.train(DATA)
    comp = ADAG(MLP, compression="int8", **kwargs)
    comp.train(DATA)
    assert (comp.history["epoch_loss"][-1]
            < plain.history["epoch_loss"][-1] * 1.25)


def test_compression_rejected_where_unsupported():
    with pytest.raises(ValueError, match="fidelity='host'"):
        DOWNPOUR(MLP, compression="int8")  # emulated fidelity
    with pytest.raises(ValueError, match="delta-family"):
        AEASGD(MLP, fidelity="host", compression="int8",
               num_workers=2).train(DATA)


def test_ack_lost_retry_resends_identical_bytes(monkeypatch):
    """A commit whose ack is lost AFTER the server applied it must be
    retried with byte-identical payload (cached encode) so the seq
    dedupe + residual bookkeeping stay consistent."""
    from distkeras_tpu.parallel import host_ps as hp

    real_commit = hp.PSClient.commit
    seen: dict[int, list[bytes]] = {}

    def flaky(self, payload, local=None, seq=None):
        seen.setdefault(seq, []).append(bytes(payload))
        out = real_commit(self, payload, local, seq=seq)
        if seq == 1 and len(seen[1]) == 1:
            raise ConnectionError("ack lost after apply")
        return out

    monkeypatch.setattr(hp.PSClient, "commit", flaky)
    t = DOWNPOUR(MLP, fidelity="host", transport="socket",
                 num_workers=1, communication_window=2, batch_size=16,
                 num_epoch=1, learning_rate=0.05, compression="int8",
                 worker_retries=2)
    t.train(DATA)
    # the retry happened and resent the exact same encoded bytes
    assert t.history.get("worker_round_retries")
    assert len(seen[1]) == 2 and seen[1][0] == seen[1][1]
    # at-most-once: 64 batches / window 2 = 32 windows, each applied
    # exactly once despite the repeat
    assert t.parameter_server_state.num_commits == 32


def test_psclient_tree_payload_on_codec_connection():
    """Direct PSClient users may pass a pytree on a codec connection;
    it is encoded client-side (no error feedback — that is the
    trainer loop's job)."""
    import numpy as np

    from distkeras_tpu.parallel.host_ps import (HostParameterServer,
                                                PSClient, PSServer)
    from distkeras_tpu.parallel.update_rules import DownpourRule

    center = {"w": np.zeros(4, np.float32)}
    ps = HostParameterServer(DownpourRule(), center)
    with PSServer(ps, center) as server:
        c = PSClient(*server.address, worker_id=0, template=center,
                     codec="int8")
        c.pull()
        pulled = c.commit({"w": np.full(4, 0.5, np.float32)})
        np.testing.assert_allclose(np.asarray(pulled["w"]),
                                   0.5, rtol=0.02)
        c.done()
        c.close()


def test_bad_compression_spec_fails_at_construction():
    with pytest.raises(KeyError):
        DOWNPOUR(MLP, fidelity="host", compression="int-8")


def test_custom_codec_rejected_on_socket_accepted_inprocess():
    class Doubling(Int8Codec):  # shadows the built-in name
        def encode_leaf(self, x):
            return super().encode_leaf(2 * x)

    kwargs = dict(fidelity="host", num_workers=2,
                  communication_window=2, batch_size=16, num_epoch=1,
                  learning_rate=0.05, compression=Doubling())
    with pytest.raises(ValueError, match="reconstructed server-side"):
        from distkeras_tpu.parallel.host_ps import PSClient

        import numpy as np

        from distkeras_tpu.parallel.host_ps import (HostParameterServer,
                                                    PSServer)
        from distkeras_tpu.parallel.update_rules import DownpourRule

        center = {"w": np.zeros(2, np.float32)}
        ps = HostParameterServer(DownpourRule(), center)
        with PSServer(ps, center) as server:
            PSClient(*server.address, worker_id=0, template=center,
                     codec=Doubling())
    # in-process: no wire, the custom codec is applied client-side
    t = DOWNPOUR(MLP, transport="inprocess", **kwargs)
    t.train(DATA)
    assert np.isfinite(t.history["epoch_loss"]).all()

"""Native columnar kernels: build, exact/close parity with the numpy
fallback paths, and the transformer fast/fallback switch."""

import numpy as np
import pytest

from distkeras_tpu import native
from distkeras_tpu.data import datasets
from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.data.transformers import (
    DenseTransformer,
    HashBucketTransformer,
    MinMaxTransformer,
    StandardScaleTransformer,
)

needs_native = pytest.mark.skipif(
    not native.available(),
    reason=f"native kernels unavailable: {native.why_unavailable()}")


@needs_native
def test_fnv1a_bucket_matches_scalar_reference():
    values = np.array(["cat_1", "", "a", "longer_categorical_value_42",
                       "cat_1", "ünïcode"], dtype=object)
    s = np.char.encode(values.astype(str), "utf-8")
    got = native.fnv1a_bucket(s, np.char.str_len(s), 1000)
    want = [HashBucketTransformer._fnv1a(str(v).encode("utf-8")) % 1000
            for v in values]
    np.testing.assert_array_equal(got, want)
    assert got[0] == got[4]  # deterministic


@needs_native
def test_affine_scale_matches_numpy():
    rng = np.random.default_rng(0)
    col = rng.normal(size=(257, 5)).astype(np.float32)
    scale = rng.uniform(0.5, 2.0, size=5)
    shift = rng.normal(size=5)
    got = native.affine_scale(col, scale, shift)
    want = (col.astype(np.float64) * scale + shift).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
    # 1-D column with scalar stats
    col1 = rng.normal(size=64).astype(np.float32)
    got1 = native.affine_scale(col1, 2.0, -1.0)
    np.testing.assert_allclose(got1, col1 * 2.0 - 1.0, rtol=1e-6)


@needs_native
def test_dense_scatter_matches_numpy():
    idx = np.array([[0, 3, -1], [2, -1, -1], [1, 2, 3]], np.int64)
    val = np.array([[1., 2., 9.], [5., 9., 9.], [7., 8., 9.]],
                   np.float32)
    got = native.dense_scatter(idx, val, 4)
    want = np.array([[1, 0, 0, 2], [0, 0, 5, 0], [0, 7, 8, 9]],
                    np.float32)
    np.testing.assert_array_equal(got, want)


def _fallback(monkeypatch):
    monkeypatch.setattr(native, "available", lambda: False)


@needs_native
def test_transformers_native_equals_fallback(monkeypatch):
    """The Criteo ETL surface produces identical tables through the
    native and numpy paths."""
    data = datasets.criteo_synth(512, num_dense=4, num_categorical=3,
                                 vocab_size=50, seed=0)
    hb = HashBucketTransformer("c0", 37)
    mm = MinMaxTransformer("dense")
    ss = StandardScaleTransformer("dense", output_col="dense_std")

    fast = ss.fit_transform(mm.fit_transform(hb.transform(data)))
    _fallback(monkeypatch)
    slow = ss.fit_transform(mm.fit_transform(hb.transform(data)))

    np.testing.assert_array_equal(fast["c0_bucket"], slow["c0_bucket"])
    np.testing.assert_allclose(fast["dense"], slow["dense"],
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(fast["dense_std"], slow["dense_std"],
                               rtol=1e-6, atol=1e-6)


@needs_native
def test_dense_transformer_native_equals_fallback(monkeypatch):
    rng = np.random.default_rng(1)
    idx = rng.integers(-1, 16, size=(128, 6))
    val = rng.normal(size=(128, 6)).astype(np.float32)
    ds = Dataset({"indices": idx, "values": val})
    t = DenseTransformer("indices", "values", dim=16)
    fast = t.transform(ds)["features"]
    _fallback(monkeypatch)
    slow = t.transform(ds)["features"]
    np.testing.assert_array_equal(fast, slow)


@needs_native
def test_csv_native_equals_python_path(monkeypatch, tmp_path):
    """The C csv lane must type and value every column exactly like
    the Python csv.reader path — int64/float32/string inference, blank
    lines, CRLF, whitespace, empty cells, hex/underscore strictness,
    int64 overflow fallback, and a file without a trailing newline."""
    body = ("a,b,c,d,e\n"
            "1,1.5,tok_1,3,99999999999999999999\n"
            "2,2.5,tok_22,,12\n"
            "\n"
            "-3, -7 ,x9,0x1A,-4\n"
            "4,8e2,t,nan,1")
    plain = tmp_path / "plain.csv"
    plain.write_text(body)
    crlf = tmp_path / "crlf.csv"
    crlf.write_bytes(body.replace("\n", "\r\n").encode() + b"\r\n")

    for p in (plain, crlf):
        fast = Dataset.from_csv(str(p))
        monkeypatch.setattr(native, "available", lambda: False)
        slow = Dataset.from_csv(str(p))
        monkeypatch.undo()
        assert fast.column_names == slow.column_names
        for k in fast.column_names:
            assert fast[k].dtype == slow[k].dtype, k
            np.testing.assert_array_equal(fast[k], slow[k])
    # spot-check the inferred types themselves
    d = Dataset.from_csv(str(plain))
    assert d["a"].dtype == np.int64
    assert d["b"].dtype == np.float32
    assert d["c"].dtype.kind == "U"
    assert d["d"].dtype.kind == "U"      # '', '0x1A', 'nan' mix
    assert d["e"].dtype == np.float32    # int64 overflow -> float


@needs_native
def test_csv_quoted_fields_route_to_python_lane(tmp_path):
    """The C tokenizer is plain-split; any quote character sends the
    whole file down the csv.reader lane so quoted fields (incl. ones
    containing the delimiter) parse identically with or without the
    native toolchain."""
    p = tmp_path / "q.csv"
    p.write_text('a,b\n1,"x,y"\n2,"plain"\n')
    d = Dataset.from_csv(str(p))  # native available, must not be used
    assert d["b"].tolist() == ["x,y", "plain"]
    assert d["a"].dtype == np.int64


@needs_native
def test_csv_native_errors_match(tmp_path):
    ragged = tmp_path / "r.csv"
    ragged.write_text("a,b\n1,2\n3\n")
    with pytest.raises(ValueError, match="fields"):
        Dataset.from_csv(str(ragged))
    hdr_only = tmp_path / "h.csv"
    hdr_only.write_text("a,b\n")
    with pytest.raises(ValueError, match="no data rows"):
        Dataset.from_csv(str(hdr_only))


def test_everything_works_without_native(monkeypatch):
    """The whole ETL surface must be fully functional with the native
    path disabled (environments without a toolchain)."""
    _fallback(monkeypatch)
    data = datasets.criteo_synth(256, num_dense=3, num_categorical=2,
                                 vocab_size=20, seed=1)
    out = MinMaxTransformer("dense").fit_transform(
        HashBucketTransformer("c0", 10).transform(data))
    assert out["c0_bucket"].dtype == np.int32
    assert out["dense"].min() >= 0.0 and out["dense"].max() <= 1.0

"""Chaos transport (``parallel.faults.ChaosTransport``): deterministic
seed-scheduled fault injection over the REAL socket path — unit
behavior per fault class, schedule determinism, and the end-to-end
sweep: async SOCKET training completes within its retry budget and
stays exactly-once under every injected fault class (the ISSUE 3
acceptance scenario)."""

import socket
import threading
import time

import jax
import numpy as np
import pytest

from distkeras_tpu.analysis import racecheck
from distkeras_tpu.data import datasets
from distkeras_tpu.models import model_config
from distkeras_tpu.parallel import transport
from distkeras_tpu.parallel.faults import ChaosTransport
from distkeras_tpu.trainers import DOWNPOUR

jax.config.update("jax_platforms", "cpu")

MLP = model_config("mlp", (8,), num_classes=4, hidden=(16,))
DATA = datasets.synthetic_classification(1024, (8,), 4, seed=0)


@pytest.fixture(autouse=True)
def _racecheck():
    """Run the whole chaos suite under the lockset race + deadlock
    detector: every lock built during a test is instrumented, and any
    report (race, order cycle, deadlock) fails the test."""
    racecheck.enable()
    yield
    reports = racecheck.disable()
    assert not reports, "\n".join(str(r) for r in reports)


def test_schedule_is_a_pure_function_of_the_seed():
    """The k-th operation draws the same fault decision on every run
    with the same seed — and a different one under a different seed."""
    kw = dict(reset_rate=0.2, truncate_rate=0.15, delay_rate=0.1,
              delay_s=0.0)
    ops = (["send", "recv", "connect"] * 30)[:80]
    a = ChaosTransport(seed=3, **kw)
    b = ChaosTransport(seed=3, **kw)
    da = [a._draw(k) for k in ops]
    assert da == [b._draw(k) for k in ops]
    assert a.counts == b.counts and a.total_injected > 0
    c = ChaosTransport(seed=4, **kw)
    assert da != [c._draw(k) for k in ops]


def test_recurring_partition_schedule_is_pure_and_periodic():
    """ISSUE 10 satellite: ``partition_every`` makes the partition
    RECURRING — a fresh window of ``partition_ops`` connect-refusals
    opens on that cadence — and the schedule stays a pure function of
    the op index: two instances agree draw for draw, and the windows
    land exactly where the arithmetic says."""
    kw = dict(partition_at=2, partition_ops=2, partition_every=6)
    ops = ["connect"] * 26
    a = ChaosTransport(seed=3, **kw)
    b = ChaosTransport(seed=3, **kw)
    da = [a._draw(k) for k in ops]
    assert da == [b._draw(k) for k in ops]
    hits = [i for i, d in enumerate(da) if d == "partition"]
    assert hits == [2, 3, 8, 9, 14, 15, 20, 21]
    assert a.counts["partition"] == len(hits)
    with pytest.raises(ValueError, match="partition_every"):
        ChaosTransport(seed=0, partition_at=0, partition_ops=4,
                       partition_every=3)


def test_partition_ports_scopes_the_cut():
    """``partition_ports`` turns the partition into a DIRECTED cut:
    connects to the named peer ports are refused inside the window,
    every other destination sails through — so a test can sever the
    worker->primary edge while the replication stream stays up."""
    ct = ChaosTransport(seed=0, partition_at=0, partition_ops=100,
                        partition_ports={5001})
    assert ct._draw("connect", port=5001) == "partition"
    assert ct._draw("connect", port=5002) is None
    assert ct._draw("connect", port=5001) == "partition"
    assert ct.counts["partition"] == 2


def test_install_is_scoped_and_exclusive():
    orig = (transport.connect, transport.send_msg, transport.recv_msg)
    with ChaosTransport(seed=0) as ct:
        assert getattr(transport.send_msg, "__self__", None) is ct
        with pytest.raises(RuntimeError, match="already installed"):
            ct.install()
    assert (transport.connect, transport.send_msg,
            transport.recv_msg) == orig


def test_reset_fault_and_injection_cap():
    """A scheduled reset closes the socket and raises before the wire
    is touched; ``max_injections`` caps the disruptive faults so a
    seeded run provably fits a retry budget."""
    with ChaosTransport(seed=0, reset_rate=1.0, max_injections=2) as ct:
        for _ in range(2):
            a, b = socket.socketpair()
            with pytest.raises(ConnectionResetError, match="chaos"):
                transport.send_msg(a, b"payload")
            b.close()
        # budget spent: operations are clean again
        a, b = socket.socketpair()
        transport.send_msg(a, b"payload")
        assert transport.recv_msg(b) == b"payload"
        a.close()
        b.close()
    assert ct.counts["reset"] == 2 and ct.total_injected == 2


def test_truncate_sends_a_strict_prefix():
    """The lost-ack wire shape: the sender dies mid-frame — the
    receiver sees a framing error (peer closed mid-message), never a
    short silent message."""
    with ChaosTransport(seed=1, truncate_rate=1.0,
                        max_injections=1) as ct:
        a, b = socket.socketpair()
        with pytest.raises(ConnectionError, match="truncated"):
            transport.send_msg(a, b"c", b"x" * 50_000)
        with pytest.raises((ConnectionError, ValueError)):
            transport.recv_msg(b)
        b.close()
    assert ct.counts["truncate"] == 1


def test_delay_fault_stalls_the_operation():
    with ChaosTransport(seed=2, delay_rate=1.0, delay_s=0.15) as ct:
        a, b = socket.socketpair()
        t0 = time.perf_counter()
        transport.send_msg(a, b"x")
        assert time.perf_counter() - t0 >= 0.15
        a.close()
        b.close()
    assert ct.counts["delay"] >= 1


def test_partition_window_refuses_connects_then_heals():
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen()
    accepted = []

    def accept_loop():
        srv.settimeout(2.0)
        try:
            while True:
                conn, _ = srv.accept()
                accepted.append(conn)
        except OSError:
            pass

    t = threading.Thread(target=accept_loop, daemon=True)
    t.start()
    try:
        with ChaosTransport(seed=0, partition_at=0,
                            partition_ops=2) as ct:
            for _ in range(2):
                with pytest.raises(ConnectionRefusedError,
                                   match="partitioned"):
                    transport.connect(*srv.getsockname())
            # the window is one-shot: the link heals
            sock = transport.connect(*srv.getsockname(), timeout=2.0)
            sock.close()
        assert ct.counts["partition"] == 2
    finally:
        srv.close()
        t.join()
        for c in accepted:
            c.close()


# ---- the end-to-end recovery sweep -----------------------------------

# every entry sets skip_ops itself: the partition class MUST cover the
# startup connects (op 0) — its recovery path is reconnect-with-backoff
# — while the rate classes skip the handshake to fault established
# exchanges instead
SWEEP = {
    "reset": dict(reset_rate=0.2, max_injections=4, skip_ops=4),
    "truncate": dict(truncate_rate=0.2, max_injections=4, skip_ops=4),
    "delay": dict(delay_rate=0.15, delay_s=0.02, skip_ops=4),
    "partition": dict(partition_at=0, partition_ops=4),
}


@pytest.mark.parametrize("fault", sorted(SWEEP))
def test_chaos_sweep_completes_within_budget_exactly_once(fault):
    """Seed-pinned chaos over the real socket transport: async
    training finishes inside the workers' retry budget for every fault
    class, the loss stays sane, and — the at-most-once proof — the
    number of APPLIED commits equals the number of completed rounds
    (a lost-ack retry under chaos is deduped, never double-applied)."""
    with ChaosTransport(seed=11, **SWEEP[fault]) as ct:
        t = DOWNPOUR(MLP, fidelity="host", transport="socket",
                     num_workers=2, communication_window=2,
                     batch_size=16, num_epoch=1, learning_rate=0.01,
                     worker_optimizer="adam", worker_retries=10)
        t.train(DATA)
    assert ct.counts[fault] > 0, ct.counts  # the class really fired
    assert "worker_failures" not in t.history  # budget held
    h = t.history["epoch_loss"]
    assert np.isfinite(h).all(), h
    # exactly-once under chaos: every completed round committed once
    assert t.parameter_server_state.num_commits == \
        len(t.history["round_loss"])
    if fault != "delay":  # delays cost time, not retries
        assert t.history.get("worker_round_retries"), (
            "disruptive chaos left no retry trace")


@pytest.mark.parametrize("fault", sorted(SWEEP))
def test_chaos_sweep_against_sharded_server(fault):
    """The same seeded sweep with the SHARDED PS (ISSUE 4 acceptance):
    the shard-addressed scatter-gather wire crosses the same chaos
    choke point, a logical commit's seq dedupes per shard, and
    at-most-once holds — applied logical commits == completed rounds
    even when a failure lands between two shard commits."""
    with ChaosTransport(seed=11, **SWEEP[fault]) as ct:
        t = DOWNPOUR(MLP, fidelity="host", transport="socket",
                     ps_shards=2, num_workers=2,
                     communication_window=2, batch_size=16,
                     num_epoch=1, learning_rate=0.01,
                     worker_optimizer="adam", worker_retries=10)
        t.train(DATA)
    assert ct.counts[fault] > 0, ct.counts
    assert "worker_failures" not in t.history
    assert np.isfinite(t.history["epoch_loss"]).all()
    ps = t.parameter_server_state
    assert ps.num_commits == len(t.history["round_loss"])
    # every shard saw every logical commit exactly once
    assert [s.num_commits for s in ps._shards] == \
        [ps.num_commits] * ps.num_shards


@pytest.mark.parametrize("fault", sorted(SWEEP))
def test_chaos_sweep_against_replicated_server(fault):
    """The same seeded sweep with the REPLICATED PS (ISSUE 10): the
    chaos choke point now also sits under the primary->standby
    replication stream, whose seq-gated appends are idempotent — so
    every fault class leaves the run exactly-once, with no spurious
    failover (the election timeout is generous against transient
    faults) and the standby byte-identical to the primary."""
    import numpy as _np

    from distkeras_tpu.models import ModelSpec
    from distkeras_tpu.parallel.replicated_ps import make_replica_group
    from distkeras_tpu.parallel.update_rules import DownpourRule

    model = ModelSpec.from_config(MLP).build()
    variables = model.init(jax.random.key(0),
                           _np.zeros((1, 8), _np.float32))
    center = jax.tree_util.tree_map(_np.asarray, variables["params"])
    nodes = make_replica_group(DownpourRule(), center, replicas=2,
                               failover_timeout=5.0)
    try:
        with ChaosTransport(seed=11, **SWEEP[fault]) as ct:
            t = DOWNPOUR(MLP, fidelity="host", transport="socket",
                         num_workers=2, communication_window=2,
                         batch_size=16, num_epoch=1,
                         learning_rate=0.01, worker_optimizer="adam",
                         worker_retries=10,
                         ps_replicas=[n.worker_address
                                      for n in nodes])
            t.train(DATA, initial_variables=variables)
        assert ct.counts[fault] > 0, ct.counts
        assert "worker_failures" not in t.history
        assert np.isfinite(t.history["epoch_loss"]).all()
        # exactly-once AND no spurious takeover under transient chaos
        assert nodes[0].role == "primary"
        assert t.history["ps_epoch"][-1] == 2
        assert nodes[0].ps.num_commits == \
            len(t.history["round_loss"])
        # the standby replayed the identical log (a chaos-downed link
        # revives on the heartbeat cadence — give catch-up a moment)
        deadline = time.perf_counter() + 10.0
        while (nodes[1].last_applied < nodes[0].ps.num_commits
               and time.perf_counter() < deadline):
            time.sleep(0.05)
        assert nodes[1].last_applied == nodes[0].ps.num_commits
        for a, b in zip(
                jax.tree_util.tree_leaves(nodes[0].ps.center),
                jax.tree_util.tree_leaves(nodes[1].ps.center)):
            _np.testing.assert_array_equal(a, b)
    finally:
        for n in nodes:
            n.stop()


def test_uninstall_is_idempotent_and_stack_safe():
    """ISSUE 6 satellite: ``uninstall`` twice is a no-op (nested
    harnesses' finally paths may both fire), never-installed instances
    uninstall harmlessly, and a stale instance whose wrappers were
    already replaced by a LATER injector restores NOTHING — only a
    LIFO unstack walks the bindings back to the true originals."""
    orig = (transport.connect, transport.send_msg, transport.recv_msg,
            transport.send_msg_gather, transport.recv_msg_into)

    def bindings():
        return (transport.connect, transport.send_msg,
                transport.recv_msg, transport.send_msg_gather,
                transport.recv_msg_into)

    # double uninstall: the second call is a no-op, not a clobber
    a = ChaosTransport(seed=0)
    a.install()
    a.uninstall()
    a.uninstall()
    assert bindings() == orig
    # uninstall without install is equally harmless
    ChaosTransport(seed=1).uninstall()
    assert bindings() == orig

    # a full reinstall cycle still works after the double-uninstall
    with ChaosTransport(seed=2) as c:
        assert transport.send_msg.__self__ is c
    assert bindings() == orig

    # LIFO stack: B on top of A; unstacking in reverse order restores
    # first A's wrappers, then the originals
    a, b = ChaosTransport(seed=3), ChaosTransport(seed=4)
    a.install()
    b.install()
    assert transport.send_msg.__self__ is b
    b.uninstall()
    assert transport.send_msg.__self__ is a
    a.uninstall()
    assert bindings() == orig

    # OUT-OF-ORDER unstack: A.uninstall while B is stacked on top must
    # not clobber B's live wrappers with A's stale snapshot
    a, b = ChaosTransport(seed=5), ChaosTransport(seed=6)
    a.install()
    b.install()
    a.uninstall()
    assert transport.send_msg.__self__ is b, (
        "stale uninstall clobbered the newer injector's bindings")
    b.uninstall()
    # B's snapshot was A's wrappers; A is already spent, so walk the
    # bindings home by hand (A keeps _orig for still-blocked threads,
    # and its wrappers delegate to the originals meanwhile)
    assert transport.send_msg.__self__ is a
    a._installed = True
    a.uninstall()
    assert bindings() == orig


def test_target_ports_scopes_firing_but_not_the_schedule():
    """ISSUE 7 satellite: ``target_ports`` restricts which hops a
    fault can FIRE on — the serving gateway's replica wire vs the PS
    exchange in one process — while the rng is still consumed on
    every op, so the schedule stays a pure function of (seed, op
    index) regardless of what traffic interleaves."""
    # 1) a non-targeted peer is never faulted, even at rate 1.0
    #    (socketpair peers have no TCP port -> unattributable -> safe)
    with ChaosTransport(seed=0, reset_rate=1.0,
                        target_ports={9999}) as ct:
        a, b = socket.socketpair()
        transport.send_msg(a, b"payload")
        assert transport.recv_msg(b) == b"payload"
        a.close()
        b.close()
    assert ct.total_injected == 0

    # 2) the targeted port DOES fire
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen()
    port = srv.getsockname()[1]
    try:
        with ChaosTransport(seed=0, reset_rate=1.0, max_injections=1,
                            target_ports={port}) as ct:
            sock = socket.create_connection(("127.0.0.1", port))
            with pytest.raises(ConnectionResetError, match="chaos"):
                transport.send_msg(sock, b"x")
        assert ct.counts["reset"] == 1
    finally:
        srv.close()

    # 3) schedule purity: the k-th op draws the same decision whether
    #    or not non-targeted ops were interleaved and filtered out
    ref = ChaosTransport(seed=7, reset_rate=0.3)
    want = [ref._draw("send", port=1234) for _ in range(60)]
    mixed = ChaosTransport(seed=7, reset_rate=0.3,
                           target_ports={1234})
    got = [mixed._draw("send", port=1234 if k % 2 == 0 else 5678)
           for k in range(60)]
    assert all(g is None for g in got[1::2])  # off-target never fires
    assert got[0::2] == want[0::2]  # same stream at the same indices


# ---- wall-clock fault windows (ISSUE 18) -------------------------------


def test_wall_clock_window_fires_only_inside_and_is_pure():
    """``windows=[(t0, t1, kinds)]`` composes a wall-clock phase onto
    the op-counter schedule: silent outside [t0, t1), only the listed
    kinds inside — and with an injectable clock the whole composite
    stays a pure function of the seed (two same-seed instances agree
    decision for decision along the same clock path)."""
    clk = {"t": 0.0}
    kw = dict(reset_rate=0.0, truncate_rate=0.0, delay_rate=0.0,
              delay_s=0.0, windows=((1.0, 2.0, ("reset", "delay")),),
              window_rate=1.0, clock=lambda: clk["t"])
    a = ChaosTransport(seed=5, **kw)
    b = ChaosTransport(seed=5, **kw)
    clk["t"] = 0.5  # before the window: nothing fires
    assert [a._draw("send") for _ in range(10)] == [None] * 10
    clk["t"] = 1.5  # inside, window_rate=1.0: every op fires
    da = [a._draw(k) for k in ["send", "recv"] * 10]
    assert set(da) == {"reset", "delay"}  # only the window's kinds
    clk["t"] = 2.5  # past the end: silent again
    assert [a._draw("send") for _ in range(10)] == [None] * 10
    # purity: b replayed along the same clock path agrees exactly
    clk["t"] = 0.5
    assert [b._draw("send") for _ in range(10)] == [None] * 10
    clk["t"] = 1.5
    assert [b._draw(k) for k in ["send", "recv"] * 10] == da
    assert a.counts == b.counts and a.counts["reset"] > 0


def test_window_stream_leaves_the_base_schedule_untouched():
    """Regression: configuring windows must not perturb the base
    op-counter schedule — the window draws come from a SEPARATE rng
    stream, consumed only inside an active window."""
    kw = dict(reset_rate=0.2, truncate_rate=0.15, delay_rate=0.1,
              delay_s=0.0)
    ops = (["send", "recv", "connect"] * 30)[:80]
    plain = ChaosTransport(seed=3, **kw)
    armed = ChaosTransport(seed=3, windows=((1e9, 2e9, "reset"),),
                           clock=lambda: 0.0, **kw)
    assert ([plain._draw(k) for k in ops]
            == [armed._draw(k) for k in ops])
    assert plain.counts == armed.counts


def test_window_partition_refuses_connects_deterministically():
    """A ``partition`` window needs no rng: every connect inside it is
    refused, sends pass (partition cuts links, not payloads)."""
    clk = {"t": 1.5}
    ct = ChaosTransport(seed=0, windows=((1.0, 2.0, "partition"),),
                        clock=lambda: clk["t"])
    assert all(ct._draw("connect") == "partition" for _ in range(5))
    assert ct._draw("send") is None
    clk["t"] = 3.0  # healed
    assert ct._draw("connect") is None
    assert ct.counts["partition"] == 5


def test_window_reset_shares_the_injection_budget():
    clk = {"t": 0.5}
    ct = ChaosTransport(seed=1, reset_rate=0.0, truncate_rate=0.0,
                        delay_rate=0.0,
                        windows=((0.0, 10.0, "reset"),),
                        window_rate=1.0, max_injections=3,
                        clock=lambda: clk["t"])
    fired = [ct._draw("send") for _ in range(20)]
    assert fired.count("reset") == 3  # capped by the shared budget
    assert ct.total_injected == 3


def test_window_validation():
    from distkeras_tpu.parallel.faults import _validate_windows

    for bad in (((2.0, 1.0, "reset"),),      # end before start
                ((-1.0, 1.0, "reset"),),     # negative start
                ((0.0, 1.0, ()),),           # no kinds
                ((0.0, 1.0, "bogus"),)):     # unknown kind
        with pytest.raises(ValueError):
            _validate_windows(bad)
    ws = _validate_windows(((0.0, 1.0, "reset"),))  # bare kind ok
    assert ws[0][2] == frozenset({"reset"})
    with pytest.raises(ValueError):
        ChaosTransport(seed=0, window_rate=1.5)

"""Serving gateway (``distkeras_tpu.gateway``): routing policies,
failover, exactly-once delivery under chaos, and rolling weight
updates from the PS — the ISSUE 7 acceptance scenarios.

The correctness bar everywhere is the engine's own: a request routed
through the gateway (in-process or over the socket arm, through
kills and retries) must produce the same greedy tokens as a solo
``DecodeEngine`` run, exactly once."""

import threading
import time

import jax
import numpy as np
import pytest

from distkeras_tpu import flight_recorder, telemetry
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.gateway import (EngineReplica, RemoteReplica,
                                   ReplicaDown, ReplicaServer,
                                   ServingGateway)
from distkeras_tpu.models import ModelSpec, generate, model_config
from distkeras_tpu.parallel.faults import ChaosTransport
from distkeras_tpu.parallel.host_ps import HostParameterServer
from distkeras_tpu.parallel.update_rules import DownpourRule
from distkeras_tpu.serving import DecodeEngine

jax.config.update("jax_platforms", "cpu")


@pytest.fixture(autouse=True)
def _racecheck():
    """Gateway/replica locks are racecheck factories: run the whole
    suite instrumented and fail on any race/order/deadlock report."""
    racecheck.enable()
    yield
    reports = racecheck.disable()
    assert not reports, "\n".join(str(r) for r in reports)


MAXLEN, VOCAB = 32, 37


def _model():
    spec = model_config("transformer_lm", (MAXLEN,),
                        input_dtype="int32", vocab_size=VOCAB,
                        num_layers=1, d_model=32, num_heads=2,
                        max_len=MAXLEN, dtype="float32")
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           np.zeros((2, MAXLEN), np.int32))
    return model, variables


def _prompts(lengths, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, (t,)).astype(np.int32)
            for t in lengths]


def _want(model, variables, prompt, n_new):
    return np.asarray(generate(model, variables, prompt[None, :],
                               max_new_tokens=n_new))[0, len(prompt):]


def _engine(model, variables, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("prefill_align", 4)
    kw.setdefault("max_new_tokens", 5)
    return DecodeEngine(model, variables, **kw)


@pytest.fixture
def flight(tmp_path):
    fr = flight_recorder.start(tmp_path / "fdr")
    yield fr
    flight_recorder.stop()


# ---- routing (stub replicas: policy logic, not decode) ----------------


class _FakeReplica:
    def __init__(self, name, load=0, alive=True, fail_first=0):
        self.name = name
        self._load = load
        self.alive = alive
        self.fail_first = fail_first
        self.dispatched: list = []

    def start(self):
        return self

    def load(self):
        return self._load

    def dispatch(self, spec, on_result):
        self.dispatched.append(spec["request_id"])
        if self.fail_first > 0:
            self.fail_first -= 1
            raise ReplicaDown(f"{self.name} injected failure")
        on_result({"request_id": spec["request_id"],
                   "prompt": spec["prompt"],
                   "tokens": np.asarray([1], np.int32)})

    def health(self):
        return {"alive": self.alive, "state": "ok",
                "load": self._load}


def test_round_robin_spreads_evenly():
    reps = [_FakeReplica(f"r{i}") for i in range(3)]
    with ServingGateway(reps, policy="round_robin") as gw:
        for r in [gw.submit([1, 2]) for _ in range(9)]:
            gw.result(r, timeout=5)
    assert [len(r.dispatched) for r in reps] == [3, 3, 3]


def test_least_loaded_prefers_the_idle_replica():
    reps = [_FakeReplica("a", load=5), _FakeReplica("b", load=0),
            _FakeReplica("c", load=3)]
    with ServingGateway(reps, policy="least_loaded") as gw:
        for r in [gw.submit([1, 2]) for _ in range(6)]:
            gw.result(r, timeout=5)
    assert len(reps[1].dispatched) == 6  # fake loads never change


def test_session_affinity_is_sticky_and_spreads_keys():
    reps = [_FakeReplica(f"r{i}") for i in range(3)]
    with ServingGateway(reps, policy="session") as gw:
        for key in ("alpha", "beta", "gamma", "delta"):
            for _ in range(4):
                gw.result(gw.submit([1], session=key), timeout=5)
    # every key landed on exactly one replica...
    total = 0
    for r in reps:
        total += len(r.dispatched)
        assert len(r.dispatched) % 4 == 0  # whole keys, never split
    assert total == 16
    # ...and the keys did not all collapse onto one replica
    assert sum(1 for r in reps if r.dispatched) >= 2, (
        [len(r.dispatched) for r in reps])


def test_prefix_affinity_routes_shared_heads_together():
    """ISSUE 8: requests sharing their first ``prefix_block`` tokens
    land on ONE replica (whose prefix store is warm); distinct heads
    spread, and a tail difference beyond the head does not split a
    group."""
    reps = [_FakeReplica(f"r{i}") for i in range(3)]
    rng = np.random.default_rng(13)
    heads = [rng.integers(0, 37, (8,)).astype(np.int32)
             for _ in range(5)]
    with ServingGateway(reps, policy="prefix", prefix_block=8) as gw:
        for head in heads:
            for _ in range(3):  # same head, different tails
                tail = rng.integers(0, 37, (4,)).astype(np.int32)
                gw.result(gw.submit(np.concatenate([head, tail])),
                          timeout=5)
    total = 0
    for r in reps:
        total += len(r.dispatched)
        assert len(r.dispatched) % 3 == 0  # whole groups, never split
    assert total == 15
    assert sum(1 for r in reps if r.dispatched) >= 2, (
        [len(r.dispatched) for r in reps])


def test_prefix_affinity_rehashes_over_survivors():
    """A dead replica's prefix key range rehashes deterministically
    over the survivors — affinity composes with failover."""
    reps = [_FakeReplica(f"r{i}") for i in range(3)]
    prompt = np.arange(10, dtype=np.int32)
    with ServingGateway(reps, policy="prefix", retries=4,
                        backoff_base=0.005) as gw:
        gw.result(gw.submit(prompt), timeout=5)
        (home,) = [r for r in reps if r.dispatched]
        home.alive = False
        for _ in range(4):
            gw.result(gw.submit(prompt), timeout=5)
    survivors = [r for r in reps if r is not home and r.dispatched]
    assert len(survivors) == 1  # rehash is sticky too
    assert len(survivors[0].dispatched) == 4


def test_prefix_block_validation():
    with pytest.raises(ValueError, match="prefix_block"):
        ServingGateway([_FakeReplica("a")], policy="prefix",
                       prefix_block=0)


def test_failover_routes_around_a_failing_replica():
    reps = [_FakeReplica("a", fail_first=10), _FakeReplica("b")]
    with ServingGateway(reps, policy="round_robin", retries=3,
                        backoff_base=0.001) as gw:
        res = gw.result(gw.submit([1, 2]), timeout=5)
    assert "error" not in res
    assert reps[1].dispatched  # completed on the healthy replica


def test_retries_exhausted_yields_an_error_result_not_a_hang():
    reps = [_FakeReplica("a", fail_first=10 ** 6),
            _FakeReplica("b", fail_first=10 ** 6)]
    with ServingGateway(reps, retries=2, backoff_base=0.001) as gw:
        res = gw.result(gw.submit([1]), timeout=10)
    assert res["error"].startswith("gateway_retries_exhausted")
    assert res["attempts"] == 3  # initial + 2 retries


def test_duplicate_completion_is_delivered_exactly_once():
    class _Dup(_FakeReplica):
        def dispatch(self, spec, on_result):
            self.dispatched.append(spec["request_id"])
            res = {"request_id": spec["request_id"],
                   "prompt": spec["prompt"],
                   "tokens": np.asarray([1], np.int32)}
            on_result(dict(res))
            on_result({**res, "tokens": np.asarray([9], np.int32)})

    with ServingGateway([_Dup("a")]) as gw:
        rid = gw.submit([1])
        res = gw.result(rid, timeout=5)
        np.testing.assert_array_equal(res["tokens"], [1])  # first won
        with pytest.raises(KeyError):
            gw.result(rid)  # consumed: delivered exactly once


def test_healthz_aggregates_per_replica_verdicts():
    reps = [_FakeReplica("a"), _FakeReplica("b"), _FakeReplica("c")]
    gw = ServingGateway(reps)
    h = gw.healthz()
    assert h["state"] == "ok" and h["alive"] == 3
    assert set(h["replicas"]) == {"a", "b", "c"}
    reps[1].alive = False
    h = gw.healthz()
    assert h["state"] == "degraded" and h["alive"] == 2
    for r in reps:
        r.alive = False
    assert gw.healthz()["state"] == "critical"


# ---- in-process replicas: correctness through the gateway -------------


def test_gateway_results_match_solo_engine_per_request():
    """Routing over K replicas is invisible in the tokens: every
    request matches its solo generate() reference, under both the
    ordered and as-completed iteration modes."""
    model, variables = _model()
    prompts = _prompts([5, 9, 3, 7, 5, 11, 4, 6])
    reqs = [{"prompt": p, "max_new_tokens": n, "i": i}
            for i, (p, n) in enumerate(
                zip(prompts, [4, 7, 3, 6, 5, 8, 2, 7]))]
    reps = [EngineReplica(_engine(model, variables), name=f"r{i}")
            for i in range(2)]
    with ServingGateway(reps, policy="least_loaded") as gw:
        out = {r["i"]: r for r in gw.run(reqs, ordered=False)}
    assert len(out) == 8
    for req in reqs:
        assert "error" not in out[req["i"]]
        np.testing.assert_array_equal(
            out[req["i"]]["tokens"],
            _want(model, variables, req["prompt"],
                  req["max_new_tokens"]))


def test_killed_replica_requests_complete_elsewhere(flight):
    """ISSUE 7 acceptance (in-process arm): kill one of K=3 replicas
    with requests in flight — every request still completes exactly
    once with correct tokens, and the flight recorder tells the story
    (``replica_down`` precedes the ``failover``s it caused)."""
    model, variables = _model()
    prompts = _prompts([5, 7, 3, 6, 4, 8, 5, 6, 7, 4, 5, 6], seed=2)
    reps = [EngineReplica(_engine(model, variables), name=f"r{i}")
            for i in range(3)]
    with ServingGateway(reps, policy="round_robin", retries=6,
                        backoff_base=0.005, seed=7) as gw:
        rids = [gw.submit(p) for p in prompts]
        reps[1].kill()  # mid-stream: ~1/3 of the requests are its
        results = [gw.result(r, timeout=60) for r in rids]
    assert [r.get("error") for r in results] == [None] * len(prompts)
    assert len({r["request_id"] for r in results}) == len(prompts)
    for p, r in zip(prompts, results):
        np.testing.assert_array_equal(
            r["tokens"], _want(model, variables, p, 5))
    events = flight.read_events()
    downs = [i for i, e in enumerate(events)
             if e["kind"] == "replica_down"]
    overs = [i for i, e in enumerate(events)
             if e["kind"] == "failover"]
    assert downs and overs, [e["kind"] for e in events]
    assert min(downs) < min(overs)  # the story reads in order
    assert all(e["replica"] == "r1" for e in events
               if e["kind"] == "replica_down")


def test_stopping_a_replica_loses_nothing():
    """Graceful maintenance stop: in-flight requests come back as
    ``engine_closed`` from the closing engine and the gateway reroutes
    them — the caller never sees the stop."""
    model, variables = _model()
    prompts = _prompts([5, 6, 4, 7, 5, 6], seed=4)
    reps = [EngineReplica(_engine(model, variables), name=f"r{i}")
            for i in range(2)]
    with ServingGateway(reps, retries=6, backoff_base=0.005) as gw:
        rids = [gw.submit(p) for p in prompts]
        reps[0].stop()
        results = [gw.result(r, timeout=60) for r in rids]
    assert [r.get("error") for r in results] == [None] * len(prompts)
    for p, r in zip(prompts, results):
        np.testing.assert_array_equal(
            r["tokens"], _want(model, variables, p, 5))


# ---- the socket arm under chaos ---------------------------------------


def test_socket_chaos_kill_completes_exactly_once(flight):
    """THE acceptance sweep: K=3 socket replicas, seeded chaos on the
    gateway→replica hop (``target_ports`` keeps the schedule pure but
    scoped), one replica killed mid-stream.  Every request completes
    exactly once with tokens equal to the solo reference; the flight
    recorder carries the ``replica_down`` → ``failover`` story."""
    model, variables = _model()
    prompts = _prompts([5, 7, 3, 6, 4, 8, 5, 6, 7, 4], seed=5)
    servers = [ReplicaServer(EngineReplica(
        _engine(model, variables), name=f"s{i}")).start()
        for i in range(3)]
    ports = {s.address[1] for s in servers}
    remotes = [RemoteReplica("127.0.0.1", s.address[1], name=f"s{i}")
               for i, s in enumerate(servers)]
    try:
        with ChaosTransport(seed=11, reset_rate=0.15,
                            max_injections=4, skip_ops=2,
                            target_ports=ports) as ct:
            with ServingGateway(remotes, policy="round_robin",
                                retries=8, backoff_base=0.01,
                                seed=3) as gw:
                rids = [gw.submit(p) for p in prompts]
                servers[1].kill()
                results = [gw.result(r, timeout=120) for r in rids]
        assert ct.total_injected > 0  # the chaos really fired
        assert [r.get("error") for r in results] == \
            [None] * len(prompts)
        assert len({r["request_id"] for r in results}) == len(prompts)
        for p, r in zip(prompts, results):
            np.testing.assert_array_equal(
                np.asarray(r["tokens"]),
                _want(model, variables, p, 5))
        kinds = [e["kind"] for e in flight.read_events()]
        assert "replica_down" in kinds and "failover" in kinds
        assert kinds.index("replica_down") < \
            len(kinds) - 1 - kinds[::-1].index("failover")
    finally:
        for s in servers:
            s.stop()


def test_remote_probe_revives_a_down_marked_replica():
    model, variables = _model()
    server = ReplicaServer(EngineReplica(
        _engine(model, variables), name="s0")).start()
    remote = RemoteReplica("127.0.0.1", server.address[1], name="s0")
    try:
        remote._mark_down(ConnectionError("test"))
        assert not remote.alive
        assert remote.probe() and remote.alive
        h = remote.health()
        assert h["alive"] and h["state"] in ("ok", "degraded")
    finally:
        server.stop()


# ---- rolling weight updates -------------------------------------------


def test_rolling_update_from_live_ps_zero_failed_requests(flight):
    """ISSUE 7 acceptance: a rolling update sourced from a LIVE
    ``HostParameterServer`` swaps the PS center into every replica —
    one at a time, zero failed requests under concurrent traffic —
    and post-rollout tokens match an engine built on the new
    weights."""
    model, variables = _model()
    new_params = jax.tree_util.tree_map(lambda x: x * 0.7,
                                        variables["params"])
    ps = HostParameterServer(DownpourRule(), new_params)
    reps = [EngineReplica(_engine(model, variables), name=f"r{i}")
            for i in range(3)]
    prompts = _prompts([5, 7, 4, 6], seed=8)
    stop = threading.Event()
    traffic: list = []

    def pump(gw):
        k = 0
        while not stop.is_set():
            rid = gw.submit(prompts[k % len(prompts)])
            traffic.append(gw.result(rid, timeout=60))
            k += 1

    with ServingGateway(reps, policy="least_loaded", retries=6,
                        backoff_base=0.005) as gw:
        t = threading.Thread(target=pump, args=(gw,), daemon=True)
        t.start()
        try:
            report = gw.rolling_update(ps, quiesce_timeout=60)
        finally:
            stop.set()
            t.join(30)
        assert report["updated"] == ["r0", "r1", "r2"]
        assert not report["rolled_back"] and not report["skipped"]
        # every replica now serves the PS center
        for rep in reps:
            got = jax.tree_util.tree_leaves(
                rep.variables()["params"])
            want = jax.tree_util.tree_leaves(new_params)
            for g, w in zip(got, want):
                np.testing.assert_allclose(np.asarray(g),
                                           np.asarray(w))
        post = [gw.result(gw.submit(p), timeout=60) for p in prompts]
    assert traffic, "no concurrent traffic was served"
    assert all(r.get("error") is None for r in traffic), (
        [r.get("error") for r in traffic if r.get("error")])
    new_vars = {"params": new_params}
    for p, r in zip(prompts, post):
        np.testing.assert_array_equal(
            r["tokens"], _want(model, new_vars, p, 5))
    swaps = [e for e in flight.read_events()
             if e["kind"] == "weight_swap" and "replica" in e]
    assert [e["replica"] for e in swaps] == ["r0", "r1", "r2"]


def test_rolling_update_from_snapshot_file(tmp_path):
    """The offline source: ``checkpoint.ps_snapshot_center`` connects
    a PS snapshot file to the serving fleet."""
    from distkeras_tpu import checkpoint

    model, variables = _model()
    new_params = jax.tree_util.tree_map(lambda x: x * 0.5,
                                        variables["params"])
    path = checkpoint.save_ps_snapshot(
        tmp_path / "snap.msgpack",
        HostParameterServer(DownpourRule(), new_params).snapshot())
    reps = [EngineReplica(_engine(model, variables), name=f"r{i}")
            for i in range(2)]
    (p,) = _prompts([6], seed=9)
    with ServingGateway(reps) as gw:
        report = gw.rolling_update(str(path))
        assert report["updated"] == ["r0", "r1"]
        res = gw.result(gw.submit(p), timeout=60)
    np.testing.assert_array_equal(
        res["tokens"], _want(model, {"params": new_params}, p, 5))


def test_rolling_update_invalidates_replica_prefix_stores(flight):
    """ISSUE 8 regression: a rolling update must clear every
    replica's prefix store (stale KV under new weights is silently
    wrong) — post-rollout outputs are byte-identical to a cold engine
    on the new weights even though the fleet served warm caches."""
    model, variables = _model()
    new_params = jax.tree_util.tree_map(lambda x: x * 0.8,
                                        variables["params"])
    reps = [EngineReplica(
        _engine(model, variables, prefix_cache_bytes=1 << 24),
        name=f"r{i}") for i in range(2)]
    rng = np.random.default_rng(21)
    head = rng.integers(0, 37, (12,)).astype(np.int32)
    prompts = [np.concatenate(
        [head, rng.integers(0, 37, (4,)).astype(np.int32)])
        for _ in range(4)]
    with ServingGateway(reps, policy="prefix",
                        prefix_block=12) as gw:
        for _ in range(2):  # second wave hits the warm store
            for p in prompts:
                assert "error" not in gw.result(gw.submit(p),
                                                timeout=60)
        assert sum(r.engine.prefix_stats()["nodes"]
                   for r in reps) > 0
        report = gw.rolling_update({"params": new_params},
                                   quiesce_timeout=60)
        assert report["updated"] == ["r0", "r1"]
        for rep in reps:
            st = rep.engine.prefix_stats()
            assert st["nodes"] == 0 and st["invalidations"] >= 1, st
        post = [gw.result(gw.submit(p), timeout=60) for p in prompts]
    new_vars = {"params": new_params}
    for p, r in zip(prompts, post):
        assert "error" not in r
        np.testing.assert_array_equal(
            r["tokens"], _want(model, new_vars, p, 5))
    kinds = [e["kind"] for e in flight.read_events()]
    assert "prefix_invalidate" in kinds


def test_rolling_update_rolls_back_on_critical_health(flight):
    """A rollout that drives a replica ``critical`` is undone: every
    already-updated replica returns to the pre-rollout weights, and
    the flight recorder carries the ``rollback`` event."""
    model, variables = _model()
    new_params = jax.tree_util.tree_map(lambda x: x * 0.9,
                                        variables["params"])
    reps = [EngineReplica(_engine(model, variables), name=f"r{i}")
            for i in range(2)]
    verdicts = iter([{"state": "ok"}, {"state": "critical"}])
    with ServingGateway(reps) as gw:
        report = gw.rolling_update(
            {"params": new_params},
            health_check=lambda rep: next(verdicts))
        assert report["rolled_back"]
        assert report["updated"] == ["r0"]  # r1's check failed
        old = jax.tree_util.tree_leaves(variables["params"])
        for rep in reps:
            got = jax.tree_util.tree_leaves(
                rep.variables()["params"])
            for g, w in zip(got, old):
                np.testing.assert_allclose(np.asarray(g),
                                           np.asarray(w))
    kinds = [e["kind"] for e in flight.read_events()]
    assert "rollback" in kinds


def test_rolling_update_over_the_socket_arm():
    """Remote replicas swap through the wire (``b"w"``/``b"v"``/
    ``b"q"`` ops) — the rollout machinery is arm-agnostic."""
    model, variables = _model()
    new_params = jax.tree_util.tree_map(lambda x: x * 0.6,
                                        variables["params"])
    servers = [ReplicaServer(EngineReplica(
        _engine(model, variables), name=f"s{i}")).start()
        for i in range(2)]
    remotes = [RemoteReplica("127.0.0.1", s.address[1], name=f"s{i}")
               for i, s in enumerate(servers)]
    (p,) = _prompts([5], seed=10)
    try:
        with ServingGateway(remotes) as gw:
            report = gw.rolling_update({"params": new_params})
            assert report["updated"] == ["s0", "s1"]
            res = gw.result(gw.submit(p), timeout=60)
        np.testing.assert_array_equal(
            np.asarray(res["tokens"]),
            _want(model, {"params": new_params}, p, 5))
    finally:
        for s in servers:
            s.stop()


# ---- engine-level swap contract ---------------------------------------


def test_swap_variables_no_recompile_and_mismatch_rejected():
    """``swap_variables`` reuses every compiled program (same
    ``compile_counts`` before/after — the hot-swap claim) and rejects
    a tree that would retrace: wrong structure, shape, or dtype."""
    model, variables = _model()
    eng = _engine(model, variables)
    (p,) = _prompts([6], seed=12)
    first = next(iter(eng.run([p])))
    np.testing.assert_array_equal(first["tokens"],
                                  _want(model, variables, p, 5))
    counts = dict(eng.compile_counts)
    new_vars = jax.tree_util.tree_map(lambda x: x * 0.8,
                                      dict(variables))
    eng.swap_variables(new_vars)
    swapped = next(iter(eng.run([p])))
    np.testing.assert_array_equal(swapped["tokens"],
                                  _want(model, new_vars, p, 5))
    assert dict(eng.compile_counts) == counts  # zero new programs

    leaves, treedef = jax.tree_util.tree_flatten(new_vars)
    with pytest.raises(ValueError, match="structure mismatch"):
        eng.swap_variables({"params": {"nope": leaves[0]}})
    bad_shape = jax.tree_util.tree_unflatten(
        treedef, [np.zeros(np.shape(x) + (1,), np.float32)
                  for x in leaves])
    with pytest.raises(ValueError, match="leaf 0 mismatch"):
        eng.swap_variables(bad_shape)
    bad_dtype = jax.tree_util.tree_unflatten(
        treedef, [np.asarray(x, np.float64) for x in leaves])
    with pytest.raises(ValueError, match="mismatch"):
        eng.swap_variables(bad_dtype)
    eng.close()


def test_failover_rate_signal_reaches_the_watchdog():
    """``gateway_failovers_total / gateway_requests_total`` is a
    first-class SLO signal: a failover storm flips the watchdog."""
    tel = telemetry.enable()
    try:
        m = telemetry.metrics()
        m.counter("gateway_requests_total", replica="a",
                  policy="round_robin").inc(10)
        m.counter("gateway_failovers_total", replica="a").inc(6)
        w = telemetry.SLOWatchdog(m)
        sig = w.signals()
        assert sig["failover_rate"] == pytest.approx(0.6)
        assert w.evaluate()["state"] == "critical"
    finally:
        telemetry.disable()


# ---- paged-KV routing + QoS passthrough (ISSUE 13) --------------------


class _PagedFakeReplica(_FakeReplica):
    """Stub replica advertising KV-page headroom and recording the
    full dispatch spec (not just the id)."""

    def __init__(self, name, load=0, pages=None):
        super().__init__(name, load=load)
        self._pages = pages
        self.specs: list = []

    def free_pages(self):
        return self._pages

    def dispatch(self, spec, on_result):
        self.specs.append(dict(spec))
        super().dispatch(spec, on_result)


def test_least_loaded_breaks_ties_on_free_pages():
    """Equal queue depth: the replica with the most free KV pages
    wins; envelope replicas (free_pages None) rank below any paged
    replica with headroom."""
    reps = [_PagedFakeReplica("a", load=1, pages=2),
            _PagedFakeReplica("b", load=1, pages=9),
            _PagedFakeReplica("c", load=1, pages=None)]
    with ServingGateway(reps, policy="least_loaded") as gw:
        for r in [gw.submit([1, 2]) for _ in range(5)]:
            gw.result(r, timeout=5)
    assert [len(r.dispatched) for r in reps] == [0, 5, 0]
    # load still dominates the tie-break: an idle envelope replica
    # beats a busy paged one
    reps = [_PagedFakeReplica("a", load=3, pages=9),
            _PagedFakeReplica("b", load=0, pages=None)]
    with ServingGateway(reps, policy="least_loaded") as gw:
        for r in [gw.submit([1, 2]) for _ in range(4)]:
            gw.result(r, timeout=5)
    assert [len(r.dispatched) for r in reps] == [0, 4]


def test_gateway_forwards_tenant_and_priority():
    rep = _PagedFakeReplica("a")
    with ServingGateway([rep], policy="round_robin") as gw:
        rid = gw.submit([1, 2, 3], tenant="acme", priority=2)
        gw.result(rid, timeout=5)
        rid2 = gw.submit([1, 2, 3])
        gw.result(rid2, timeout=5)
    assert rep.specs[0]["tenant"] == "acme"
    assert rep.specs[0]["priority"] == 2
    # absent knobs are NOT forwarded (envelope engines would reject
    # unknown kwargs from a stale gateway otherwise)
    assert "tenant" not in rep.specs[1]
    assert "priority" not in rep.specs[1]


def test_engine_replica_reports_free_pages():
    """A paged in-process replica surfaces allocator headroom through
    ``free_pages()`` and ``health()``; an envelope replica reports
    None (routing falls back to queue depth alone)."""
    model, variables = _model()
    eng = _engine(model, variables, buckets=[32], kv_pages=8)
    rep = EngineReplica(eng, name="paged0").start()
    assert rep.free_pages() == 8
    assert rep.health()["free_pages"] == 8
    with ServingGateway([rep], policy="least_loaded") as gw:
        p = _prompts([6])[0]
        rid = gw.submit(p, max_new_tokens=4, tenant="t0", priority=2)
        out = gw.result(rid, timeout=60)
        np.testing.assert_array_equal(
            out["tokens"], _want(model, variables, p, 4))
        assert rep.free_pages() == 8  # all pages returned
    eng2 = _engine(model, variables, buckets=[32])
    rep2 = EngineReplica(eng2, name="env0").start()
    try:
        assert rep2.free_pages() is None
        assert rep2.health()["free_pages"] is None
    finally:
        rep2.stop()

"""Pipeline parallelism as a trainer surface (VERDICT.md r2 Weak #3:
"PP is an op, not a trainer"): SyncTrainer(pipeline_stages=S) trains a
baseline-shaped TransformerLM dp x pp with loss parity vs the
unpipelined run."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.data import datasets
from distkeras_tpu.models import model_config
from distkeras_tpu.trainers import SyncTrainer

LM_CFG = dict(vocab_size=64, num_layers=4, d_model=32, num_heads=2,
              max_len=16, dtype="float32")


def _lm_spec(**over):
    cfg = {**LM_CFG, **over}
    return model_config("transformer_lm", (16,), input_dtype="int32",
                        **cfg)


def test_scan_blocks_matches_per_layer_modules():
    """scan_blocks=True is the same math as the per-layer module stack
    (params mapped by stacking the per-layer subtrees)."""
    from distkeras_tpu.models.transformer import TransformerLM

    tok = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(2, 16)))
    plain = TransformerLM(**LM_CFG)
    scanned = TransformerLM(**LM_CFG, scan_blocks=True)
    vp = plain.init(jax.random.key(0), tok)

    blocks = [vp["params"][f"Block_{i}"]
              for i in range(LM_CFG["num_layers"])]
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *blocks)
    vs = {"params": {
        **{k: v for k, v in vp["params"].items()
           if not k.startswith("Block_")},
        "blocks": {"layer": stacked}}}
    np.testing.assert_allclose(
        np.asarray(scanned.apply(vs, tok)),
        np.asarray(plain.apply(vp, tok)), rtol=2e-5, atol=2e-5)


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device CPU mesh")
def test_pipelined_trainer_matches_unpipelined():
    """dp2 x pp4 over 8 devices: identical init -> per-epoch loss
    parity with the unpipelined (dp-only) run, and both learn."""
    data = datasets.lm_synth(256, seq_len=16, vocab_size=64, seed=0)
    spec_scan = _lm_spec(scan_blocks=True)
    kw = dict(batch_size=8, num_epoch=2, learning_rate=1e-3,
              worker_optimizer="adam",
              loss="sparse_categorical_crossentropy", seed=0)

    from distkeras_tpu.models import ModelSpec

    v0 = ModelSpec.from_config(spec_scan).build().init(
        jax.random.key(7),
        jnp.zeros((2, 16), jnp.int32))

    ref = SyncTrainer(spec_scan, num_workers=2, **kw)
    ref.train(data, initial_variables=v0)

    pp = SyncTrainer(spec_scan, num_workers=2, pipeline_stages=4, **kw)
    pp.train(data, initial_variables=v0)

    ref_losses = np.asarray(ref.history["epoch_loss"])
    pp_losses = np.asarray(pp.history["epoch_loss"])
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=1e-3,
                               atol=1e-3)
    assert pp_losses[-1] < pp_losses[0], pp_losses


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device CPU mesh")
def test_pipelined_trainer_converges_and_checkpoints(tmp_path):
    data = datasets.lm_synth(256, seq_len=16, vocab_size=64, seed=1)
    t = SyncTrainer(_lm_spec(), num_workers=2, pipeline_stages=4,
                    batch_size=8, num_epoch=4, learning_rate=1e-2,
                    worker_optimizer="adam",
                    loss="sparse_categorical_crossentropy", seed=0,
                    checkpoint_dir=str(tmp_path / "ck"))
    t.train(data)
    losses = t.history["epoch_loss"]
    # steady decrease epoch over epoch (the mod-arithmetic LM at this
    # width learns slowly but monotonically)
    assert all(b < a for a, b in zip(losses, losses[1:])), losses
    assert losses[-1] < losses[0] - 0.25, losses
    # the trained variables carry the scanned (stacked) layer tree
    stack = t.trained_variables["params"]["blocks"]["layer"]
    leaf = jax.tree_util.tree_leaves(stack)[0]
    assert leaf.shape[0] == 4


def test_pipeline_trainer_guards():
    with pytest.raises(ValueError, match="mutually exclusive"):
        SyncTrainer(_lm_spec(), model_parallel=2, pipeline_stages=2)
    t = SyncTrainer(model_config("mlp", (8,), num_classes=4,
                                 hidden=(8,)),
                    pipeline_stages=2, batch_size=8, num_epoch=1)
    data = datasets.synthetic_classification(64, (8,), 4, seed=0)
    with pytest.raises(ValueError, match="transformer_lm"):
        t.train(data)
    t2 = SyncTrainer(_lm_spec(num_layers=3), pipeline_stages=2,
                     batch_size=8, num_epoch=1,
                     loss="sparse_categorical_crossentropy")
    lm = datasets.lm_synth(64, seq_len=16, vocab_size=64, seed=0)
    with pytest.raises(ValueError, match="divide"):
        t2.train(lm)
    t3 = SyncTrainer(_lm_spec(num_experts=2), pipeline_stages=2,
                     batch_size=8, num_epoch=1,
                     loss="sparse_categorical_crossentropy")
    with pytest.raises(ValueError, match="MoE|dense-FFN"):
        t3.train(lm)

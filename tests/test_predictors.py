"""Predictor + evaluator: trained-model inference appends a prediction
column (reference predictors.py / evaluators.py surface)."""

import numpy as np
import pytest

from distkeras_tpu.data import datasets
from distkeras_tpu.evaluators import (
    AccuracyEvaluator,
    LossEvaluator,
    evaluate_model,
)
from distkeras_tpu.models import model_config
from distkeras_tpu.predictors import ModelPredictor
from distkeras_tpu.trainers import SingleTrainer

MLP = model_config("mlp", (8,), num_classes=4, hidden=(32,))


def _trained():
    data = datasets.synthetic_classification(2048, (8,), 4, seed=0)
    t = SingleTrainer(MLP, worker_optimizer="adam", learning_rate=3e-3,
                      batch_size=64, num_epoch=3)
    return t.train(data), data


def test_predict_appends_column_and_beats_chance():
    variables, data = _trained()
    pred = ModelPredictor(MLP, variables, output="class",
                          batch_size=64).predict(data)
    assert pred["prediction"].shape == (len(data),)
    acc = AccuracyEvaluator().evaluate(pred)
    assert acc > 0.5  # 4-class chance is 0.25

    probs = ModelPredictor(MLP, variables, output="prob",
                           batch_size=64).predict(data)
    assert probs["prediction"].shape == (len(data), 4)
    np.testing.assert_allclose(probs["prediction"].sum(axis=1), 1.0,
                               atol=1e-5)


def test_predict_handles_ragged_tail():
    variables, data = _trained()
    odd = data.take(777)  # not a multiple of any batch size
    pred = ModelPredictor(MLP, variables, output="logits",
                          batch_size=64).predict(odd)
    assert pred["prediction"].shape == (777, 4)


def test_multi_output_model_appends_column_per_head():
    """An ingested two-head keras DAG predicts one column per output
    (``prediction_0/1`` in output_layers order) — the serving half of
    multi-output support (training such specs is rejected loudly)."""
    import json as _json

    import jax

    from distkeras_tpu.compat import from_keras_json
    from distkeras_tpu.data import Dataset

    arch = {
        "class_name": "Model",
        "config": {
            "name": "two_head",
            "layers": [
                {"name": "in0", "class_name": "InputLayer",
                 "config": {"batch_input_shape": [None, 4]},
                 "inbound_nodes": []},
                {"name": "enc", "class_name": "Dense",
                 "config": {"units": 5, "activation": "relu"},
                 "inbound_nodes": [[["in0", 0, 0, {}]]]},
                {"name": "head_a", "class_name": "Dense",
                 "config": {"units": 3},
                 "inbound_nodes": [[["enc", 0, 0, {}]]]},
                {"name": "head_b", "class_name": "Dense",
                 "config": {"units": 1},
                 "inbound_nodes": [[["enc", 0, 0, {}]]]},
            ],
            "input_layers": [["in0", 0, 0]],
            "output_layers": [["head_a", 0, 0], ["head_b", 0, 0]],
        },
    }
    spec, _ = from_keras_json(_json.dumps(arch))
    x = np.random.default_rng(0).normal(size=(37, 4)).astype(
        np.float32)
    variables = spec.build().init(jax.random.key(0), x[:2])
    data = Dataset({"features": x})
    out = ModelPredictor(spec, variables, output="logits",
                         batch_size=16).predict(data)
    assert out["prediction_0"].shape == (37, 3)
    assert out["prediction_1"].shape == (37, 1)
    classes = ModelPredictor(spec, variables, output="class",
                             batch_size=16).predict(data)
    assert classes["prediction_0"].dtype == np.int32
    assert set(np.unique(classes["prediction_0"])) <= {0, 1, 2}


def test_multi_shard_prediction_matches_single(devices):
    variables, data = _trained()
    single = ModelPredictor(MLP, variables, num_shards=1,
                            batch_size=64).predict(data.take(512))
    multi = ModelPredictor(MLP, variables, num_shards=8,
                           batch_size=8).predict(data.take(512))
    np.testing.assert_allclose(single["prediction"],
                               multi["prediction"], atol=1e-5)


def test_evaluate_model_and_loss_evaluator():
    variables, data = _trained()
    metrics = evaluate_model(MLP, variables, data)
    assert metrics["accuracy"] > 0.5
    scored = ModelPredictor(MLP, variables, output="class",
                            batch_size=64).predict(data)
    err = LossEvaluator(lambda p, y: (p != y).astype(float)
                        ).evaluate(scored)
    np.testing.assert_allclose(err, 1.0 - metrics["accuracy"], atol=1e-9)


def test_tensor_parallel_inference_matches_dp(devices):
    """model_parallel=2 inference returns the same predictions as the
    replicated predictor — layout only, GSPMD collectives."""
    import jax

    from distkeras_tpu.models import ModelSpec, model_config
    from distkeras_tpu.data import datasets

    lm = model_config("transformer_lm", (16,), input_dtype="int32",
                      vocab_size=32, num_layers=1, d_model=32,
                      num_heads=4, max_len=16, dtype="float32")
    spec = ModelSpec.from_config(lm)
    variables = spec.build().init(jax.random.key(0),
                                  np.zeros((2, 16), np.int32))
    data = datasets.lm_synth(64, seq_len=16, vocab_size=32, seed=9)

    base = ModelPredictor(spec, variables, output="logits",
                          batch_size=16, num_shards=4)
    tp = ModelPredictor(spec, variables, output="logits",
                        batch_size=16, num_shards=4, model_parallel=2)
    want = np.asarray(base.predict(data)["prediction"])
    got = np.asarray(tp.predict(data)["prediction"])
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_tp_predictor_validation(devices):
    import jax

    from distkeras_tpu.models import ModelSpec, model_config

    lm = model_config("transformer_lm", (16,), input_dtype="int32",
                      vocab_size=32, num_layers=1, d_model=32,
                      num_heads=4, max_len=16, dtype="float32")
    spec = ModelSpec.from_config(lm)
    variables = spec.build().init(jax.random.key(0),
                                  np.zeros((2, 16), np.int32))
    with pytest.raises(ValueError, match="model_parallel"):
        ModelPredictor(spec, variables, model_parallel=0)
    with pytest.raises(ValueError, match="devices"):  # from create_mesh
        ModelPredictor(spec, variables, num_shards=8, model_parallel=2)
    with pytest.raises(ValueError, match="tp_rules"):
        ModelPredictor(spec.build(), variables, model_parallel=2)
    with pytest.raises(ValueError, match="model_parallel"):
        from distkeras_tpu.parallel import tensor_parallel as tp

        ModelPredictor(spec, variables,
                       tp_rules=tp.rules_for("transformer_lm"))


def test_evaluate_model_ignores_user_prediction_columns():
    """ADVICE r5: head counting matches exactly the contiguous
    prediction_0..n-1 columns the predictor appends — a user dataset
    that already carries its own prediction_*-named columns (inputs
    are kept in the scored frame) must not miscount heads."""
    import json

    import jax

    from distkeras_tpu.compat import from_keras_json
    from distkeras_tpu.data import Dataset

    arch = {"class_name": "Model", "config": {"name": "m", "layers": [
        {"name": "in0", "class_name": "InputLayer",
         "config": {"batch_input_shape": [None, 6]},
         "inbound_nodes": []},
        {"name": "a", "class_name": "Dense", "config": {"units": 3},
         "inbound_nodes": [[["in0", 0, 0, {}]]]},
        {"name": "b", "class_name": "Dense", "config": {"units": 2},
         "inbound_nodes": [[["in0", 0, 0, {}]]]},
    ], "input_layers": [["in0", 0, 0]],
       "output_layers": [["a", 0, 0], ["b", 0, 0]]}}
    spec, _ = from_keras_json(json.dumps(arch))
    variables = spec.build().init(jax.random.key(3),
                                  np.zeros((2, 6), np.float32))
    rng = np.random.default_rng(21)
    cols = {
        "features": rng.normal(size=(32, 6)).astype(np.float32),
        "label_a": rng.integers(0, 3, size=32),
        "label_b": rng.integers(0, 2, size=32),
        # user columns that USED to inflate the startswith count:
        "prediction_note": np.zeros(32, np.int32),
        "prediction_raw": np.zeros(32, np.int32),
        # non-contiguous numbered column is not predictor output either
        "prediction_7": np.zeros(32, np.int32),
    }
    got = evaluate_model(spec, variables, Dataset(cols),
                         label_col=["label_a", "label_b"])
    assert set(got) == {"label_a", "label_b"}
    clean = {k: v for k, v in cols.items()
             if not k.startswith("prediction")}
    want = evaluate_model(spec, variables, Dataset(clean),
                          label_col=["label_a", "label_b"])
    assert got == want
    # the genuine head-count mismatch is still loud
    with pytest.raises(ValueError, match="heads"):
        evaluate_model(spec, variables, Dataset(cols),
                       label_col=["label_a"])

"""Keras model ingestion — the reference's serialization surface.

The reference's whole workflow starts from a Keras model: users build a
``Sequential``, the framework ships ``serialize_keras_model`` output
(architecture JSON + weight list) to workers, and every trainer returns
a Keras model (SURVEY.md §2.1 "Utils", §3.5).  This module lets those
users bring the same artifact here: ``from_keras_json`` parses the
architecture JSON into a registered flax model family
(``keras_sequential``) and maps the Keras weight list onto flax
variables, so a reference user's model drops into any trainer /
predictor / evaluator unchanged.

Keras itself is NOT required: the JSON is parsed structurally (both the
Keras 2 era format the reference produced and the Keras 3 one), and
weights are plain arrays.  When Keras *is* installed, ``from_keras``
takes a live model.

Supported layers: InputLayer, Dense, Activation, Dropout, Flatten,
Conv1D, Conv2D (incl. dilated and grouped), DepthwiseConv2D,
Conv2DTranspose, SeparableConv2D, MaxPooling2D, AveragePooling2D,
GlobalAveragePooling2D, Embedding, BatchNormalization, LSTM, GRU
(``reset_after=True``, the keras >= 2.3 default), SimpleRNN,
Bidirectional(LSTM|GRU) — the reference's IMDB workflow shape — plus
the merge layers (Add / Subtract / Multiply / Average / Maximum /
Concatenate) for functional DAGs, and NESTED submodels used as layers —
both ``Sequential`` stacks and single-input/single-output functional
graphs (replayed inline; shared nested encoders — the siamese idiom —
apply one parameter set per call).  Anything else raises with the
layer name so the gap is visible, not silent.

Model topologies: ``Sequential``; functional ``Model(inputs,
outputs)`` graphs — linear chains lower to the ``keras_sequential``
family, true DAGs (branches + merges) to ``keras_graph``; SHARED
layers (called more than once) lower to one flax module applied at
every call node — one parameter set, keras's own sharing semantics;
multi-OUTPUT models forward as a tuple in ``output_layers`` order
(trainers reject them loudly — per-output losses are not supported);
multi-input models ingest as ONE flattened, concatenated features
array with per-input column slices (the reference-era Wide&Deep
shape); rank > 1 inputs (an image branch beside a feature branch)
reshape their slice back to the recorded per-sample shape.
"""

from __future__ import annotations

import json
from typing import Any, Mapping, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.models.core import ModelSpec, register_model

_ACTIVATIONS = {
    "linear": lambda x: x,
    "relu": nn.relu,
    "relu6": nn.relu6,
    "elu": nn.elu,
    "selu": nn.selu,
    "gelu": nn.gelu,
    "sigmoid": nn.sigmoid,
    "tanh": nn.tanh,
    "softmax": lambda x: nn.softmax(x, axis=-1),
    "softplus": nn.softplus,
    "swish": nn.swish,
    "silu": nn.silu,
    "leaky_relu": nn.leaky_relu,
}


# keras merge layers -> normalized kinds (the DAG walker's join nodes)
_MERGE_KINDS = {
    "Add": "merge_add",
    "Subtract": "merge_subtract",
    "Multiply": "merge_multiply",
    "Average": "merge_average",
    "Maximum": "merge_maximum",
    "Concatenate": "merge_concat",
}


def _activation(name: str):
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise NotImplementedError(
            f"keras activation {name!r} is not supported; supported: "
            f"{sorted(_ACTIVATIONS)}") from None


def _pair(v) -> tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    return tuple(int(x) for x in v)  # type: ignore[return-value]


def _normalize_layer(class_name: str, cfg: Mapping[str, Any]) -> Optional[dict]:
    """One keras layer config -> a minimal normalized dict (or ``None``
    for structural no-ops).  Only the fields the forward pass needs
    survive, so the normalized form is stable across keras versions."""
    if class_name == "InputLayer":
        return None
    if class_name == "Sequential":
        # a nested Sequential submodel used as a layer — the classic
        # shared-encoder idiom.  Normalize its layer stack recursively;
        # apply/weight-consumption walk the sublayers in order.
        raw = cfg if isinstance(cfg, list) else cfg.get("layers", [])
        sub = []
        for entry in raw:
            norm = _normalize_layer(entry["class_name"],
                                    entry.get("config", {}))
            if norm is not None:
                sub.append(norm)
        if not sub:
            raise ValueError("nested Sequential contains no layers")
        return {"kind": "nested", "layers": sub}
    if class_name in ("Functional", "Model"):
        # a nested functional submodel used as a layer (the shared-
        # encoder idiom with internal branches/merges): parse its DAG
        # with the same walker as a top-level functional model and
        # carry the graph spec; apply/weight-consumption replay it
        # inline.  Single-tensor boundary only — a nested model's
        # call site in the outer graph is one tensor in, one out.
        graph = _parse_functional({"class_name": "Functional",
                                   "config": cfg})
        if len(graph["outputs"]) != 1:
            raise NotImplementedError(
                "nested functional submodels must have exactly one "
                f"output; got {len(graph['outputs'])}")
        if graph["input_slices"]:
            raise NotImplementedError(
                "nested functional submodels must have exactly one "
                "input")
        return {"kind": "nested_graph", "graph": graph}
    if class_name == "Dense":
        return {"kind": "dense", "units": int(cfg["units"]),
                "use_bias": bool(cfg.get("use_bias", True)),
                "activation": cfg.get("activation", "linear")}
    if class_name == "Activation":
        return {"kind": "activation",
                "activation": cfg["activation"]}
    if class_name == "Dropout":
        return {"kind": "dropout", "rate": float(cfg["rate"])}
    if class_name == "Flatten":
        return {"kind": "flatten"}
    if class_name == "Conv2D":
        if cfg.get("data_format") not in (None, "channels_last"):
            raise NotImplementedError(
                "only channels_last Conv2D is supported")
        return {"kind": "conv2d", "filters": int(cfg["filters"]),
                "kernel_size": list(_pair(cfg["kernel_size"])),
                "strides": list(_pair(cfg.get("strides", 1))),
                "padding": str(cfg.get("padding", "valid")).upper(),
                "dilation": list(_pair(cfg.get("dilation_rate", 1))),
                "groups": int(cfg.get("groups", 1)),
                "use_bias": bool(cfg.get("use_bias", True)),
                "activation": cfg.get("activation", "linear")}
    if class_name == "Conv1D":
        if cfg.get("data_format") not in (None, "channels_last"):
            raise NotImplementedError(
                "only channels_last Conv1D is supported")
        def one(v):
            return int(v[0]) if isinstance(v, (list, tuple)) else int(v)
        padding = str(cfg.get("padding", "valid")).upper()
        if padding == "CAUSAL":
            raise NotImplementedError(
                "Conv1D(padding='causal') is not supported")
        return {"kind": "conv1d", "filters": int(cfg["filters"]),
                "kernel_size": one(cfg["kernel_size"]),
                "strides": one(cfg.get("strides", 1)),
                "padding": padding,
                "dilation": one(cfg.get("dilation_rate", 1)),
                "groups": int(cfg.get("groups", 1)),
                "use_bias": bool(cfg.get("use_bias", True)),
                "activation": cfg.get("activation", "linear")}
    if class_name == "DepthwiseConv2D":
        if cfg.get("data_format") not in (None, "channels_last"):
            raise NotImplementedError(
                "only channels_last DepthwiseConv2D is supported")
        return {"kind": "dwconv2d",
                "kernel_size": list(_pair(cfg["kernel_size"])),
                "strides": list(_pair(cfg.get("strides", 1))),
                "padding": str(cfg.get("padding", "valid")).upper(),
                "dilation": list(_pair(cfg.get("dilation_rate", 1))),
                "depth_multiplier": int(cfg.get("depth_multiplier", 1)),
                "use_bias": bool(cfg.get("use_bias", True)),
                "activation": cfg.get("activation", "linear")}
    if class_name == "Conv2DTranspose":
        if cfg.get("data_format") not in (None, "channels_last"):
            raise NotImplementedError(
                "only channels_last Conv2DTranspose is supported")
        if _pair(cfg.get("dilation_rate", 1)) != (1, 1):
            raise NotImplementedError(
                "dilated Conv2DTranspose is not supported")
        if cfg.get("output_padding") is not None:
            raise NotImplementedError(
                "Conv2DTranspose(output_padding=...) is not supported")
        return {"kind": "convtranspose2d",
                "filters": int(cfg["filters"]),
                "kernel_size": list(_pair(cfg["kernel_size"])),
                "strides": list(_pair(cfg.get("strides", 1))),
                "padding": str(cfg.get("padding", "valid")).upper(),
                "use_bias": bool(cfg.get("use_bias", True)),
                "activation": cfg.get("activation", "linear")}
    if class_name == "SeparableConv2D":
        if cfg.get("data_format") not in (None, "channels_last"):
            raise NotImplementedError(
                "only channels_last SeparableConv2D is supported")
        if _pair(cfg.get("dilation_rate", 1)) != (1, 1):
            raise NotImplementedError(
                "dilated SeparableConv2D is not supported")
        return {"kind": "sepconv2d", "filters": int(cfg["filters"]),
                "kernel_size": list(_pair(cfg["kernel_size"])),
                "strides": list(_pair(cfg.get("strides", 1))),
                "padding": str(cfg.get("padding", "valid")).upper(),
                "depth_multiplier": int(cfg.get("depth_multiplier", 1)),
                "use_bias": bool(cfg.get("use_bias", True)),
                "activation": cfg.get("activation", "linear")}
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        pool = _pair(cfg.get("pool_size", 2))
        return {"kind": "pool",
                "op": "max" if class_name.startswith("Max") else "avg",
                "pool_size": list(pool),
                "strides": list(_pair(cfg.get("strides") or pool)),
                "padding": str(cfg.get("padding", "valid")).upper()}
    if class_name == "GlobalAveragePooling2D":
        return {"kind": "global_avg_pool"}
    if class_name == "Embedding":
        if cfg.get("mask_zero"):
            raise NotImplementedError(
                "Embedding(mask_zero=True) is not supported: keras "
                "propagates the mask into recurrent layers, which the "
                "ingested model would silently ignore on padded "
                "sequences — rebuild natively (models.BiLSTMClassifier "
                "masks pads) or re-export without mask_zero")
        return {"kind": "embedding",
                "input_dim": int(cfg["input_dim"]),
                "output_dim": int(cfg["output_dim"])}
    if class_name in _MERGE_KINDS:
        norm = {"kind": _MERGE_KINDS[class_name]}
        if class_name == "Concatenate":
            # whether a positive axis is "the last axis" depends on the
            # tensor rank, unknown until apply time — record it and
            # validate there (axis=1 on rank-2 inputs is the common
            # Wide&Deep spelling and identical to -1)
            norm["axis"] = int(cfg.get("axis", -1))
        return norm
    if class_name == "LSTM":
        return _normalize_lstm(cfg, kind="lstm")
    if class_name == "GRU":
        return _normalize_gru(cfg, kind="gru")
    if class_name == "SimpleRNN":
        return _normalize_simple_rnn(cfg)
    if class_name == "Bidirectional":
        inner = cfg.get("layer", {})
        inner_cls = inner.get("class_name")
        if inner_cls not in ("LSTM", "GRU"):
            raise NotImplementedError(
                f"Bidirectional({inner_cls!r}) is not supported; only "
                f"Bidirectional(LSTM) and Bidirectional(GRU)")
        if cfg.get("merge_mode", "concat") != "concat":
            raise NotImplementedError(
                f"Bidirectional merge_mode="
                f"{cfg.get('merge_mode')!r} is not supported; only "
                f"'concat'")
        if inner_cls == "LSTM":
            return _normalize_lstm(inner.get("config", {}),
                                   kind="bilstm")
        return _normalize_gru(inner.get("config", {}), kind="bigru")
    if class_name == "BatchNormalization":
        if not (cfg.get("center", True) and cfg.get("scale", True)):
            raise NotImplementedError(
                "BatchNormalization with center=False or scale=False "
                "is not supported (the weight mapping assumes "
                "[gamma, beta, mean, var])")
        axis = cfg.get("axis", -1)
        if isinstance(axis, (list, tuple)) and len(axis) == 1:
            axis = axis[0]
        if axis != -1:
            raise NotImplementedError(
                f"BatchNormalization over axis {axis!r} is not "
                f"supported; only the last (channels) axis is")
        return {"kind": "batchnorm",
                "epsilon": float(cfg.get("epsilon", 1e-3)),
                "momentum": float(cfg.get("momentum", 0.99))}
    raise NotImplementedError(
        f"keras layer {class_name!r} is not supported by the "
        f"ingestion shim (Dense/Conv2D/pooling/Embedding/BatchNorm/"
        f"LSTM/Bidirectional(LSTM) stacks are); rebuild this model "
        f"natively with distkeras_tpu.models instead")


def _normalize_lstm(cfg: Mapping[str, Any], kind: str) -> dict:
    """LSTM config checks: only the (modern keras default) gate
    functions match flax's LSTMCell equations exactly."""
    if cfg.get("activation", "tanh") != "tanh" or \
            cfg.get("recurrent_activation", "sigmoid") != "sigmoid":
        raise NotImplementedError(
            f"LSTM with activation={cfg.get('activation')!r} / "
            f"recurrent_activation={cfg.get('recurrent_activation')!r} "
            f"is not supported; only tanh/sigmoid (note: keras<2.3 "
            f"defaulted recurrent_activation to 'hard_sigmoid')")
    if not cfg.get("use_bias", True):
        raise NotImplementedError("LSTM(use_bias=False) not supported")
    if cfg.get("go_backwards"):
        raise NotImplementedError(
            "LSTM(go_backwards=True) not supported (use Bidirectional)")
    if cfg.get("dropout") or cfg.get("recurrent_dropout"):
        raise NotImplementedError(
            "LSTM dropout/recurrent_dropout are not supported — "
            "silently dropping them would change training behavior; "
            "re-export without them or add a standalone Dropout layer")
    if cfg.get("stateful"):
        raise NotImplementedError("stateful LSTM is not supported")
    return {"kind": kind, "units": int(cfg["units"]),
            "return_sequences": bool(cfg.get("return_sequences",
                                             False))}


def _rnn_common_checks(cfg: Mapping[str, Any], what: str):
    if not cfg.get("use_bias", True):
        raise NotImplementedError(
            f"{what}(use_bias=False) not supported")
    if cfg.get("go_backwards"):
        raise NotImplementedError(
            f"{what}(go_backwards=True) not supported "
            f"(use Bidirectional)")
    if cfg.get("dropout") or cfg.get("recurrent_dropout"):
        raise NotImplementedError(
            f"{what} dropout/recurrent_dropout are not supported — "
            f"silently dropping them would change training behavior")
    if cfg.get("stateful"):
        raise NotImplementedError(f"stateful {what} is not supported")


def _normalize_gru(cfg: Mapping[str, Any], kind: str) -> dict:
    """GRU maps onto ``flax.linen.GRUCell`` exactly when keras runs its
    modern form: ``reset_after=True`` (the keras >= 2.3 default) applies
    the reset gate to the *transformed* hidden state, which is flax's
    ``r * (W_hn h + b_hn)``; the legacy ``reset_after=False`` resets the
    raw ``h`` before the matmul — a different equation, rejected."""
    if cfg.get("activation", "tanh") != "tanh" or \
            cfg.get("recurrent_activation", "sigmoid") != "sigmoid":
        raise NotImplementedError(
            f"GRU with activation={cfg.get('activation')!r} / "
            f"recurrent_activation="
            f"{cfg.get('recurrent_activation')!r} is not supported; "
            f"only tanh/sigmoid")
    if not cfg.get("reset_after", True):
        raise NotImplementedError(
            "GRU(reset_after=False) (the pre-keras-2.3 form) applies "
            "the reset gate before the recurrent matmul, which flax's "
            "GRUCell cannot express; re-export with reset_after=True")
    _rnn_common_checks(cfg, "GRU")
    return {"kind": kind, "units": int(cfg["units"]),
            "return_sequences": bool(cfg.get("return_sequences",
                                             False))}


def _normalize_simple_rnn(cfg: Mapping[str, Any]) -> dict:
    activation = cfg.get("activation", "tanh")
    _activation(activation)  # raises on unsupported names
    _rnn_common_checks(cfg, "SimpleRNN")
    return {"kind": "simple_rnn", "units": int(cfg["units"]),
            "activation": activation,
            "return_sequences": bool(cfg.get("return_sequences",
                                             False))}


def _leading_kind(layer: Mapping[str, Any]) -> str:
    """First concrete layer kind, descending nested Sequentials — what
    the input-dtype inference needs to see."""
    while layer["kind"] == "nested":
        layer = layer["layers"][0]
    return layer["kind"]


def _infer_input_shape(arch: Mapping[str, Any]) -> tuple[int, ...] | None:
    """Per-sample input shape from the first layer's
    ``batch_shape`` (keras 3) / ``batch_input_shape`` (keras 1/2),
    when recorded — descending into nested Sequential submodels,
    where the only recorded shape may live."""
    config = arch.get("config", {})
    raw_layers = (config if isinstance(config, list)
                  else config.get("layers", []))

    def scan(entries):
        for entry in entries:
            cfg = entry.get("config", {})
            if isinstance(cfg, Mapping):
                shape = (cfg.get("batch_shape")
                         or cfg.get("batch_input_shape"))
                if shape is not None:
                    return shape
            sub = (cfg if isinstance(cfg, list)
                   else cfg.get("layers") if isinstance(cfg, Mapping)
                   else None)
            if sub:
                found = scan(sub)
                if found is not None:
                    return found
        return None

    shape = scan(raw_layers)
    if shape is None:
        return None
    if any(d is None for d in shape[1:]):
        return None  # variable-length dims: caller must pass one
    return tuple(int(d) for d in shape[1:])


def _inbound_refs(node) -> list[tuple[str, int]]:
    """Predecessor ``(layer name, producing call index)`` pairs from
    one inbound-node entry.  The call index is what distinguishes the
    outputs of a SHARED layer (called more than once).

    Keras 2 era (the reference's format): a list of
    ``[name, node_index, tensor_index, kwargs]`` quads.  Keras 3: a
    dict whose args/kwargs embed ``__keras_tensor__`` objects carrying
    ``keras_history = [name, node_index, tensor_index]``."""
    refs: list[tuple[str, int]] = []

    def add(name, node_index, tensor_index):
        if int(tensor_index) != 0:
            raise NotImplementedError(
                f"layer {name!r} produces multiple output tensors "
                f"(tensor_index {tensor_index}); multi-output LAYERS "
                f"are not supported (multi-output MODELS are)")
        refs.append((name, int(node_index)))

    if isinstance(node, Mapping):
        def walk(obj):
            if isinstance(obj, Mapping):
                if obj.get("class_name") == "__keras_tensor__":
                    hist = obj.get("config", {})["keras_history"]
                    add(hist[0], hist[1], hist[2])
                else:
                    for v in obj.values():
                        walk(v)
            elif isinstance(obj, (list, tuple)):
                for v in obj:
                    walk(v)
        walk(node.get("args", []))
        walk(node.get("kwargs", {}))
    else:
        for item in node:
            add(item[0], item[1], item[2])
    return refs


def _ref_pairs(refs) -> list[tuple[str, int]]:
    """``(name, call index)`` pairs out of ``input_layers`` /
    ``output_layers``: either one ``[name, node, tensor]`` ref (keras 3
    single), a list of such refs (multi / keras 2), or a bare name
    list."""
    if not refs:
        return []
    if isinstance(refs[0], str):
        # ["name", 0, 0] (single ref) vs ["a", "b"] (keras-3 multi)
        if len(refs) == 3 and not isinstance(refs[1], str) \
                and not isinstance(refs[2], str):
            return [(refs[0], int(refs[1]))]
        return [(r, 0) for r in refs if isinstance(r, str)]
    return [(r[0], int(r[1])) for r in refs]


def _parse_functional(arch: Mapping[str, Any]) -> dict:
    """Functional ``Model(inputs, outputs)`` graphs → a JSON-able graph
    spec.

    Supported: DAGs built from the normalized layer set plus the merge
    layers (Add/Subtract/Multiply/Average/Maximum/Concatenate);
    SHARED layers (called more than once — one graph node per call,
    all calls applying one parameter set); MULTI-OUTPUT models (the
    forward returns a tuple in ``output_layers`` order — trainers that
    need a single loss head reject them loudly).  Multi-INPUT models
    of any rank: the inputs flatten and concatenate (in
    ``input_layers`` order) into one features array; each Input node
    slices its columns back out and rank > 1 inputs reshape to their
    recorded per-sample shape — the reference-era Wide&Deep shape,
    extended to mixed image/feature branches.  An input whose
    per-sample shape is unrecorded (None dims) is rejected loudly."""
    config = arch.get("config", {})
    raw_layers = config.get("layers", [])
    if not raw_layers:
        raise ValueError("keras architecture contains no layers")
    names: list[str] = []
    by_name: dict[str, dict] = {}
    call_preds: dict[str, list[list[tuple[str, int]]]] = {}
    for entry in raw_layers:
        name = entry.get("name") or entry.get("config", {}).get("name")
        if name is None:
            raise ValueError("functional layer entry has no name")
        names.append(name)
        by_name[name] = entry
        inbound = entry.get("inbound_nodes", [])
        # one CALL per inbound node; an InputLayer (no inbound) is one
        # call with no predecessors
        call_preds[name] = ([_inbound_refs(n) for n in inbound]
                            or [[]])

    out_refs = _ref_pairs(config.get("output_layers", []))
    if not out_refs:
        raise ValueError("functional model declares no output layers")
    in_refs = _ref_pairs(config.get("input_layers", []))
    in_names = [n for n, _ in in_refs]
    if not in_names:
        raise ValueError("functional model declares no input layers")

    # Multi-input: the inputs flatten and concatenate (in input_layers
    # order) into ONE per-sample feature row; each Input node slices
    # its columns back out and, for rank > 1 inputs (an image branch
    # next to a feature branch, say), reshapes them to the recorded
    # per-sample shape.  This is the columnar-dataset contract every
    # trainer/predictor already speaks (AssembleTransformer produces
    # exactly such rows).
    input_slices = []
    if len(in_names) > 1:
        start = 0
        for n in in_names:
            cfg_n = by_name[n].get("config", {})
            shape = (cfg_n.get("batch_shape")
                     or cfg_n.get("batch_input_shape"))
            if (shape is None or len(shape) < 2
                    or any(d is None for d in shape[1:])):
                raise NotImplementedError(
                    f"multi-input ingestion needs every input's "
                    f"per-sample shape recorded (no None dims past "
                    f"the batch); input {n!r} has batch shape "
                    f"{shape!r}")
            dims = [int(d) for d in shape[1:]]
            width = 1
            for d in dims:
                width *= d
            entry = [n, start, start + width]
            if len(dims) > 1:
                entry.append(dims)  # rank>1: reshape after slicing
            input_slices.append(entry)
            start += width

    # Call-node ids in config-list order (layers with one call keep
    # id == config position, the round-3 numbering); params are keyed
    # by config position — the keras get_weights() order.
    id_of_call: dict[tuple[str, int], int] = {}
    param_of: dict[str, int] = {}
    for i, n in enumerate(names):
        param_of[n] = i
        for j in range(len(call_preds[n])):
            id_of_call[(n, j)] = len(id_of_call)

    def resolve(ref: tuple[str, int], consumer: str) -> int:
        name, j = ref
        if (name, j) not in id_of_call:
            raise ValueError(
                f"layer {consumer!r} consumes call {j} of {name!r}, "
                f"which has only "
                f"{len(call_preds.get(name, []))} call(s)")
        return id_of_call[(name, j)]

    preds_by_id: dict[int, list[int]] = {}
    for n in names:
        for j, refs in enumerate(call_preds[n]):
            preds_by_id[id_of_call[(n, j)]] = [
                resolve(r, n) for r in refs]

    # Kahn topological order over call nodes.
    total = len(id_of_call)
    pending = {i: len(preds_by_id[i]) for i in range(total)}
    ready = [i for i in range(total) if pending[i] == 0]
    topo: list[int] = []
    succs: dict[int, list[int]] = {i: [] for i in range(total)}
    for i, ps in preds_by_id.items():
        for p in ps:
            succs[p].append(i)
    while ready:
        cur = ready.pop(0)
        topo.append(cur)
        for m in succs[cur]:
            pending[m] -= 1
            if pending[m] == 0:
                ready.append(m)
    if len(topo) != total:
        raise ValueError(
            "functional graph is cyclic or disconnected at call "
            f"nodes {sorted(set(range(total)) - set(topo))}")

    nodes = []
    for n in names:
        entry = by_name[n]
        shared = len(call_preds[n]) > 1
        for j in range(len(call_preds[n])):
            nid = id_of_call[(n, j)]
            p = preds_by_id[nid]
            if entry["class_name"] == "InputLayer" or n in in_names:
                if shared:
                    raise ValueError(
                        f"input layer {n!r} has {len(call_preds[n])} "
                        f"inbound nodes")
                node = {"kind": "input"}
            else:
                node = dict(_normalize_layer(entry["class_name"],
                                             entry.get("config", {}))
                            or {})
                if not node:  # InputLayer routed above; cannot occur
                    raise AssertionError(entry["class_name"])
                if node["kind"].startswith("merge_"):
                    if len(p) < 2:
                        raise ValueError(
                            f"merge layer {n!r} has {len(p)} inputs")
                elif len(p) != 1:
                    raise NotImplementedError(
                        f"layer {n!r} ({entry['class_name']}) takes "
                        f"{len(p)} input tensors; only merge layers "
                        f"may take several")
            node["id"] = nid
            node["param"] = param_of[n]
            node["inputs"] = list(p)
            nodes.append(node)
    nodes.sort(key=lambda nd: nd["id"])

    return {
        "nodes": nodes,                       # call-id order
        "topo": topo,
        "outputs": [resolve(r, "<output_layers>") for r in out_refs],
        "input_slices": [[id_of_call[(n, 0)], *rest]
                         for n, *rest in input_slices],
    }


def _graph_is_chain(graph: dict) -> list[dict] | None:
    """A single-input, single-output, merge-free, branch-free,
    share-free DAG whose config-list order is already executable
    (keras serializes layers in its own topological order, which is
    also ``get_weights()`` order) is a plain chain: return its
    normalized layer list so it lowers to the simpler
    ``keras_sequential`` family; ``None`` otherwise."""
    nodes = graph["nodes"]
    if len(graph["outputs"]) != 1:
        return None
    n_inputs = sum(1 for n in nodes if n["kind"] == "input")
    if n_inputs != 1:
        return None
    param_calls: dict[int, int] = {}
    succ_count: dict[int, int] = {}
    for n in nodes:
        if n["kind"].startswith("merge_"):
            return None
        pc = param_calls.get(n["param"], 0) + 1
        param_calls[n["param"]] = pc
        if pc > 1:
            return None  # shared layer: needs the graph family
        for i in n["inputs"]:
            succ_count[i] = succ_count.get(i, 0) + 1
        if any(i >= n["id"] for i in n["inputs"]):
            return None  # config order not executable: graph path
    if any(c > 1 for c in succ_count.values()):
        return None
    return [{k: v for k, v in n.items()
             if k not in ("id", "inputs", "param")}
            for n in nodes if n["kind"] != "input"]


def _parse_arch(arch: Mapping[str, Any]) -> list[dict]:
    class_name = arch.get("class_name")
    if class_name != "Sequential":
        raise NotImplementedError(
            f"only Sequential and Functional keras models are "
            f"supported, got {class_name!r}")
    config = arch.get("config", {})
    # Keras 1 stored the layer list directly under config; 2/3 under
    # config["layers"].
    raw_layers = (config if isinstance(config, list)
                  else config.get("layers", []))
    layers = []
    for entry in raw_layers:
        if entry["class_name"] in _MERGE_KINDS:
            raise NotImplementedError(
                f"merge layer {entry['class_name']!r} cannot appear in "
                f"a Sequential model (it takes multiple inputs); "
                f"export the functional Model instead")
        norm = _normalize_layer(entry["class_name"],
                                entry.get("config", {}))
        if norm is not None:
            layers.append(norm)
    if not layers:
        raise ValueError("keras architecture contains no layers")
    return layers


@register_model("keras_sequential")
class KerasSequential(nn.Module):
    """Flax twin of an ingested keras ``Sequential``.

    ``layers`` is the normalized layer list ``_parse_arch`` produces —
    plain JSON data, so specs built from keras models serialize through
    ``ModelSpec``/checkpoints like any native family.  Parameterized
    layers are named ``layer_{i}`` (their position in the *normalized*
    list), which is what makes the keras weight-list mapping
    deterministic."""

    layers: Sequence[Mapping[str, Any]] = ()
    dtype: str = "float32"

    @nn.compact
    def __call__(self, x, train: bool = False):
        dtype = jnp.dtype(self.dtype)
        x = jnp.asarray(x, dtype)
        for i, layer in enumerate(self.layers):
            x = _apply_layer(layer, f"layer_{i}", x, dtype, train)
        return x


def _apply_layer(layer, name: str, x, dtype, train: bool,
                 memo: dict | None = None):
    """One normalized layer's forward.  Called from inside a module's
    ``@nn.compact`` ``__call__`` — flax binds the submodules created
    here to the calling module, so ``KerasSequential`` and
    ``KerasGraph`` share one per-kind implementation (and one
    weight-mapping convention).

    ``memo`` (per parameter id, graph family only) caches the created
    submodules across calls: a keras layer called at several graph
    nodes lowers to one flax module applied several times — the flax
    weight-sharing idiom.  Explicitly-named modules MUST go through it
    (flax rejects a second same-name creation)."""
    kind = layer["kind"]

    def get(key: str, ctor):
        if memo is None:
            return ctor()
        if key not in memo:
            memo[key] = ctor()
        return memo[key]

    if kind == "nested":
        # nested Sequential: apply the stack; each sublayer gets its
        # own name suffix and (when sharing) its own memo slot
        for i, sub in enumerate(layer["layers"]):
            sub_memo = None
            if memo is not None:
                sub_memo = memo.setdefault(f"s{i}", {})
            x = _apply_layer(sub, f"{name}_s{i}", x, dtype, train,
                             memo=sub_memo)
        return x
    if kind == "nested_graph":
        # nested functional submodel: replay its call graph inline via
        # the shared walker.  Sublayers are named {name}_g{param}
        # (param = inner config position, the inner get_weights()
        # order).  The memo is ALWAYS a dict here — even under the
        # sequential lowering (outer memo None), an inner layer shared
        # across inner call nodes must apply one flax module, or the
        # second creation of the same explicit name would crash flax;
        # a fresh local dict is correct there because a sequential
        # outer layer is applied exactly once.
        g = layer["graph"]
        memos = memo.setdefault("g", {}) if memo is not None else {}
        outs = _walk_graph(g["nodes"], g["topo"], lambda nid: x,
                           lambda p: f"{name}_g{p}", dtype, train,
                           memos)
        return outs[int(g["outputs"][0])]
    if kind == "dense":
        # contracts the last axis, any rank — keras semantics
        x = get("m", lambda: nn.Dense(
            layer["units"], use_bias=layer["use_bias"],
            dtype=dtype, name=name))(x)
        return _activation(layer["activation"])(x)
    if kind == "activation":
        return _activation(layer["activation"])(x)
    if kind == "dropout":
        return nn.Dropout(layer["rate"], deterministic=not train)(x)
    if kind == "flatten":
        return x.reshape((x.shape[0], -1))
    if kind in ("conv2d", "conv1d"):
        size = (tuple(layer["kernel_size"])
                if kind == "conv2d" else (layer["kernel_size"],))
        strides = (tuple(layer["strides"])
                   if kind == "conv2d" else (layer["strides"],))
        dilation = layer.get("dilation", 1)
        dilation = (tuple(dilation) if isinstance(dilation, (list,
                                                             tuple))
                    else (dilation,))
        x = get("m", lambda: nn.Conv(
            layer["filters"], size, strides=strides,
            padding=layer["padding"], use_bias=layer["use_bias"],
            kernel_dilation=dilation,
            feature_group_count=layer.get("groups", 1),
            dtype=dtype, name=name))(x)
        return _activation(layer["activation"])(x)
    if kind == "dwconv2d":
        # keras DepthwiseConv2D == flax grouped conv with one group
        # per input channel; keras's [k, k, cin, mult] kernel folds to
        # flax's [k, k, 1, cin*mult] (same channel order — channel i's
        # multipliers contiguous), exactly the sepconv dw mapping
        channels = int(x.shape[-1])
        mult = layer["depth_multiplier"]
        x = get("m", lambda: nn.Conv(
            channels * mult, tuple(layer["kernel_size"]),
            strides=tuple(layer["strides"]),
            padding=layer["padding"],
            kernel_dilation=tuple(layer.get("dilation", (1, 1))),
            use_bias=layer["use_bias"],
            feature_group_count=channels,
            dtype=dtype, name=name))(x)
        return _activation(layer["activation"])(x)
    if kind == "convtranspose2d":
        # transpose_kernel=True takes the kernel in keras's own
        # [k, k, out, in] layout AND flips it the way keras's
        # gradient-of-conv semantics do — verified exact vs keras
        x = get("m", lambda: nn.ConvTranspose(
            layer["filters"], tuple(layer["kernel_size"]),
            strides=tuple(layer["strides"]),
            padding=layer["padding"], use_bias=layer["use_bias"],
            transpose_kernel=True, dtype=dtype, name=name))(x)
        return _activation(layer["activation"])(x)
    if kind == "sepconv2d":
        channels = int(x.shape[-1])
        mult = layer["depth_multiplier"]
        x = get("dw", lambda: nn.Conv(
            channels * mult, tuple(layer["kernel_size"]),
            strides=tuple(layer["strides"]),
            padding=layer["padding"], use_bias=False,
            feature_group_count=channels,
            dtype=dtype, name=name + "_dw"))(x)
        x = get("pw", lambda: nn.Conv(
            layer["filters"], (1, 1), use_bias=layer["use_bias"],
            dtype=dtype, name=name + "_pw"))(x)
        return _activation(layer["activation"])(x)
    if kind == "pool":
        fn = nn.max_pool if layer["op"] == "max" else nn.avg_pool
        return fn(x, tuple(layer["pool_size"]),
                  strides=tuple(layer["strides"]),
                  padding=layer["padding"])
    if kind == "global_avg_pool":
        return x.mean(axis=(1, 2))
    if kind == "embedding":
        return get("m", lambda: nn.Embed(
            layer["input_dim"], layer["output_dim"],
            dtype=dtype, name=name))(x.astype(jnp.int32))
    if kind == "batchnorm":
        return get("m", lambda: nn.BatchNorm(
            use_running_average=not train,
            epsilon=layer["epsilon"], momentum=layer["momentum"],
            dtype=dtype, name=name))(x)
    if kind in ("lstm", "gru", "simple_rnn"):
        # the RNN wrapper owns no params; naming the CELL is what pins
        # the weight-mapping path (and what a shared layer reuses)
        cell = get("cell", lambda: _make_cell(kind, layer, dtype, name))
        y = nn.RNN(cell)(x)
        return y if layer["return_sequences"] else y[:, -1]
    if kind in ("bilstm", "bigru"):
        # keras Bidirectional(merge_mode='concat'): backward outputs
        # are time-aligned (keep_order); its "last" output is the one
        # at original index 0
        base = "lstm" if kind == "bilstm" else "gru"
        fwd = get("fwd", lambda: _make_cell(base, layer, dtype,
                                            name + "_fwd"))
        bwd = get("bwd", lambda: _make_cell(base, layer, dtype,
                                            name + "_bwd"))
        yf = nn.RNN(fwd)(x)
        yb = nn.RNN(bwd, reverse=True, keep_order=True)(x)
        if layer["return_sequences"]:
            return jnp.concatenate([yf, yb], axis=-1)
        return jnp.concatenate([yf[:, -1], yb[:, 0]], axis=-1)
    raise AssertionError(kind)  # unreachable: _normalize_layer gates


def _make_cell(base: str, layer, dtype, name: str):
    if base == "lstm":
        return nn.OptimizedLSTMCell(layer["units"], dtype=dtype,
                                    name=name)
    if base == "gru":
        return nn.GRUCell(layer["units"], dtype=dtype, name=name)
    return nn.SimpleCell(layer["units"],
                         activation_fn=_activation(layer["activation"]),
                         dtype=dtype, name=name)


def _walk_graph(nodes, topo, input_value, name_for, dtype,
                train: bool, memos: dict):
    """Execute a parsed call graph: the one walker behind both
    ``KerasGraph.__call__`` and nested functional submodel replay.

    ``input_value(nid)`` supplies each input node's tensor (the
    top-level graph resolves multi-input column slices there; nested
    graphs are single-input and feed the call-site tensor).
    ``name_for(param)`` names parameterized submodules; ``memos``
    (param id -> created submodules) makes every call of a shared
    layer apply ONE flax module — keras's sharing semantics.
    Returns the full ``{call id: tensor}`` map."""
    by_id = {int(n["id"]): n for n in nodes}
    outs: dict[int, Any] = {}
    for nid in topo:
        node = by_id[int(nid)]
        kind = node["kind"]
        if kind == "input":
            outs[int(nid)] = input_value(int(nid))
            continue
        ins = [outs[int(i)] for i in node["inputs"]]
        if kind.startswith("merge_"):
            outs[int(nid)] = _apply_merge(kind, ins, node)
        else:
            p = int(node.get("param", node["id"]))
            outs[int(nid)] = _apply_layer(
                node, name_for(p), ins[0], dtype, train,
                memo=memos.setdefault(p, {}))
    return outs


def _graph_param_layers(graph: Mapping[str, Any]) -> dict:
    """``{param id: node}`` with one entry per LAYER (a shared layer's
    call nodes collapse to its first node) — the keras
    ``get_weights()`` unit."""
    seen: dict[int, Mapping[str, Any]] = {}
    for n in graph["nodes"]:
        seen.setdefault(int(n.get("param", n["id"])), n)
    return seen


def _apply_merge(kind: str, ins, layer=None):
    if kind == "merge_concat":
        axis = int(layer.get("axis", -1)) if layer else -1
        if axis not in (-1, ins[0].ndim - 1):
            raise NotImplementedError(
                f"Concatenate over axis {axis} of rank-{ins[0].ndim} "
                f"tensors is not supported; only the last (feature) "
                f"axis")
        return jnp.concatenate(ins, axis=-1)
    if kind == "merge_add":
        out = ins[0]
        for y in ins[1:]:
            out = out + y
        return out
    if kind == "merge_subtract":
        if len(ins) != 2:
            raise ValueError(
                f"Subtract takes exactly 2 inputs, got {len(ins)}")
        return ins[0] - ins[1]
    if kind == "merge_multiply":
        out = ins[0]
        for y in ins[1:]:
            out = out * y
        return out
    if kind == "merge_average":
        out = ins[0]
        for y in ins[1:]:
            out = out + y
        return out / len(ins)
    if kind == "merge_maximum":
        out = ins[0]
        for y in ins[1:]:
            out = jnp.maximum(out, y)
        return out
    raise AssertionError(kind)


@register_model("keras_graph")
class KerasGraph(nn.Module):
    """Flax twin of an ingested keras functional DAG.

    ``nodes`` is ``_parse_functional``'s call-node list (one node per
    LAYER CALL; a shared layer contributes several nodes carrying the
    same ``param`` id).  Parameterized nodes are named
    ``layer_{param}`` — the layer's config-list position, which is the
    keras ``get_weights()`` order — and calls sharing a ``param``
    apply one flax module (one parameter set).  ``topo`` is an
    executable order; ``outputs`` the result node ids (a 1-tuple
    returns the bare array, longer tuples return a tuple in
    ``output_layers`` order).  ``input_slices`` (multi-input models)
    map each Input node to its column slice of the single concatenated
    features array; empty means one Input taking ``x`` whole.

    ``output`` (int) is the round-3 single-output spelling, still
    honored so serialized round-3 specs and checkpoints load
    unchanged; ``outputs`` wins when non-empty.

    ``input_slices`` entries are ``(node id, start, end)`` — or
    ``(node id, start, end, dims)`` for a rank > 1 input, whose
    columns reshape to the recorded per-sample ``dims`` after slicing
    (how an image branch rides the flat concatenated row)."""

    nodes: Sequence[Mapping[str, Any]] = ()
    topo: Sequence[int] = ()
    output: int = 0
    outputs: Sequence[int] = ()
    input_slices: Sequence[Sequence[Any]] = ()
    dtype: str = "float32"

    @nn.compact
    def __call__(self, x, train: bool = False):
        dtype = jnp.dtype(self.dtype)
        x = jnp.asarray(x, dtype)
        slices = {int(s[0]): (int(s[1]), int(s[2]),
                              tuple(int(d) for d in s[3])
                              if len(s) > 3 else None)
                  for s in self.input_slices}

        def input_value(nid: int):
            if nid in slices:
                a, b, dims = slices[nid]
                piece = x[..., a:b]
                if dims is not None:
                    piece = piece.reshape(piece.shape[:-1] + dims)
                return piece
            return x

        outs = _walk_graph(self.nodes, self.topo, input_value,
                           lambda p: f"layer_{p}", dtype, train,
                           memos={})
        if self.outputs:
            result = tuple(outs[int(o)] for o in self.outputs)
            return result[0] if len(result) == 1 else result
        return outs[int(self.output)]


def _lstm_cell_params(W: np.ndarray, U: np.ndarray,
                      b: np.ndarray) -> dict:
    """Keras fused LSTM arrays -> flax ``OptimizedLSTMCell`` params.

    Keras packs the four gates along the last axis in order i, f, g(c),
    o — the same equations flax's cell computes with per-gate denses:
    input kernels ``ii/if/ig/io`` (no bias) and hidden kernels
    ``hi/hf/hg/ho`` (carrying the single keras bias)."""
    u = U.shape[0]
    if W.shape[1] != 4 * u or b.shape[0] != 4 * u:
        raise ValueError(
            f"LSTM weight shapes do not agree: kernel {W.shape}, "
            f"recurrent {U.shape}, bias {b.shape}")
    Wi, Wf, Wg, Wo = (W[:, j * u:(j + 1) * u] for j in range(4))
    Ui, Uf, Ug, Uo = (U[:, j * u:(j + 1) * u] for j in range(4))
    bi, bf, bg, bo = (b[j * u:(j + 1) * u] for j in range(4))
    return {"ii": {"kernel": Wi}, "if": {"kernel": Wf},
            "ig": {"kernel": Wg}, "io": {"kernel": Wo},
            "hi": {"kernel": Ui, "bias": bi},
            "hf": {"kernel": Uf, "bias": bf},
            "hg": {"kernel": Ug, "bias": bg},
            "ho": {"kernel": Uo, "bias": bo}}


def _map_weights(layers: Sequence[Mapping[str, Any]],
                 weights: Sequence[np.ndarray]) -> dict:
    """Keras ``get_weights()`` order -> flax variables.

    Keras lists each layer's arrays in creation order: Dense/Conv
    ``[kernel, bias]`` (kernels already HWIO / in-out, matching flax),
    Embedding ``[table]``, BatchNorm ``[gamma, beta, moving_mean,
    moving_var]``, LSTM ``[kernel (in, 4u), recurrent (u, 4u),
    bias (4u)]`` with gate order i, f, g(c), o (Bidirectional: forward
    triple then backward triple)."""
    return _map_named_weights(
        [(f"layer_{i}", layer) for i, layer in enumerate(layers)],
        weights)


def _map_graph_weights(graph: dict,
                       weights: Sequence[np.ndarray]) -> dict:
    """Weight mapping for a ``KerasGraph``: one entry per LAYER (param
    id), in config-list order — keras lists each layer's arrays once
    in ``get_weights()`` no matter how many times it is called, and
    all of a shared layer's call nodes apply that single set."""
    seen = _graph_param_layers(graph)
    return _map_named_weights(
        [(f"layer_{p}", seen[p]) for p in sorted(seen)], weights)


def _map_named_weights(named_layers, weights) -> dict:
    weights = [np.asarray(w) for w in weights]
    params: dict[str, Any] = {}
    batch_stats: dict[str, Any] = {}
    pos = 0

    def take() -> np.ndarray:
        nonlocal pos
        if pos >= len(weights):
            raise ValueError(
                f"keras weight list exhausted at array {pos}; the "
                f"architecture expects more arrays than provided")
        w = weights[pos]
        pos += 1
        return w

    _consume_layers(named_layers, take, params, batch_stats)
    if pos != len(weights):
        raise ValueError(
            f"keras weight list has {len(weights)} arrays but the "
            f"architecture consumes {pos}")
    variables: dict[str, Any] = {"params": params}
    if batch_stats:
        variables["batch_stats"] = batch_stats
    return variables


def _gru_cell_params(W: np.ndarray, U: np.ndarray,
                     b: np.ndarray) -> dict:
    """Keras fused GRU arrays (``reset_after=True``) -> flax
    ``GRUCell`` params.

    Keras packs the three gates along the last axis in order z, r, h
    and carries TWO bias rows (input-side and recurrent-side).  Flax's
    input denses (``iz/ir/in``) carry a bias while ``hz/hr`` do not,
    so the z/r recurrent biases fold into the input biases (both sit
    inside the same sigmoid, additively); the h-gate keeps them apart
    (``in`` takes the input bias, ``hn`` the recurrent one — keras
    ``reset_after=True`` multiplies exactly that term by r)."""
    u = U.shape[0]
    if W.shape[1] != 3 * u or b.shape != (2, 3 * u):
        raise ValueError(
            f"GRU weight shapes do not agree (expecting the "
            f"reset_after=True layout): kernel {W.shape}, recurrent "
            f"{U.shape}, bias {b.shape}")
    Wz, Wr, Wh = (W[:, j * u:(j + 1) * u] for j in range(3))
    Uz, Ur, Uh = (U[:, j * u:(j + 1) * u] for j in range(3))
    biz, bir, bih = (b[0, j * u:(j + 1) * u] for j in range(3))
    bhz, bhr, bhh = (b[1, j * u:(j + 1) * u] for j in range(3))
    return {"iz": {"kernel": Wz, "bias": biz + bhz},
            "ir": {"kernel": Wr, "bias": bir + bhr},
            "in": {"kernel": Wh, "bias": bih},
            "hz": {"kernel": Uz}, "hr": {"kernel": Ur},
            "hn": {"kernel": Uh, "bias": bhh}}


def _consume_layers(named_layers, take, params, batch_stats):
    """Shared weight-consumption walk for the sequential and graph
    families (keras lists arrays per layer in creation order)."""
    for name, layer in named_layers:
        kind = layer["kind"]
        if kind == "nested":
            # keras lists a nested submodel's arrays in its own layer
            # order, inline at the submodel's position
            _consume_layers(
                [(f"{name}_s{i}", sub)
                 for i, sub in enumerate(layer["layers"])],
                take, params, batch_stats)
        elif kind == "nested_graph":
            # nested functional: arrays inline at the submodel's
            # position, one entry per inner LAYER (param id) in inner
            # config order — shared inner calls consume one set
            seen = _graph_param_layers(layer["graph"])
            _consume_layers(
                [(f"{name}_g{p}", seen[p]) for p in sorted(seen)],
                take, params, batch_stats)
        elif kind in ("dense", "conv2d", "conv1d", "convtranspose2d"):
            # convtranspose2d: flax ConvTranspose(transpose_kernel=
            # True) stores the kernel in keras's own layout — as-is
            entry = {"kernel": take()}
            if layer["use_bias"]:
                entry["bias"] = take()
            params[name] = entry
        elif kind == "dwconv2d":
            dw = take()  # [k, k, cin, mult] -> grouped-conv layout
            k1, k2, cin, mult = dw.shape
            entry = {"kernel": dw.reshape(k1, k2, 1, cin * mult)}
            if layer["use_bias"]:
                entry["bias"] = take()
            params[name] = entry
        elif kind == "sepconv2d":
            dw = take()  # [k, k, in, mult] -> flax group-conv layout
            k1, k2, cin, mult = dw.shape
            params[name + "_dw"] = {
                "kernel": dw.reshape(k1, k2, 1, cin * mult)}
            pw = {"kernel": take()}
            if layer["use_bias"]:
                pw["bias"] = take()
            params[name + "_pw"] = pw
        elif kind == "embedding":
            params[name] = {"embedding": take()}
        elif kind == "batchnorm":
            params[name] = {"scale": take(), "bias": take()}
            batch_stats[name] = {"mean": take(), "var": take()}
        elif kind == "lstm":
            params[name] = _lstm_cell_params(take(), take(), take())
        elif kind == "bilstm":
            params[name + "_fwd"] = _lstm_cell_params(
                take(), take(), take())
            params[name + "_bwd"] = _lstm_cell_params(
                take(), take(), take())
        elif kind == "gru":
            params[name] = _gru_cell_params(take(), take(), take())
        elif kind == "bigru":
            params[name + "_fwd"] = _gru_cell_params(
                take(), take(), take())
            params[name + "_bwd"] = _gru_cell_params(
                take(), take(), take())
        elif kind == "simple_rnn":
            params[name] = {"i": {"kernel": take()},
                            "h": {"kernel": take()}}
            params[name]["i"]["bias"] = take()


def from_keras_json(arch_json: str,
                    weights: Sequence[np.ndarray] | None = None,
                    input_shape: Sequence[int] | None = None,
                    dtype: str = "float32"):
    """Ingest ``model.to_json()`` (+ optional ``model.get_weights()``).

    Returns ``(spec, variables)`` — a ``ModelSpec`` usable with every
    trainer (family ``keras_sequential`` for Sequential models and
    functional chains; ``keras_graph`` for true functional DAGs, whose
    kwargs carry the node graph instead of a layer list), and the
    mapped flax variables (``None`` when no weights were given; pass the variables
    as ``initial_variables=`` to continue training, or to a predictor /
    evaluator directly).  ``input_shape`` (per-sample, no batch dim) is
    required only when the JSON does not record one."""
    arch = json.loads(arch_json)
    if arch.get("class_name") in ("Functional", "Model"):
        # keras 2 called functional models "Model"; 2.4+/3 "Functional"
        graph = _parse_functional(arch)
        chain = _graph_is_chain(graph)
        if chain is not None:
            if not chain:
                raise ValueError(
                    "keras architecture contains no layers (the model "
                    "maps its input straight to output)")
            layers = chain  # lowers to the simpler sequential family
        else:
            return _graph_spec(graph, arch, weights, input_shape,
                               dtype)
    else:
        layers = _parse_arch(arch)
    if input_shape is None:
        input_shape = _infer_input_shape(arch)
        if input_shape is None:
            raise ValueError(
                "the keras JSON records no input shape (the model was "
                "never built); pass input_shape=")
    input_dtype = ("int32" if _leading_kind(layers[0]) == "embedding"
                   else "float32")
    spec = ModelSpec(family="keras_sequential",
                     kwargs={"layers": tuple(layers), "dtype": dtype},
                     input_shape=tuple(int(d) for d in input_shape),
                     input_dtype=input_dtype)
    variables = (None if weights is None
                 else _map_weights(layers, weights))
    return spec, variables


def _graph_spec(graph, arch, weights, input_shape, dtype):
    """ModelSpec + variables for a true-DAG functional model
    (``KerasGraph`` family)."""
    if graph["input_slices"]:
        # multi-input: one concatenated features array, width = the
        # inputs' total (input_shape= cannot override a recorded total)
        total = graph["input_slices"][-1][2]
        if input_shape is not None \
                and tuple(input_shape) != (total,):
            raise ValueError(
                f"multi-input model concatenates its inputs into "
                f"[N, {total}]; input_shape={tuple(input_shape)} "
                f"conflicts")
        input_shape = (total,)
    elif input_shape is None:
        input_shape = _infer_input_shape(arch)
        if input_shape is None:
            raise ValueError(
                "the keras JSON records no input shape (the model was "
                "never built); pass input_shape=")
    # int32 features only when EVERY consumer of every input node is an
    # embedding (mixed wide&deep-style inputs stay float; the embedding
    # branch casts its own slice)
    input_ids = {n["id"] for n in graph["nodes"]
                 if n["kind"] == "input"}
    consumers = [n for n in graph["nodes"]
                 if any(i in input_ids for i in n["inputs"])]
    input_dtype = ("int32" if consumers and all(
        _leading_kind(n) == "embedding" for n in consumers)
        else "float32")
    kwargs = {"nodes": tuple(graph["nodes"]),
              "topo": tuple(graph["topo"]),
              "output": graph["outputs"][0],
              "input_slices": tuple(
                  tuple(tuple(v) if isinstance(v, list) else v
                        for v in s)
                  for s in graph["input_slices"]),
              "dtype": dtype}
    if len(graph["outputs"]) > 1:
        kwargs["outputs"] = tuple(graph["outputs"])
    spec = ModelSpec(
        family="keras_graph",
        kwargs=kwargs,
        input_shape=tuple(int(d) for d in input_shape),
        input_dtype=input_dtype)
    variables = (None if weights is None
                 else _map_graph_weights(graph, weights))
    return spec, variables


def from_keras(model, dtype: str = "float32"):
    """Ingest a live keras model: ``from_keras_json(model.to_json(),
    model.get_weights())``."""
    return from_keras_json(model.to_json(), model.get_weights(),
                           dtype=dtype)

"""Keras model ingestion — the reference's serialization surface.

The reference's whole workflow starts from a Keras model: users build a
``Sequential``, the framework ships ``serialize_keras_model`` output
(architecture JSON + weight list) to workers, and every trainer returns
a Keras model (SURVEY.md §2.1 "Utils", §3.5).  This module lets those
users bring the same artifact here: ``from_keras_json`` parses the
architecture JSON into a registered flax model family
(``keras_sequential``) and maps the Keras weight list onto flax
variables, so a reference user's model drops into any trainer /
predictor / evaluator unchanged.

Keras itself is NOT required: the JSON is parsed structurally (both the
Keras 2 era format the reference produced and the Keras 3 one), and
weights are plain arrays.  When Keras *is* installed, ``from_keras``
takes a live model.

Supported layers: InputLayer, Dense, Activation, Dropout, Flatten,
Conv2D, MaxPooling2D, AveragePooling2D, GlobalAveragePooling2D,
Embedding, BatchNormalization, LSTM, Bidirectional(LSTM) — the
reference's IMDB workflow shape.  Anything else raises with the layer
name so the gap is visible, not silent.
"""

from __future__ import annotations

import json
from typing import Any, Mapping, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.models.core import ModelSpec, register_model

_ACTIVATIONS = {
    "linear": lambda x: x,
    "relu": nn.relu,
    "relu6": nn.relu6,
    "elu": nn.elu,
    "selu": nn.selu,
    "gelu": nn.gelu,
    "sigmoid": nn.sigmoid,
    "tanh": nn.tanh,
    "softmax": lambda x: nn.softmax(x, axis=-1),
    "softplus": nn.softplus,
    "swish": nn.swish,
    "silu": nn.silu,
    "leaky_relu": nn.leaky_relu,
}


def _activation(name: str):
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise NotImplementedError(
            f"keras activation {name!r} is not supported; supported: "
            f"{sorted(_ACTIVATIONS)}") from None


def _pair(v) -> tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    return tuple(int(x) for x in v)  # type: ignore[return-value]


def _normalize_layer(class_name: str, cfg: Mapping[str, Any]) -> Optional[dict]:
    """One keras layer config -> a minimal normalized dict (or ``None``
    for structural no-ops).  Only the fields the forward pass needs
    survive, so the normalized form is stable across keras versions."""
    if class_name == "InputLayer":
        return None
    if class_name == "Dense":
        return {"kind": "dense", "units": int(cfg["units"]),
                "use_bias": bool(cfg.get("use_bias", True)),
                "activation": cfg.get("activation", "linear")}
    if class_name == "Activation":
        return {"kind": "activation",
                "activation": cfg["activation"]}
    if class_name == "Dropout":
        return {"kind": "dropout", "rate": float(cfg["rate"])}
    if class_name == "Flatten":
        return {"kind": "flatten"}
    if class_name == "Conv2D":
        if cfg.get("data_format") not in (None, "channels_last"):
            raise NotImplementedError(
                "only channels_last Conv2D is supported")
        if _pair(cfg.get("dilation_rate", 1)) != (1, 1):
            raise NotImplementedError(
                "dilated Conv2D is not supported")
        if int(cfg.get("groups", 1)) != 1:
            raise NotImplementedError(
                "grouped Conv2D is not supported")
        return {"kind": "conv2d", "filters": int(cfg["filters"]),
                "kernel_size": list(_pair(cfg["kernel_size"])),
                "strides": list(_pair(cfg.get("strides", 1))),
                "padding": str(cfg.get("padding", "valid")).upper(),
                "use_bias": bool(cfg.get("use_bias", True)),
                "activation": cfg.get("activation", "linear")}
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        pool = _pair(cfg.get("pool_size", 2))
        return {"kind": "pool",
                "op": "max" if class_name.startswith("Max") else "avg",
                "pool_size": list(pool),
                "strides": list(_pair(cfg.get("strides") or pool)),
                "padding": str(cfg.get("padding", "valid")).upper()}
    if class_name == "GlobalAveragePooling2D":
        return {"kind": "global_avg_pool"}
    if class_name == "Embedding":
        if cfg.get("mask_zero"):
            raise NotImplementedError(
                "Embedding(mask_zero=True) is not supported: keras "
                "propagates the mask into recurrent layers, which the "
                "ingested model would silently ignore on padded "
                "sequences — rebuild natively (models.BiLSTMClassifier "
                "masks pads) or re-export without mask_zero")
        return {"kind": "embedding",
                "input_dim": int(cfg["input_dim"]),
                "output_dim": int(cfg["output_dim"])}
    if class_name == "LSTM":
        return _normalize_lstm(cfg, kind="lstm")
    if class_name == "Bidirectional":
        inner = cfg.get("layer", {})
        if inner.get("class_name") != "LSTM":
            raise NotImplementedError(
                f"Bidirectional({inner.get('class_name')!r}) is not "
                f"supported; only Bidirectional(LSTM)")
        if cfg.get("merge_mode", "concat") != "concat":
            raise NotImplementedError(
                f"Bidirectional merge_mode="
                f"{cfg.get('merge_mode')!r} is not supported; only "
                f"'concat'")
        return _normalize_lstm(inner.get("config", {}), kind="bilstm")
    if class_name == "BatchNormalization":
        if not (cfg.get("center", True) and cfg.get("scale", True)):
            raise NotImplementedError(
                "BatchNormalization with center=False or scale=False "
                "is not supported (the weight mapping assumes "
                "[gamma, beta, mean, var])")
        axis = cfg.get("axis", -1)
        if isinstance(axis, (list, tuple)) and len(axis) == 1:
            axis = axis[0]
        if axis != -1:
            raise NotImplementedError(
                f"BatchNormalization over axis {axis!r} is not "
                f"supported; only the last (channels) axis is")
        return {"kind": "batchnorm",
                "epsilon": float(cfg.get("epsilon", 1e-3)),
                "momentum": float(cfg.get("momentum", 0.99))}
    raise NotImplementedError(
        f"keras layer {class_name!r} is not supported by the "
        f"ingestion shim (Dense/Conv2D/pooling/Embedding/BatchNorm/"
        f"LSTM/Bidirectional(LSTM) stacks are); rebuild this model "
        f"natively with distkeras_tpu.models instead")


def _normalize_lstm(cfg: Mapping[str, Any], kind: str) -> dict:
    """LSTM config checks: only the (modern keras default) gate
    functions match flax's LSTMCell equations exactly."""
    if cfg.get("activation", "tanh") != "tanh" or \
            cfg.get("recurrent_activation", "sigmoid") != "sigmoid":
        raise NotImplementedError(
            f"LSTM with activation={cfg.get('activation')!r} / "
            f"recurrent_activation={cfg.get('recurrent_activation')!r} "
            f"is not supported; only tanh/sigmoid (note: keras<2.3 "
            f"defaulted recurrent_activation to 'hard_sigmoid')")
    if not cfg.get("use_bias", True):
        raise NotImplementedError("LSTM(use_bias=False) not supported")
    if cfg.get("go_backwards"):
        raise NotImplementedError(
            "LSTM(go_backwards=True) not supported (use Bidirectional)")
    if cfg.get("dropout") or cfg.get("recurrent_dropout"):
        raise NotImplementedError(
            "LSTM dropout/recurrent_dropout are not supported — "
            "silently dropping them would change training behavior; "
            "re-export without them or add a standalone Dropout layer")
    if cfg.get("stateful"):
        raise NotImplementedError("stateful LSTM is not supported")
    return {"kind": kind, "units": int(cfg["units"]),
            "return_sequences": bool(cfg.get("return_sequences",
                                             False))}


def _infer_input_shape(arch: Mapping[str, Any]) -> tuple[int, ...] | None:
    """Per-sample input shape from the first layer's
    ``batch_shape`` (keras 3) / ``batch_input_shape`` (keras 1/2),
    when recorded."""
    config = arch.get("config", {})
    raw_layers = (config if isinstance(config, list)
                  else config.get("layers", []))
    for entry in raw_layers:
        cfg = entry.get("config", {})
        shape = cfg.get("batch_shape") or cfg.get("batch_input_shape")
        if shape is not None:
            if any(d is None for d in shape[1:]):
                return None  # variable-length dims: caller must pass one
            return tuple(int(d) for d in shape[1:])
    return None


def _inbound_names(node) -> list[str]:
    """Predecessor layer names from one inbound-node entry.

    Keras 2 era (the reference's format): a list of
    ``[name, node_index, tensor_index, kwargs]`` quads.  Keras 3: a
    dict whose args/kwargs embed ``__keras_tensor__`` objects carrying
    ``keras_history = [name, node, tensor]``."""
    names: list[str] = []
    if isinstance(node, Mapping):
        def walk(obj):
            if isinstance(obj, Mapping):
                if obj.get("class_name") == "__keras_tensor__":
                    names.append(
                        obj.get("config", {})["keras_history"][0])
                else:
                    for v in obj.values():
                        walk(v)
            elif isinstance(obj, (list, tuple)):
                for v in obj:
                    walk(v)
        walk(node.get("args", []))
        walk(node.get("kwargs", {}))
    else:
        for item in node:
            names.append(item[0])
    return names


def _single_ref_name(refs) -> str | None:
    """Layer name out of ``input_layers``/``output_layers``, which is
    ``[name, 0, 0]`` (one ref, keras 3) or ``[[name, 0, 0], ...]``
    (list of refs, keras 2) — ``None`` when there is more than one."""
    if not refs:
        return None
    if isinstance(refs[0], str):  # single [name, 0, 0]
        return refs[0]
    if len(refs) != 1:
        return None
    return refs[0][0]


def _parse_functional(arch: Mapping[str, Any]) -> list[dict]:
    """Linear-chain functional ``Model(inputs, outputs)`` graphs →
    the same normalized layer list as Sequential.

    True DAGs are rejected with the offending merge/branch layer named
    (VERDICT.md r2 Missing #1): multi-input models, layers with
    multiple inbound tensors (Add/Concatenate/...), shared layers
    (called more than once), and branching outputs all raise."""
    config = arch.get("config", {})
    raw_layers = config.get("layers", [])
    if not raw_layers:
        raise ValueError("keras architecture contains no layers")
    by_name: dict[str, dict] = {}
    preds: dict[str, list[str]] = {}
    for entry in raw_layers:
        name = entry.get("name") or entry.get("config", {}).get("name")
        if name is None:
            raise ValueError("functional layer entry has no name")
        by_name[name] = entry
        nodes = entry.get("inbound_nodes", [])
        if len(nodes) > 1:
            raise NotImplementedError(
                f"layer {name!r} is called {len(nodes)} times (shared "
                f"layer); only linear-chain functional graphs are "
                f"supported")
        preds[name] = _inbound_names(nodes[0]) if nodes else []

    in_name = _single_ref_name(config.get("input_layers", []))
    out_name = _single_ref_name(config.get("output_layers", []))
    if in_name is None or out_name is None:
        raise NotImplementedError(
            "multi-input / multi-output functional models are not "
            "supported; only single-input single-output linear chains "
            "(rebuild true DAGs natively with distkeras_tpu.models, "
            "e.g. models.WideDeep for two-branch configs)")

    for name, p in preds.items():
        if len(p) > 1:
            cls = by_name[name]["class_name"]
            raise NotImplementedError(
                f"functional graph is not a linear chain: layer "
                f"{name!r} ({cls}) merges {len(p)} inputs "
                f"({', '.join(p)}); merge layers make a true DAG — "
                f"rebuild natively with distkeras_tpu.models")

    successors: dict[str, list[str]] = {}
    for name, p in preds.items():
        for q in p:
            successors.setdefault(q, []).append(name)
    for name, succ in successors.items():
        if len(succ) > 1:
            raise NotImplementedError(
                f"functional graph is not a linear chain: layer "
                f"{name!r} branches into {', '.join(sorted(succ))}")

    # walk the chain from input to output
    chain, cur = [in_name], in_name
    while cur != out_name:
        nxt = successors.get(cur, [])
        if not nxt:
            raise ValueError(
                f"functional graph ends at {cur!r} without reaching "
                f"the declared output {out_name!r}")
        cur = nxt[0]
        chain.append(cur)
    unused = set(by_name) - set(chain)
    if unused:
        raise NotImplementedError(
            f"functional graph has layers outside the input->output "
            f"chain: {sorted(unused)}")

    layers = []
    for name in chain:
        entry = by_name[name]
        norm = _normalize_layer(entry["class_name"],
                                entry.get("config", {}))
        if norm is not None:
            layers.append(norm)
    if not layers:
        raise ValueError("keras architecture contains no layers")
    return layers


def _parse_arch(arch: Mapping[str, Any]) -> list[dict]:
    class_name = arch.get("class_name")
    if class_name in ("Functional", "Model"):
        # keras 2 called functional models "Model"; 2.4+/3 "Functional"
        return _parse_functional(arch)
    if class_name != "Sequential":
        raise NotImplementedError(
            f"only Sequential and linear-chain Functional keras "
            f"models are supported, got {class_name!r}")
    config = arch.get("config", {})
    # Keras 1 stored the layer list directly under config; 2/3 under
    # config["layers"].
    raw_layers = (config if isinstance(config, list)
                  else config.get("layers", []))
    layers = []
    for entry in raw_layers:
        norm = _normalize_layer(entry["class_name"],
                                entry.get("config", {}))
        if norm is not None:
            layers.append(norm)
    if not layers:
        raise ValueError("keras architecture contains no layers")
    return layers


@register_model("keras_sequential")
class KerasSequential(nn.Module):
    """Flax twin of an ingested keras ``Sequential``.

    ``layers`` is the normalized layer list ``_parse_arch`` produces —
    plain JSON data, so specs built from keras models serialize through
    ``ModelSpec``/checkpoints like any native family.  Parameterized
    layers are named ``layer_{i}`` (their position in the *normalized*
    list), which is what makes the keras weight-list mapping
    deterministic."""

    layers: Sequence[Mapping[str, Any]] = ()
    dtype: str = "float32"

    @nn.compact
    def __call__(self, x, train: bool = False):
        dtype = jnp.dtype(self.dtype)
        x = jnp.asarray(x, dtype)
        for i, layer in enumerate(self.layers):
            kind = layer["kind"]
            name = f"layer_{i}"
            if kind == "dense":
                # contracts the last axis, any rank — keras semantics
                x = nn.Dense(layer["units"],
                             use_bias=layer["use_bias"],
                             dtype=dtype, name=name)(x)
                x = _activation(layer["activation"])(x)
            elif kind == "activation":
                x = _activation(layer["activation"])(x)
            elif kind == "dropout":
                x = nn.Dropout(layer["rate"],
                               deterministic=not train)(x)
            elif kind == "flatten":
                x = x.reshape((x.shape[0], -1))
            elif kind == "conv2d":
                x = nn.Conv(layer["filters"],
                            tuple(layer["kernel_size"]),
                            strides=tuple(layer["strides"]),
                            padding=layer["padding"],
                            use_bias=layer["use_bias"],
                            dtype=dtype, name=name)(x)
                x = _activation(layer["activation"])(x)
            elif kind == "pool":
                fn = nn.max_pool if layer["op"] == "max" else nn.avg_pool
                x = fn(x, tuple(layer["pool_size"]),
                       strides=tuple(layer["strides"]),
                       padding=layer["padding"])
            elif kind == "global_avg_pool":
                x = x.mean(axis=(1, 2))
            elif kind == "embedding":
                x = nn.Embed(layer["input_dim"], layer["output_dim"],
                             dtype=dtype, name=name)(
                                 x.astype(jnp.int32))
            elif kind == "batchnorm":
                x = nn.BatchNorm(use_running_average=not train,
                                 epsilon=layer["epsilon"],
                                 momentum=layer["momentum"],
                                 dtype=dtype, name=name)(x)
            elif kind == "lstm":
                # the RNN wrapper owns no params; naming the CELL is
                # what pins the weight-mapping path
                y = nn.RNN(nn.OptimizedLSTMCell(layer["units"],
                                                dtype=dtype,
                                                name=name))(x)
                x = y if layer["return_sequences"] else y[:, -1]
            elif kind == "bilstm":
                # keras Bidirectional(LSTM, merge_mode='concat'):
                # backward outputs are time-aligned (keep_order); its
                # "last" output is the one at original index 0
                yf = nn.RNN(nn.OptimizedLSTMCell(
                    layer["units"], dtype=dtype, name=name + "_fwd"))(x)
                yb = nn.RNN(nn.OptimizedLSTMCell(
                    layer["units"], dtype=dtype, name=name + "_bwd"),
                    reverse=True, keep_order=True)(x)
                if layer["return_sequences"]:
                    x = jnp.concatenate([yf, yb], axis=-1)
                else:
                    x = jnp.concatenate([yf[:, -1], yb[:, 0]], axis=-1)
            else:  # unreachable: _normalize_layer gates kinds
                raise AssertionError(kind)
        return x


def _lstm_cell_params(W: np.ndarray, U: np.ndarray,
                      b: np.ndarray) -> dict:
    """Keras fused LSTM arrays -> flax ``OptimizedLSTMCell`` params.

    Keras packs the four gates along the last axis in order i, f, g(c),
    o — the same equations flax's cell computes with per-gate denses:
    input kernels ``ii/if/ig/io`` (no bias) and hidden kernels
    ``hi/hf/hg/ho`` (carrying the single keras bias)."""
    u = U.shape[0]
    if W.shape[1] != 4 * u or b.shape[0] != 4 * u:
        raise ValueError(
            f"LSTM weight shapes do not agree: kernel {W.shape}, "
            f"recurrent {U.shape}, bias {b.shape}")
    Wi, Wf, Wg, Wo = (W[:, j * u:(j + 1) * u] for j in range(4))
    Ui, Uf, Ug, Uo = (U[:, j * u:(j + 1) * u] for j in range(4))
    bi, bf, bg, bo = (b[j * u:(j + 1) * u] for j in range(4))
    return {"ii": {"kernel": Wi}, "if": {"kernel": Wf},
            "ig": {"kernel": Wg}, "io": {"kernel": Wo},
            "hi": {"kernel": Ui, "bias": bi},
            "hf": {"kernel": Uf, "bias": bf},
            "hg": {"kernel": Ug, "bias": bg},
            "ho": {"kernel": Uo, "bias": bo}}


def _map_weights(layers: Sequence[Mapping[str, Any]],
                 weights: Sequence[np.ndarray]) -> dict:
    """Keras ``get_weights()`` order -> flax variables.

    Keras lists each layer's arrays in creation order: Dense/Conv
    ``[kernel, bias]`` (kernels already HWIO / in-out, matching flax),
    Embedding ``[table]``, BatchNorm ``[gamma, beta, moving_mean,
    moving_var]``, LSTM ``[kernel (in, 4u), recurrent (u, 4u),
    bias (4u)]`` with gate order i, f, g(c), o (Bidirectional: forward
    triple then backward triple)."""
    weights = [np.asarray(w) for w in weights]
    params: dict[str, Any] = {}
    batch_stats: dict[str, Any] = {}
    pos = 0

    def take() -> np.ndarray:
        nonlocal pos
        if pos >= len(weights):
            raise ValueError(
                f"keras weight list exhausted at array {pos}; the "
                f"architecture expects more arrays than provided")
        w = weights[pos]
        pos += 1
        return w

    for i, layer in enumerate(layers):
        kind, name = layer["kind"], f"layer_{i}"
        if kind in ("dense", "conv2d"):
            entry = {"kernel": take()}
            if layer["use_bias"]:
                entry["bias"] = take()
            params[name] = entry
        elif kind == "embedding":
            params[name] = {"embedding": take()}
        elif kind == "batchnorm":
            params[name] = {"scale": take(), "bias": take()}
            batch_stats[name] = {"mean": take(), "var": take()}
        elif kind == "lstm":
            params[name] = _lstm_cell_params(take(), take(), take())
        elif kind == "bilstm":
            params[name + "_fwd"] = _lstm_cell_params(
                take(), take(), take())
            params[name + "_bwd"] = _lstm_cell_params(
                take(), take(), take())
    if pos != len(weights):
        raise ValueError(
            f"keras weight list has {len(weights)} arrays but the "
            f"architecture consumes {pos}")
    variables: dict[str, Any] = {"params": params}
    if batch_stats:
        variables["batch_stats"] = batch_stats
    return variables


def from_keras_json(arch_json: str,
                    weights: Sequence[np.ndarray] | None = None,
                    input_shape: Sequence[int] | None = None,
                    dtype: str = "float32"):
    """Ingest ``model.to_json()`` (+ optional ``model.get_weights()``).

    Returns ``(spec, variables)`` — a ``ModelSpec`` of family
    ``keras_sequential`` usable with every trainer, and the mapped flax
    variables (``None`` when no weights were given; pass the variables
    as ``initial_variables=`` to continue training, or to a predictor /
    evaluator directly).  ``input_shape`` (per-sample, no batch dim) is
    required only when the JSON does not record one."""
    arch = json.loads(arch_json)
    layers = _parse_arch(arch)
    if input_shape is None:
        input_shape = _infer_input_shape(arch)
        if input_shape is None:
            raise ValueError(
                "the keras JSON records no input shape (the model was "
                "never built); pass input_shape=")
    input_dtype = ("int32" if layers[0]["kind"] == "embedding"
                   else "float32")
    spec = ModelSpec(family="keras_sequential",
                     kwargs={"layers": tuple(layers), "dtype": dtype},
                     input_shape=tuple(int(d) for d in input_shape),
                     input_dtype=input_dtype)
    variables = (None if weights is None
                 else _map_weights(layers, weights))
    return spec, variables


def from_keras(model, dtype: str = "float32"):
    """Ingest a live keras model: ``from_keras_json(model.to_json(),
    model.get_weights())``."""
    return from_keras_json(model.to_json(), model.get_weights(),
                           dtype=dtype)

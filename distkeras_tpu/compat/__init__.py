from distkeras_tpu.compat.keras import (  # noqa: F401
    KerasSequential,
    from_keras,
    from_keras_json,
)

"""Speculative decoding for ``serving.DecodeEngine`` — proposers and
the acceptance rule.

PERF.md §18 measured autoregressive decode at ~94% of nominal HBM
bandwidth: there is no kernel left to win, so every further decode
token/s must come from an ALGORITHM that trades abundant FLOPs for
scarce bandwidth.  Speculative decoding (Leviathan et al. 2023) is
exactly that trade: a cheap PROPOSER guesses the next ``k`` tokens,
and one verification pass of the target model scores all ``k + 1``
positions at once — the per-token cost of the big static cache read
is amortized over every accepted token, and the greedy acceptance
rule makes the output byte-identical to plain decode by construction
(a wrong guess costs FLOPs, never correctness).

Two proposers, per ``DecodeEngine(speculative=...)``:

* ``"ngram"`` — model-free prompt-lookup drafting (Saxena 2023): the
  last ``ngram`` tokens of the slot's prompt+generated ledger are
  matched against the ledger's own history, and the tokens that
  FOLLOWED the most recent earlier occurrence are proposed.  Zero
  extra device memory, zero proposer FLOPs; it wins exactly when the
  output re-treads its context (summarization, code edits, RAG).
* ``"draft"`` — a smaller ``TransformerLM`` sharing the vocab runs
  ``k`` cached T=1 greedy steps per slot per engine step, with its
  own per-pool envelope KV cache.  Draft KV is always
  RECOMPUTE-class state: it is never swapped to host by preemption
  and is rebuilt from the token ledger (one bounded-shape prefill)
  whenever it is invalidated — admission, readmission, weight swap.

The module is engine-agnostic on purpose: ``normalize`` validates the
user-facing config dict, ``ngram_propose`` is pure host-side numpy,
and the draft program factories return jitted callables the engine
owns (trace-time compile counters stay in ``serving`` so the compile
guard sees one counter namespace).
"""

from __future__ import annotations

from typing import Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.models.generate import _decode_model, decode_step

#: accepted ``proposer`` spellings for ``DecodeEngine(speculative=)``
PROPOSERS = ("ngram", "draft")


def normalize(cfg, *, vocab_size: int, max_len: int) -> Optional[dict]:
    """Validate and normalize a ``speculative=`` engine config.

    Returns ``None`` (speculation off) or a dict with keys
    ``proposer`` (``"ngram"`` | ``"draft"``), ``k`` (proposal window,
    >= 1), ``ngram`` (match length for the n-gram proposer, >= 1),
    and — for the draft proposer — ``draft_model`` (a decode-mode
    ``TransformerLM``) plus ``draft_variables``.  The draft model
    must share the target's vocab (the acceptance rule compares token
    ids) and its ``max_len`` must cover every bucket envelope (its
    per-pool KV cache is cloned at the bucket envelope).
    """
    if cfg is None:
        return None
    if not isinstance(cfg, Mapping):
        raise ValueError(
            f"speculative must be a mapping (or None); got "
            f"{type(cfg).__name__}")
    unknown = set(cfg) - {"proposer", "k", "ngram", "draft_model",
                          "draft_variables"}
    if unknown:
        raise ValueError(
            f"speculative config has unknown keys {sorted(unknown)}; "
            "expected proposer/k/ngram/draft_model/draft_variables")
    proposer = cfg.get("proposer", "ngram")
    if proposer not in PROPOSERS:
        raise ValueError(
            f"speculative proposer must be one of {PROPOSERS}; got "
            f"{proposer!r}")
    k = int(cfg.get("k", 4))
    if k < 1:
        raise ValueError(f"speculative k must be >= 1; got {k}")
    ngram = int(cfg.get("ngram", 2))
    if ngram < 1:
        raise ValueError(
            f"speculative ngram must be >= 1; got {ngram}")
    out = {"proposer": proposer, "k": k, "ngram": ngram,
           "draft_model": None, "draft_variables": None}
    if proposer == "draft":
        if cfg.get("draft_model") is None:
            raise ValueError(
                "speculative proposer 'draft' needs a draft_model")
        if cfg.get("draft_variables") is None:
            raise ValueError(
                "speculative proposer 'draft' needs draft_variables")
        draft = _decode_model(cfg["draft_model"])
        if draft.vocab_size != vocab_size:
            raise ValueError(
                f"draft_model vocab_size={draft.vocab_size} must "
                f"equal the target's ({vocab_size}) — the acceptance "
                "rule compares token ids")
        if draft.max_len < max_len:
            raise ValueError(
                f"draft_model max_len={draft.max_len} must cover the "
                f"target's max_len={max_len} — every bucket envelope "
                "clones a draft cache at its own length")
        out["draft_model"] = draft
        out["draft_variables"] = dict(cfg["draft_variables"])
    return out


def ngram_propose(ledger: np.ndarray, k: int, n: int) -> np.ndarray:
    """Prompt-lookup drafting over one slot's token ledger.

    Matches the ledger's last ``n`` tokens against every earlier
    position (most recent occurrence wins — recency beats frequency
    for repetitive suffixes) and proposes up to ``k`` tokens that
    followed the match.  Returns an int32 array of length 0..k; an
    empty result means "no guess" and the engine falls back to the
    plain single-token verify for that slot this step.
    """
    ledger = np.asarray(ledger, np.int32)
    t = len(ledger)
    if t < n + 1:
        return np.empty((0,), np.int32)
    pat = ledger[t - n:]
    # candidate match starts: the pattern may match anywhere ending
    # strictly before the ledger tail (a match ending at the tail is
    # the pattern itself)
    for s in range(t - n - 1, -1, -1):
        if np.array_equal(ledger[s:s + n], pat):
            lo = s + n
            return ledger[lo:lo + k].copy()
    return np.empty((0,), np.int32)


def make_draft_propose(dec, env: int, k: int, pad_id: int,
                       on_trace=None):
    """Compiled batched draft proposer for one pool: ``k`` cached
    greedy T=1 steps over every slot at once (``slot_pos`` scatter,
    the engine's own step idiom).  Dead slots (``live[s]`` False)
    re-write row ``env - 1`` of the DRAFT cache — harmless by the
    eligibility bound: a live slot's draft rows never reach past
    ``env - 2`` (see ``serving`` — ``rem > k`` plus the routing
    invariant ``t_p + max_new <= env``), so the dead row is never
    read.  Greedy only: speculation requires ``temperature == 0``.

    The scan runs ``k + 1`` steps, one MORE than the proposals it
    returns: step ``k`` writes the k-th proposal's own K/V row and
    its output is discarded.  That keeps the draft-cache invariant
    "rows ``0..L-2`` written, feed token = ledger's last" true after
    EVERY commit length — including full acceptance, where the
    committed ledger reaches one past the last proposal — so the
    engine never needs a variable-length catch-up pass (which would
    break the bounded compiled-program set).

    Returns ``draft_propose(variables, cache, tok, pos, live) ->
    (cache, props)`` with ``props[k, slots]`` int32.  ``on_trace``
    runs at trace time (the engine's compile-guard counter hook).
    """

    def propose_impl(variables, cache, tok, pos, live):
        if on_trace is not None:
            on_trace()
        params = {"params": variables["params"]}

        def body(carry, _):
            cache, tok, pos = carry
            step_pos = jnp.where(live, jnp.minimum(pos, env - 1),
                                 env - 1)
            cache, nxt = decode_step(dec, params, cache, tok,
                                     slot_pos=step_pos,
                                     temperature=0.0)
            nxt = jnp.where(live, nxt, pad_id)
            return (cache, nxt, pos + 1), nxt

        (cache, _, _), props = jax.lax.scan(
            body, (cache, tok, pos), None, length=k + 1)
        return cache, props[:k]

    return propose_impl


def make_draft_prefill(dec, on_trace=None):
    """Compiled draft-cache rebuild for one slot: run the ledger's
    tokens (all but the last — that one is the next step's feed)
    through the draft model from position 0 and install the fresh
    envelope into the pool-shaped draft cache at ``slot``.  The whole
    slot envelope is replaced, so a slot inherited dirty from a
    previous request is clean by construction; right-pad rows sit
    beyond every causal horizon until overwritten (the engine's
    standing prefill argument).

    Returns ``draft_prefill(variables, cache, tokens, slot) ->
    cache`` with ``tokens`` a ``[1, t_pad]`` int32 chunk.
    """

    def prefill_impl(variables, cache, tokens, slot):
        if on_trace is not None:
            on_trace(tokens.shape[1])
        params = {"params": variables["params"]}
        # fresh [1, ...] cache (mutable init), merged over the slot;
        # logits are sliced to one row by decode mode and discarded
        _, st = dec.apply(params, tokens, mutable=["cache"])

        def merge(pool_leaf, new_leaf):
            if jnp.ndim(new_leaf) == 0:  # scalar pos: host-owned
                return pool_leaf
            return jax.lax.dynamic_update_slice(
                pool_leaf, new_leaf,
                (slot,) + (0,) * (new_leaf.ndim - 1))

        return jax.tree_util.tree_map(merge, cache, st["cache"])

    return prefill_impl


def accept_length(proposed: np.ndarray, greedy: np.ndarray) -> int:
    """The greedy acceptance rule: the longest prefix of ``proposed``
    that the target model would itself have generated.  ``greedy[j]``
    is the target's argmax AFTER seeing proposal ``j`` tokens deep
    (``greedy[0]`` follows the committed context alone), so proposal
    ``j`` (0-based) is accepted iff every earlier proposal was and
    ``proposed[j] == greedy[j]``.  Bonus-token logic lives in the
    engine: position ``n`` of ``greedy`` is always committable.
    """
    n = 0
    for j in range(len(proposed)):
        if int(proposed[j]) != int(greedy[j]):
            break
        n += 1
    return n

"""Worker machinery: the on-chip training loop.

TPU-native redesign of the reference's ``distkeras/workers.py`` (SURVEY.md
§3.2): where the reference's worker is a Python closure shipped into a
Spark task that calls ``model.train_on_batch`` and crosses the Python ↔
backend boundary *every step*, the rebuild's worker is a jitted
``train_step`` scanned over a window of batches — the whole communication
window executes on-device in one XLA program (the hot-loop fix called out
in SURVEY.md §3.2 observations).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import optax
from flax import struct

from distkeras_tpu.ops.losses import resolve_loss

Pytree = Any


# ---------------------------------------------------------------------------
# Optimizers, resolvable by Keras-style names (reference workers compile the
# model with a `worker_optimizer` string — SURVEY.md §2.1 Worker base).
# ---------------------------------------------------------------------------

OPTIMIZERS: dict[str, Callable[..., optax.GradientTransformation]] = {
    "sgd": lambda lr=0.01, **kw: optax.sgd(lr, **kw),
    "momentum": lambda lr=0.01, m=0.9, **kw: optax.sgd(lr, momentum=m, **kw),
    "nesterov": lambda lr=0.01, m=0.9, **kw: optax.sgd(
        lr, momentum=m, nesterov=True, **kw),
    "adam": lambda lr=0.001, **kw: optax.adam(lr, **kw),
    "adagrad": lambda lr=0.01, **kw: optax.adagrad(lr, **kw),
    "rmsprop": lambda lr=0.001, **kw: optax.rmsprop(lr, **kw),
    "adamw": lambda lr=0.001, **kw: optax.adamw(lr, **kw),
}


SCHEDULES: dict[str, Callable[..., Any]] = {
    "constant": lambda value: optax.constant_schedule(value),
    "cosine": optax.cosine_decay_schedule,
    "exponential": optax.exponential_decay,
    "warmup_cosine": optax.warmup_cosine_decay_schedule,
    "piecewise_constant": lambda init_value, boundaries_and_scales:
        optax.piecewise_constant_schedule(
            init_value, {int(k): float(v)
                         for k, v in boundaries_and_scales.items()}),
}


def resolve_schedule(spec):
    """Learning-rate spec -> something optax accepts as a rate.

    ``spec`` may be a float (constant), a callable (an optax schedule,
    passed through), or a JSON-friendly dict
    ``{"schedule": <name>, **kwargs}`` with optax's own kwarg names —
    e.g. ``{"schedule": "cosine", "init_value": 0.1,
    "decay_steps": 1000}``.  Schedules advance with the optimizer's
    update count: per-worker local steps under the PS trainers, global
    steps under Single/Sync.
    """
    import numbers

    if spec is None or isinstance(spec, numbers.Real) or callable(spec):
        return spec  # numbers.Real covers numpy scalar types too
    if hasattr(spec, "dtype") and getattr(spec, "ndim", None) == 0:
        return spec  # 0-d array scalar — optax takes it directly
    if isinstance(spec, Mapping):
        kwargs = dict(spec)
        name = kwargs.pop("schedule", None)
        if name not in SCHEDULES:
            raise KeyError(f"unknown schedule {name!r}; known: "
                           f"{sorted(SCHEDULES)}")
        return SCHEDULES[name](**kwargs)
    raise TypeError(f"cannot resolve a learning rate from {type(spec)}")


def resolve_optimizer(optimizer, learning_rate=None,
                      **kwargs) -> optax.GradientTransformation:
    """String name / optax transform -> optax transform.
    ``learning_rate`` accepts anything ``resolve_schedule`` does."""
    if isinstance(optimizer, optax.GradientTransformation):
        return optimizer
    if isinstance(optimizer, str):
        if optimizer not in OPTIMIZERS:
            raise KeyError(f"unknown optimizer {optimizer!r}; known: "
                           f"{sorted(OPTIMIZERS)}")
        if learning_rate is not None:
            kwargs["lr"] = resolve_schedule(learning_rate)
        return OPTIMIZERS[optimizer](**kwargs)
    raise TypeError(f"cannot resolve optimizer from {type(optimizer)}")


# ---------------------------------------------------------------------------
# Train state.
# ---------------------------------------------------------------------------


class TrainState(struct.PyTreeNode):
    """Per-worker training state.

    ``model_state`` carries non-parameter collections (e.g. BatchNorm
    ``batch_stats``); it stays worker-local under the PS trainers —
    parameter-server rules exchange ``params`` only (SURVEY.md §7 L1).
    """

    step: jnp.ndarray
    params: Pytree
    opt_state: Pytree
    model_state: Mapping[str, Pytree]
    rng: jax.Array

    @classmethod
    def create(cls, variables: Mapping[str, Pytree],
               tx: optax.GradientTransformation,
               rng: jax.Array) -> "TrainState":
        params = variables["params"]
        model_state = {k: v for k, v in variables.items() if k != "params"}
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=tx.init(params), model_state=model_state,
                   rng=rng)

    def variables(self) -> dict[str, Pytree]:
        return {"params": self.params, **self.model_state}


# ---------------------------------------------------------------------------
# Jitted step + window runner.
# ---------------------------------------------------------------------------


def make_train_step(model, loss, tx: optax.GradientTransformation,
                    features_col: str = "features",
                    label_col: str = "label"):
    """Build ``step(state, batch) -> (state, metrics)``.

    Handles dropout rngs and mutable collections (batch_stats) generically;
    pure and jittable, so it can be ``vmap``-ed per worker and ``scan``-ed
    over a communication window.

    MULTI-OUTPUT models (tuple forward — e.g. an ingested two-head
    keras DAG): pass ``loss`` as a sequence of per-head losses and
    ``label_col`` as the matching sequence of label columns; the
    objective is their sum (plus any sown auxiliary losses).
    """
    multi = isinstance(loss, (list, tuple))
    if multi != isinstance(label_col, (list, tuple)):
        raise ValueError(
            "loss and label_col must both be sequences (one per "
            "output head) or both single values; got "
            f"loss={loss!r}, label_col={label_col!r}")
    if multi:
        if len(loss) != len(label_col):
            raise ValueError(
                f"{len(loss)} losses vs {len(label_col)} label "
                f"columns — one of each per output head")
        head_fns = [resolve_loss(l) for l in loss]

        def loss_fn(logits, ys):
            if not (isinstance(logits, tuple)
                    and len(logits) == len(head_fns)):
                raise ValueError(
                    f"model produced "
                    f"{len(logits) if isinstance(logits, tuple) else 1}"
                    f" output head(s) but {len(head_fns)} losses were "
                    f"configured")
            total = jnp.float32(0.0)
            for fn, lg, y in zip(head_fns, logits, ys):
                total = total + fn(lg, y)
            return total
    else:
        single_fn = resolve_loss(loss)

        def loss_fn(logits, y):
            if isinstance(logits, tuple):
                raise ValueError(
                    "multi-output model needs a sequence of losses "
                    "and label columns (one per head); got a single "
                    "loss")
            return single_fn(logits, y)

    def step(state: TrainState, batch: Mapping[str, jnp.ndarray]):
        x = batch[features_col]
        y = (tuple(batch[c] for c in label_col) if multi
             else batch[label_col])
        rng = jax.random.fold_in(state.rng, state.step)
        # "losses" is ALWAYS mutable — auxiliary objectives sown by
        # modules (e.g. the MoE load-balance loss) must reach the
        # objective even when the caller built the state from
        # params-only variables (no init-time "losses" entry), or they
        # would be dropped silently.
        carried_keys = list(state.model_state)
        mutable_keys = carried_keys + (
            [] if "losses" in carried_keys else ["losses"])

        def objective(params):
            # "losses" is stripped from the INPUT so each apply sows a
            # fresh, shape-stable collection — flax sow would otherwise
            # append to the carried tuples every step, breaking the
            # scan carry.
            model_state_in = {k: v for k, v in state.model_state.items()
                              if k != "losses"}
            variables = {"params": params, **model_state_in}
            logits, new_model_state = model.apply(
                variables, x, train=True, rngs={"dropout": rng},
                mutable=mutable_keys)
            new_model_state = dict(new_model_state)
            aux_sum = jnp.float32(0.0)
            for leaf in jax.tree_util.tree_leaves(
                    new_model_state.get("losses", {})):
                aux_sum = aux_sum + leaf
            if "losses" not in carried_keys:
                # keep the carry's structure identical to the input
                # state (scan requires it)
                new_model_state.pop("losses", None)
            task_loss = loss_fn(logits, y)
            return task_loss + aux_sum, (task_loss, aux_sum,
                                         new_model_state)

        ((loss_val, (task_loss, aux_sum, new_model_state)),
         grads) = jax.value_and_grad(
            objective, has_aux=True)(state.params)
        updates, new_opt_state = tx.update(grads, state.opt_state,
                                           state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(step=state.step + 1, params=new_params,
                                  opt_state=new_opt_state,
                                  model_state=new_model_state)
        # "loss" stays the task loss (comparable with eval loss and
        # aux-free runs); the auxiliary sum is reported separately.
        metrics = {"loss": task_loss, "aux_loss": aux_sum,
                   "grad_norm": optax.global_norm(grads)}
        return new_state, metrics

    return step


def make_window_runner(step_fn):
    """``run(state, batches) -> (state, metrics)``: lax.scan ``step_fn``
    over a stacked window of batches (leaves ``[window, B, ...]``).  This
    is the reference's per-window inner loop compiled into one XLA program.
    """

    def run(state: TrainState, batches: Mapping[str, jnp.ndarray]):
        return jax.lax.scan(step_fn, state, batches)

    return run


def make_eval_step(model, loss, features_col: str = "features",
                   label_col: str = "label"):
    """Build ``eval_step(variables, batch) -> metrics`` (no mutation)."""
    loss_fn = resolve_loss(loss)

    @functools.partial(jax.jit, static_argnums=())
    def eval_step(variables, batch):
        logits = model.apply(variables, batch[features_col], train=False)
        return {"loss": loss_fn(logits, batch[label_col]),
                "logits": logits}

    return eval_step

"""L0 runtime: device mesh construction and worker-axis placement.

TPU-native replacement for the reference's cluster substrate (SURVEY.md §1
L0: Spark executors scheduled by the JVM).  Here "a worker" is a slice of a
``jax.sharding.Mesh``: data-parallel workers live along the ``workers`` axis
and exchange state over ICI via XLA collectives instead of TCP sockets to a
driver thread (SURVEY.md §2.4).

Single-chip emulation: when the requested worker count exceeds the device
count, workers fold into a leading batch axis handled by ``vmap`` on one
device — the ``local[N]`` analogue the reference got from Spark
(SURVEY.md §4 "multi-node without a cluster").
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "workers"
MODEL_AXIS = "model"


def create_mesh(num_workers: int | None = None,
                model_parallel: int = 1,
                devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build a ``(workers, model)`` mesh over the available devices.

    ``num_workers`` defaults to ``len(devices) // model_parallel``.  The
    worker axis is the data-parallel axis (the analogue of the reference's
    ``num_workers`` Spark partitions); the model axis hosts tensor
    parallelism for models that shard parameters.
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_workers is None:
        num_workers = max(1, len(devices) // model_parallel)
    need = num_workers * model_parallel
    if need > len(devices):
        raise ValueError(
            f"mesh needs {need} devices ({num_workers} workers x "
            f"{model_parallel} model-parallel), have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(num_workers, model_parallel)
    return Mesh(grid, (WORKER_AXIS, MODEL_AXIS))


@dataclasses.dataclass(frozen=True)
class WorkerPlacement:
    """How the emulated worker axis maps onto hardware.

    ``mesh_workers`` workers are real mesh rows (SPMD over ICI);
    ``vmap_workers`` further workers are folded per-device via ``vmap`` —
    total emulated workers = mesh_workers * vmap_workers.
    """

    mesh: Mesh | None
    mesh_workers: int
    vmap_workers: int

    @property
    def num_workers(self) -> int:
        return self.mesh_workers * self.vmap_workers


def place_workers(num_workers: int,
                  devices: Sequence[jax.Device] | None = None
                  ) -> WorkerPlacement:
    """Choose a placement for ``num_workers`` data-parallel workers.

    Uses as many real devices as divide the worker count; the remainder is
    emulated with ``vmap`` (single-chip development, the reference's
    ``local[N]`` mode).
    """
    devices = list(devices if devices is not None else jax.devices())
    n_dev = len(devices)
    mesh_workers = 1
    for cand in range(min(n_dev, num_workers), 0, -1):
        if num_workers % cand == 0:
            mesh_workers = cand
            break
    vmap_workers = num_workers // mesh_workers
    mesh = None
    if mesh_workers > 1:
        mesh = Mesh(np.asarray(devices[:mesh_workers]), (WORKER_AXIS,))
    return WorkerPlacement(mesh=mesh, mesh_workers=mesh_workers,
                           vmap_workers=vmap_workers)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis across workers."""
    return NamedSharding(mesh, P(WORKER_AXIS))

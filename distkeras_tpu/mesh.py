"""L0 runtime: device mesh construction and worker-axis placement.

TPU-native replacement for the reference's cluster substrate (SURVEY.md §1
L0: Spark executors scheduled by the JVM).  Here "a worker" is a slice of a
``jax.sharding.Mesh``: data-parallel workers live along the ``workers`` axis
and exchange state over ICI via XLA collectives instead of TCP sockets to a
driver thread (SURVEY.md §2.4).

Single-chip emulation: when the requested worker count exceeds the device
count, workers fold into a leading batch axis handled by ``vmap`` on one
device — the ``local[N]`` analogue the reference got from Spark
(SURVEY.md §4 "multi-node without a cluster").
"""

from __future__ import annotations

import dataclasses
import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "workers"
MODEL_AXIS = "model"


def initialize_cluster(coordinator_address: str | None = None,
                       num_processes: int | None = None,
                       process_id: int | None = None,
                       local_device_ids: Sequence[int] | None = None
                       ) -> None:
    """Join (or form) a multi-host cluster: ``jax.distributed.initialize``.

    The L0 substrate entry the reference delegated to Spark (SURVEY.md §1
    L0: executors scheduled by the JVM; §7 L0 of the build plan).  After
    this returns, ``jax.devices()`` is the *global* device list across
    all processes and every mesh built from it spans hosts — the trainers
    need no other changes because collectives ride the mesh.

    On TPU pods all arguments are auto-detected from the environment;
    elsewhere (CPU fleets, tests) pass them explicitly, or export
    ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID``.  No-op when called twice or when running
    single-process with no coordinator configured.
    """
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes in (None, 1):
        return  # single-process run; nothing to join
    if jax.distributed.is_initialized():
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id,
        local_device_ids=local_device_ids)


def process_shard(dataset, seed: int | None = None):
    """This process's rows of a logically-global ``Dataset`` — the
    multi-host analogue of Spark shipping partitions to executors.  Every
    process must hold the same global rows (same generator seed); the
    optional ``seed`` applies the same cross-process shuffle first."""
    if jax.process_count() == 1:
        return dataset
    if seed is not None:
        dataset = dataset.shuffle(seed=seed)
    return dataset.shard(jax.process_count(), jax.process_index())


def global_batch_from_local(sharding, local_tree):
    """Assemble globally-sharded device arrays from host-local data.

    ``local_tree`` is any pytree of arrays; ``sharding`` is either one
    ``NamedSharding`` applied to every leaf or a matching pytree of
    per-leaf shardings (tensor-parallel states).  Single-process: a
    plain sharded ``device_put``.  Multi-process: each host contributes
    only its shard's rows (for replicated shardings, the full replica)
    and ``jax.make_array_from_process_local_data`` stitches the global
    array — the DCN-free path for per-host data loading (SURVEY.md §7
    L0 "host-local data loading").
    """
    if isinstance(sharding, jax.sharding.Sharding):
        sharding = jax.tree_util.tree_map(lambda _: sharding, local_tree)
    if jax.process_count() == 1:
        return jax.device_put(local_tree, sharding)

    def put(v, s):
        # Typed PRNG keys can't pass through numpy: ship the raw uint32
        # key data and re-wrap it on the global array.
        if hasattr(v, "dtype") and jax.dtypes.issubdtype(
                v.dtype, jax.dtypes.prng_key):
            data = jax.make_array_from_process_local_data(
                s, np.asarray(jax.random.key_data(v)))
            return jax.random.wrap_key_data(data)
        return jax.make_array_from_process_local_data(s, np.asarray(v))

    return jax.tree_util.tree_map(put, local_tree, sharding)


def _select_spanning_devices(devices: Sequence[jax.Device],
                             need: int) -> list[jax.Device]:
    """Pick ``need`` devices such that, multi-process, every process
    contributes an equal share (grouped by process, process-major order).

    A naive ``devices[:need]`` prefix can land entirely on process 0's
    devices, leaving other processes with no addressable shard — their
    ``make_array_from_process_local_data`` then fails (or worse, the job
    silently trains on a subset of the data).
    """
    devices = list(devices)
    pc = jax.process_count()
    if pc == 1:
        return devices[:need]
    if need % pc:
        raise ValueError(
            f"multi-host mesh needs a device count ({need}) divisible "
            f"by the process count ({pc})")
    per = need // pc
    by_proc: dict[int, list[jax.Device]] = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    if len(by_proc) < pc or any(len(v) < per
                                for v in by_proc.values()):
        raise ValueError(
            f"cannot take {per} devices from each of {pc} processes: "
            f"per-process device counts are "
            f"{ {p: len(v) for p, v in by_proc.items()} }")
    return [d for p in sorted(by_proc) for d in by_proc[p][:per]]


def create_mesh(num_workers: int | None = None,
                model_parallel: int = 1,
                devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build a ``(workers, model)`` mesh over the available devices.

    ``num_workers`` defaults to ``len(devices) // model_parallel``.  The
    worker axis is the data-parallel axis (the analogue of the reference's
    ``num_workers`` Spark partitions); the model axis hosts tensor
    parallelism for models that shard parameters.  Multi-process, the
    chosen devices always span every process equally.
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_workers is None:
        num_workers = max(1, len(devices) // model_parallel)
    need = num_workers * model_parallel
    if need > len(devices):
        raise ValueError(
            f"mesh needs {need} devices ({num_workers} workers x "
            f"{model_parallel} model-parallel), have {len(devices)}")
    chosen = _select_spanning_devices(devices, need)
    grid = np.asarray(chosen).reshape(num_workers, model_parallel)
    return Mesh(grid, (WORKER_AXIS, MODEL_AXIS))


@dataclasses.dataclass(frozen=True)
class WorkerPlacement:
    """How the emulated worker axis maps onto hardware.

    ``mesh_workers`` workers are real mesh rows (SPMD over ICI);
    ``vmap_workers`` further workers are folded per-device via ``vmap`` —
    total emulated workers = mesh_workers * vmap_workers.
    """

    mesh: Mesh | None
    mesh_workers: int
    vmap_workers: int

    @property
    def num_workers(self) -> int:
        return self.mesh_workers * self.vmap_workers


def place_workers(num_workers: int,
                  devices: Sequence[jax.Device] | None = None
                  ) -> WorkerPlacement:
    """Choose a placement for ``num_workers`` data-parallel workers.

    Uses as many real devices as divide the worker count; the remainder is
    emulated with ``vmap`` (single-chip development, the reference's
    ``local[N]`` mode).
    """
    devices = list(devices if devices is not None else jax.devices())
    n_dev = len(devices)
    pc = jax.process_count()
    mesh_workers = 1
    for cand in range(min(n_dev, num_workers), 0, -1):
        # Multi-process, only process-spanning worker counts are usable
        # (every process must own an equal slice of the worker axis).
        if num_workers % cand == 0 and (pc == 1 or cand % pc == 0):
            mesh_workers = cand
            break
    vmap_workers = num_workers // mesh_workers
    mesh = None
    if mesh_workers > 1:
        chosen = _select_spanning_devices(devices, mesh_workers)
        mesh = Mesh(np.asarray(chosen), (WORKER_AXIS,))
    return WorkerPlacement(mesh=mesh, mesh_workers=mesh_workers,
                           vmap_workers=vmap_workers)


def fetch(x) -> np.ndarray:
    """Device array -> host numpy, multi-host safe: sharded
    non-fully-addressable arrays are allgathered (tiled, i.e. shards
    concatenated in place), replicated ones read from a local replica
    (single-process: a plain copy)."""
    if jax.process_count() > 1 and hasattr(x, "is_fully_addressable") \
            and not x.is_fully_addressable:
        if x.sharding.is_fully_replicated:
            return np.asarray(x.addressable_data(0))
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis across workers."""
    return NamedSharding(mesh, P(WORKER_AXIS))


def shardings_for(mesh: Mesh, specs) -> "jax.tree_util.PyTreeDef":
    """``PartitionSpec`` pytree -> ``NamedSharding`` pytree on ``mesh``.

    The placement half of the regex-rule resolver
    (``parallel.ps_dataplane.match_partition_rules``): rules produce
    specs, this binds them to devices."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))

"""Loader for the native columnar kernels (``native/columnar.cpp``).

Builds the shared library with the system C++ compiler on first use
(cached next to the source, keyed by a source hash) and exposes ctypes
wrappers.  Every entry point has a numpy fallback in
``data/transformers.py``; ``available()`` gates the fast path, and
``DISTKERAS_TPU_DISABLE_NATIVE=1`` forces the fallback (e.g. for
environments without a toolchain — nothing in the framework *requires*
the native path, it is the host-side ETL fast lane).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import pathlib
import shutil
import subprocess
import threading

import numpy as np

_SRC = pathlib.Path(__file__).resolve().parent.parent / "native" / \
    "columnar.cpp"
_BUILD_DIR = pathlib.Path(__file__).resolve().parent / "_native_build"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_failed: str | None = None


def _compiler() -> str | None:
    for cc in ("g++", "clang++", "c++"):
        if shutil.which(cc):
            return cc
    return None


def _build() -> ctypes.CDLL:
    src = _SRC.read_text()
    tag = hashlib.sha256(src.encode()).hexdigest()[:16]
    out = _BUILD_DIR / f"columnar-{tag}.so"
    if not out.exists():
        cc = _compiler()
        if cc is None:
            raise RuntimeError("no C++ compiler on PATH")
        _BUILD_DIR.mkdir(exist_ok=True)
        # per-process tmp name: concurrent builders (pytest workers,
        # multi-host shared FS) must not write the same inode; the
        # rename then makes whichever finishes last win atomically
        tmp = out.with_suffix(f".tmp{os.getpid()}.so")
        subprocess.run(
            [cc, "-O3", "-shared", "-fPIC", "-std=c++17",
             str(_SRC), "-o", str(tmp)],
            check=True, capture_output=True, text=True)
        os.replace(tmp, out)
    return ctypes.CDLL(str(out))


def _load() -> ctypes.CDLL | None:
    global _lib, _failed
    if _lib is not None or _failed is not None:
        return _lib
    with _lock:
        if _lib is not None or _failed is not None:
            return _lib
        if os.environ.get("DISTKERAS_TPU_DISABLE_NATIVE") == "1":
            _failed = "disabled by DISTKERAS_TPU_DISABLE_NATIVE"
            return None
        try:
            lib = _build()
        except subprocess.CalledProcessError as e:
            _failed = (f"native build unavailable: {e}\n"
                       f"{e.stderr}")  # the compiler diagnostic
            return None
        except (RuntimeError, OSError) as e:
            _failed = f"native build unavailable: {e}"
            return None
        lib.fnv1a_bucket.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p]
        lib.affine_scale.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.dense_scatter.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p]
        lib.csv_index.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_char, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_void_p]
        lib.csv_index.restype = ctypes.c_int64
        lib.csv_parse_numeric.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p]
        lib.csv_parse_numeric.restype = ctypes.c_int64
        lib.csv_fill_bytes.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def why_unavailable() -> str | None:
    _load()
    return _failed


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def fnv1a_bucket(fixed_width_bytes: np.ndarray, lengths: np.ndarray,
                 num_buckets: int) -> np.ndarray:
    """FNV-1a bucket ids for a numpy ``S``-dtype array (one hash per
    row over its real bytes)."""
    lib = _load()
    assert lib is not None, "check available() first"
    s = np.ascontiguousarray(fixed_width_bytes)
    width = s.dtype.itemsize
    n = len(s)
    mat = s.view(np.uint8).reshape(n, width)  # zero-copy
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    out = np.empty(n, dtype=np.int32)
    lib.fnv1a_bucket(_ptr(mat), n, width, _ptr(lengths),
                     ctypes.c_uint64(num_buckets), _ptr(out))
    return out


def affine_scale(col: np.ndarray, scale: np.ndarray,
                 shift: np.ndarray) -> np.ndarray:
    """``col * scale + shift`` column-wise; ``col`` is float32
    ``[N, ...]`` (trailing dims flattened), scale/shift float64 per
    column."""
    lib = _load()
    assert lib is not None, "check available() first"
    col = np.ascontiguousarray(col, dtype=np.float32)
    rows = col.shape[0]
    cols = int(np.prod(col.shape[1:])) if col.ndim > 1 else 1
    # ravel: per-column stats of an [N, 28, 28] feature column arrive
    # shaped (28, 28); the kernel is flat per trailing element
    scale = np.ascontiguousarray(np.broadcast_to(
        np.asarray(scale, np.float64).ravel(), (cols,)))
    shift = np.ascontiguousarray(np.broadcast_to(
        np.asarray(shift, np.float64).ravel(), (cols,)))
    out = np.empty_like(col)
    lib.affine_scale(_ptr(col), rows, cols, _ptr(scale), _ptr(shift),
                     _ptr(out))
    return out


def parse_csv(data: bytes, skip: int, delimiter: str,
              names: list[str]) -> dict[str, np.ndarray]:
    """Tokenize + type a delimited text buffer (GIL released inside the
    C calls).  ``data[skip:]`` holds the data rows; each column comes
    back int64 / float32 / unicode exactly like ``Dataset.from_csv``'s
    Python path.  Raises ``ValueError`` on ragged rows."""
    lib = _load()
    assert lib is not None, "check available() first"
    cols = len(names)
    buf = np.frombuffer(data, dtype=np.uint8)  # zero-copy view
    max_rows = max(data.count(b"\n", skip) + 1, 1)
    off = np.empty(max_rows * cols, dtype=np.int64)
    lens = np.empty(max_rows * cols, dtype=np.int32)
    rows = lib.csv_index(_ptr(buf), len(data), skip,
                         delimiter.encode(), cols, _ptr(off),
                         _ptr(lens))
    if rows < 0:
        raise ValueError(
            f"row at data line {-rows} does not have {cols} fields")
    if rows == 0:
        raise ValueError("no data rows")
    out: dict[str, np.ndarray] = {}
    iout = np.empty(rows, dtype=np.int64)
    fout = np.empty(rows, dtype=np.float64)
    for c, name in enumerate(names):
        verdict = lib.csv_parse_numeric(_ptr(buf), _ptr(off),
                                        _ptr(lens), rows, cols, c,
                                        _ptr(iout), _ptr(fout))
        if verdict == 0:
            out[name] = iout.copy()
        elif verdict == 1:
            out[name] = fout.astype(np.float32)
        else:
            width = max(
                int(lens[:rows * cols].reshape(rows, cols)[:, c].max()),
                1)
            raw = np.empty(rows, dtype=f"S{width}")
            lib.csv_fill_bytes(_ptr(buf), _ptr(off), _ptr(lens),
                               rows, cols, c, width,
                               _ptr(raw.view(np.uint8)))
            try:
                out[name] = raw.astype(f"U{width}")
            except UnicodeDecodeError:
                # non-ASCII bytes: decode per cell (rare; numpy's
                # bytes->str cast is ASCII-only)
                out[name] = np.asarray(
                    [v.decode() for v in raw.tolist()])
    return out


def dense_scatter(indices: np.ndarray, values: np.ndarray,
                  dim: int) -> np.ndarray:
    """Padded ``(indices, values)`` rows -> dense ``[N, dim]`` float32
    (pad index < 0 ignored)."""
    lib = _load()
    assert lib is not None, "check available() first"
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    val = np.ascontiguousarray(values, dtype=np.float32)
    if idx.size and idx.max() >= dim:
        # match the numpy fallback, which raises IndexError here —
        # malformed sparse data must fail loudly on both paths
        raise IndexError(
            f"sparse index {int(idx.max())} out of bounds for dim {dim}")
    rows, nnz = idx.shape
    out = np.zeros((rows, dim), dtype=np.float32)
    lib.dense_scatter(_ptr(idx), _ptr(val), rows, nnz, dim, _ptr(out))
    return out

"""Synthetic dataset generators for the five baseline configs
(BASELINE.md).  The reference's examples download MNIST / ATLAS Higgs /
Criteo; with zero egress the rebuild generates *learnable* synthetic stand-
ins (labels are a deterministic function of features, so convergence tests
have signal), with the same column names the real loaders would produce:
``features`` / ``label``.
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.data.dataset import Dataset


def _rng(seed: int) -> np.random.Generator:
    return np.random.default_rng(seed)


def synthetic_classification(num_rows: int, feature_shape: tuple[int, ...],
                             num_classes: int, seed: int = 0,
                             margin: float = 1.0) -> Dataset:
    """Gaussian mixture with one center per class: ``x = center[label] +
    noise``, center coordinates ~ N(0, margin²), unit noise.

    The per-coordinate class signal is ~``margin * sqrt(2)`` noise stds —
    deliberately NOT normalized by dimension, so gradient descent sees
    strong signal in every coordinate and smoke-test budgets converge at
    any feature size.  (Both earlier generators — argmax-of-linear-map and
    dim-normalized centers — had large aggregate but vanishing
    per-coordinate signal at 784 dims: feature learning stalled on the
    uniform-loss plateau for hundreds of epochs.)"""
    rng = _rng(seed)
    dim = int(np.prod(feature_shape))
    label = rng.integers(0, num_classes, size=num_rows).astype(np.int32)
    centers = _rng(seed + 1).normal(size=(num_classes, dim)) * margin
    x = rng.normal(size=(num_rows, dim)) + centers[label]
    x = x.astype(np.float32).reshape(num_rows, *feature_shape)
    return Dataset({"features": x, "label": label})


def mnist_synth(num_rows: int = 4096, seed: int = 0) -> Dataset:
    """MNIST-shaped: 28x28x1 in [0,1], 10 classes."""
    ds = synthetic_classification(num_rows, (28, 28, 1), 10, seed)
    return ds.map_column("features", lambda x: (x - x.min()) /
                         (x.max() - x.min()))


def cifar10_synth(num_rows: int = 4096, seed: int = 1) -> Dataset:
    return synthetic_classification(num_rows, (32, 32, 3), 10, seed)


def imagenet_synth(num_rows: int = 512, image_size: int = 224,
                   num_classes: int = 1000, seed: int = 2) -> Dataset:
    return synthetic_classification(num_rows,
                                    (image_size, image_size, 3),
                                    num_classes, seed)


def imdb_synth(num_rows: int = 2048, seq_len: int = 64,
               vocab_size: int = 1000, seed: int = 3) -> Dataset:
    """Token sequences (0 = pad); label = whether "positive" tokens (ids
    below vocab/2) outnumber "negative" ones — order-free but recurrent-
    friendly signal."""
    rng = _rng(seed)
    lengths = rng.integers(seq_len // 2, seq_len + 1, size=num_rows)
    tokens = rng.integers(1, vocab_size, size=(num_rows, seq_len))
    mask = np.arange(seq_len)[None, :] < lengths[:, None]
    tokens = (tokens * mask).astype(np.int32)
    positive = ((tokens > 0) & (tokens < vocab_size // 2)).sum(axis=1)
    label = (positive * 2 > lengths).astype(np.int32)
    return Dataset({"features": tokens, "label": label})


def criteo_synth(num_rows: int = 4096, num_dense: int = 13,
                 num_categorical: int = 26, vocab_size: int = 1000,
                 seed: int = 4) -> Dataset:
    """Criteo-shaped CTR rows: dense float features (log-normal, like
    Criteo counts), string categoricals, binary label correlated with a
    random subset of both."""
    rng = _rng(seed)
    dense = rng.lognormal(0.0, 1.0,
                          size=(num_rows, num_dense)).astype(np.float32)
    cats = rng.integers(0, vocab_size, size=(num_rows, num_categorical))
    cat_strings = np.char.add("cat_", cats.astype(str))
    w_dense = _rng(seed + 1).normal(size=num_dense)
    score = np.log1p(dense) @ w_dense + (cats[:, 0] % 2) - 0.5
    label = (score > np.median(score)).astype(np.int32)
    cols = {"label": label}
    cols["dense"] = dense
    for j in range(num_categorical):
        cols[f"c{j}"] = cat_strings[:, j]
    return Dataset(cols)


def lm_synth(num_rows: int = 1024, seq_len: int = 128,
             vocab_size: int = 256, seed: int = 5) -> Dataset:
    """Language-model rows for the Transformer: next-token targets over a
    deterministic mod-arithmetic sequence (perfectly learnable)."""
    rng = _rng(seed)
    start = rng.integers(1, vocab_size, size=(num_rows, 2))
    seq = np.zeros((num_rows, seq_len + 1), dtype=np.int64)
    seq[:, :2] = start
    for t in range(2, seq_len + 1):
        seq[:, t] = (seq[:, t - 1] + seq[:, t - 2]) % vocab_size
    return Dataset({"features": seq[:, :-1].astype(np.int32),
                    "label": seq[:, 1:].astype(np.int32)})

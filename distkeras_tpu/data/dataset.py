"""``Dataset``: a named-column table with DataFrame-shaped verbs.

Replaces the reference's use of ``pyspark.sql.DataFrame`` (SURVEY.md §2.1:
trainers take a DataFrame plus ``features_col``/``label_col``).  Columns are
numpy arrays aligned on the row axis; verbs are cheap, vectorized, and
return new ``Dataset`` views.  ``shard``/``repartition`` are the analogues
of Spark's partitioning that the distributed trainers use to split rows
across the worker mesh axis.
"""

from __future__ import annotations

from typing import Callable, Iterator, Mapping, Sequence

import numpy as np


class Dataset:
    """Immutable dict of aligned columns."""

    def __init__(self, columns: Mapping[str, np.ndarray]):
        if not columns:
            raise ValueError("Dataset needs at least one column")
        cols = {k: np.asarray(v) for k, v in columns.items()}
        n = {len(v) for v in cols.values()}
        if len(n) != 1:
            raise ValueError(
                f"column lengths differ: "
                f"{ {k: len(v) for k, v in cols.items()} }")
        self._columns = cols
        self._num_rows = n.pop()

    # -- basic accessors ---------------------------------------------------

    @property
    def columns(self) -> dict[str, np.ndarray]:
        return dict(self._columns)

    @property
    def column_names(self) -> list[str]:
        return list(self._columns)

    def __len__(self) -> int:
        return self._num_rows

    def __getitem__(self, name: str) -> np.ndarray:
        return self._columns[name]

    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def __repr__(self) -> str:
        shapes = {k: v.shape for k, v in self._columns.items()}
        return f"Dataset(rows={self._num_rows}, columns={shapes})"

    # -- IO ---------------------------------------------------------------

    @classmethod
    def from_csv(cls, path, *, delimiter: str = ",",
                 header: bool = True,
                 names: Sequence[str] | None = None) -> "Dataset":
        """Read a delimited text file into typed columns.

        The reference ingested CSVs through Spark's reader (its Criteo/
        ATLAS notebooks); here each column is auto-typed: all-numeric
        columns become f32 (ints stay int64), anything else a numpy
        string column — ready for ``LabelIndexTransformer`` /
        ``HashBucketTransformer``.  ``names`` overrides or supplies the
        column names (required when ``header=False``); plain unquoted
        CSV/TSV only.  Numeric means plain decimal spellings: hex
        (``0x1a``) and digit-underscore (``1_000``) tokens type as
        strings on both parse paths.

        When the native kernels are available
        (``distkeras_tpu.native``), tokenizing and type conversion run
        in C with the GIL released — both faster and overlappable by
        the out-of-core segment-prefetch thread; the Python path below
        is the semantic reference and the fallback.
        """
        from distkeras_tpu import native as _native

        with open(path, "rb") as fh:
            raw = fh.read()

        if (_native.available() and len(delimiter) == 1
                and delimiter.isascii() and b'"' not in raw):
            # a quote character anywhere sends the whole file down the
            # csv.reader lane: the C tokenizer is plain-split and would
            # otherwise silently disagree on quoted fields
            ds = cls._from_csv_native(raw, path, delimiter, header,
                                      names)
            if ds is not None:
                return ds

        import csv as _csv
        import io as _io

        reader = _csv.reader(_io.StringIO(raw.decode(), newline=""),
                             delimiter=delimiter)
        rows = [row for row in reader if row]
        if not rows:
            raise ValueError(f"{path}: empty file")
        if header:
            file_names, rows = rows[0], rows[1:]
            names = list(names) if names is not None else file_names
        elif names is None:
            raise ValueError("header=False needs explicit names=")
        else:
            names = list(names)
        if not rows:
            raise ValueError(f"{path}: no data rows")
        widths = {len(r) for r in rows}
        if widths != {len(names)}:
            raise ValueError(
                f"{path}: rows have {sorted(widths)} fields, "
                f"expected {len(names)}")
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(
                f"{path}: duplicate column name(s) {sorted(dupes)}")

        def typed(values: list[str]) -> np.ndarray:
            # underscore/hex spellings type as strings (int("1_0")
            # would accept them; the native lane cannot — both lanes
            # are strict so they agree)
            plain = not any("_" in v or "x" in v or "X" in v
                            for v in values)
            if plain:
                try:
                    return np.asarray([int(v) for v in values],
                                      dtype=np.int64)
                except (ValueError, OverflowError):
                    # OverflowError: ids past int64 fall through to the
                    # float/string paths instead of crashing
                    pass
                try:
                    return np.asarray([float(v) for v in values],
                                      dtype=np.float32)
                except ValueError:
                    pass
            return np.asarray(values)

        return cls({name: typed([r[c] for r in rows])
                    for c, name in enumerate(names)})

    @classmethod
    def _from_csv_native(cls, raw: bytes, path, delimiter: str,
                         header: bool, names):
        """C parse lane (see ``native.parse_csv``); returns ``None`` to
        fall back to the csv.reader lane when the buffer needs it
        (undecodable header bytes; the caller already routes quoted
        files away)."""
        from distkeras_tpu import native as _native

        # header = first non-blank line, parsed in Python (names need
        # decoding anyway); data region starts after it
        skip = 0
        if header:
            while skip < len(raw):
                eol = raw.find(b"\n", skip)
                if eol < 0:
                    eol = len(raw)
                line = raw[skip:eol].rstrip(b"\r")
                if line:
                    try:
                        file_names = line.decode().split(delimiter)
                    except UnicodeDecodeError:
                        return None  # csv.reader lane handles encoding
                    skip = eol + 1
                    break
                skip = eol + 1
            else:
                raise ValueError(f"{path}: empty file")
            names = (list(names) if names is not None
                     else [n for n in file_names])
        elif names is None:
            raise ValueError("header=False needs explicit names=")
        else:
            names = list(names)
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(
                f"{path}: duplicate column name(s) {sorted(dupes)}")
        try:
            cols = _native.parse_csv(raw, skip, delimiter, names)
        except ValueError as e:
            if "fields" in str(e):
                raise ValueError(f"{path}: {e}") from None
            raise ValueError(f"{path}: no data rows") from None
        return cls(cols)

    @classmethod
    def from_npz(cls, path) -> "Dataset":
        """Read an ``.npz`` archive: each array becomes a column."""
        with np.load(path) as archive:
            return cls({k: np.asarray(archive[k])
                        for k in archive.files})

    @classmethod
    def from_npz_shards(cls, pattern_or_paths):
        """Out-of-core dataset over many ``.npz`` shard files (glob
        pattern or path list) — returns a ``ShardedDataset`` that
        trainers stream one shard at a time (``data/sharded.py``)."""
        from distkeras_tpu.data.sharded import from_npz_shards

        return from_npz_shards(pattern_or_paths)

    @classmethod
    def from_csv_shards(cls, pattern_or_paths, *, delimiter: str = ",",
                        header: bool = True, names=None):
        """Out-of-core dataset over many delimited text files — the
        reference's Criteo/ATLAS ingestion shape, streamed one file at
        a time (``data/sharded.py``)."""
        from distkeras_tpu.data.sharded import from_csv_shards

        return from_csv_shards(pattern_or_paths, delimiter=delimiter,
                               header=header, names=names)

    def to_npz_shards(self, prefix, rows_per_shard: int) -> list[str]:
        """Write this dataset as ``.npz`` shard files readable by
        ``from_npz_shards``; returns the paths."""
        from distkeras_tpu.data.sharded import to_npz_shards

        return to_npz_shards(self, prefix, rows_per_shard)

    def to_npz(self, path) -> str:
        """Write all columns to an ``.npz`` archive (the format the
        examples' ``--data-npz`` flag reads).  Returns the actual file
        path (numpy appends ``.npz`` when missing).  A column named
        ``file`` is rejected — it collides with ``np.savez``'s
        parameter and cannot be stored by keyword."""
        if "file" in self._columns:
            raise ValueError(
                "cannot write a column named 'file' to npz (collides "
                "with np.savez's parameter); rename() it first")
        path = str(path)
        if not path.endswith(".npz"):
            path += ".npz"
        np.savez(path, **self._columns)
        return path

    # -- DataFrame-shaped verbs -------------------------------------------

    def select(self, names: Sequence[str]) -> "Dataset":
        return Dataset({k: self._columns[k] for k in names})

    def with_column(self, name: str, values: np.ndarray) -> "Dataset":
        cols = self.columns
        cols[name] = np.asarray(values)
        return Dataset(cols)

    def drop(self, *names: str) -> "Dataset":
        return Dataset(
            {k: v for k, v in self._columns.items() if k not in names})

    def rename(self, mapping: Mapping[str, str]) -> "Dataset":
        return Dataset(
            {mapping.get(k, k): v for k, v in self._columns.items()})

    def filter(self, mask: np.ndarray) -> "Dataset":
        mask = np.asarray(mask, dtype=bool)
        return Dataset({k: v[mask] for k, v in self._columns.items()})

    def map_column(self, name: str,
                   fn: Callable[[np.ndarray], np.ndarray],
                   out: str | None = None) -> "Dataset":
        return self.with_column(out or name, fn(self._columns[name]))

    def take(self, n: int) -> "Dataset":
        return Dataset({k: v[:n] for k, v in self._columns.items()})

    def shuffle(self, seed: int = 0) -> "Dataset":
        perm = np.random.default_rng(seed).permutation(self._num_rows)
        return Dataset({k: v[perm] for k, v in self._columns.items()})

    def train_test_split(self, test_fraction: float = 0.2,
                         seed: int = 0) -> tuple["Dataset", "Dataset"]:
        """Shuffled ``(train, test)`` split — the holdout idiom the
        reference notebooks did with Spark ``randomSplit``.  Both parts
        are non-empty or this raises."""
        if not 0.0 < test_fraction < 1.0:
            raise ValueError(
                f"test_fraction must be in (0, 1), got {test_fraction}")
        n_test = int(round(self._num_rows * test_fraction))
        if n_test == 0 or n_test == self._num_rows:
            raise ValueError(
                f"split of {self._num_rows} rows at {test_fraction} "
                f"leaves an empty part")
        perm = np.random.default_rng(seed).permutation(self._num_rows)
        cols = self._columns
        return (Dataset({k: v[perm[n_test:]] for k, v in cols.items()}),
                Dataset({k: v[perm[:n_test]] for k, v in cols.items()}))

    def concat(self, other: "Dataset") -> "Dataset":
        if set(self.column_names) != set(other.column_names):
            raise ValueError("column sets differ")
        return Dataset({k: np.concatenate([v, other[k]])
                        for k, v in self._columns.items()})

    # -- partitioning (the Spark repartition analogue) --------------------

    def shard(self, num_shards: int, index: int,
              drop_remainder: bool = True) -> "Dataset":
        """Rows of shard ``index`` out of ``num_shards`` (contiguous split,
        equal sizes when ``drop_remainder``)."""
        if not 0 <= index < num_shards:
            raise ValueError(f"index {index} not in [0, {num_shards})")
        per = self._num_rows // num_shards
        if per == 0:
            raise ValueError(
                f"{self._num_rows} rows cannot fill {num_shards} shards")
        start = index * per
        stop = start + per if drop_remainder else (
            self._num_rows if index == num_shards - 1 else start + per)
        return Dataset({k: v[start:stop]
                        for k, v in self._columns.items()})

    def repartition(self, num_shards: int) -> list["Dataset"]:
        return [self.shard(num_shards, i) for i in range(num_shards)]

    # -- batching ----------------------------------------------------------

    def batches(self, batch_size: int, *, columns: Sequence[str]
                | None = None, drop_remainder: bool = True,
                ) -> Iterator[dict[str, np.ndarray]]:
        cols = ({k: self._columns[k] for k in columns}
                if columns else self._columns)
        stop = ((self._num_rows // batch_size) * batch_size
                if drop_remainder else self._num_rows)
        for start in range(0, stop, batch_size):
            yield {k: v[start:start + batch_size]
                   for k, v in cols.items()}

    def num_batches(self, batch_size: int,
                    drop_remainder: bool = True) -> int:
        if drop_remainder:
            return self._num_rows // batch_size
        return -(-self._num_rows // batch_size)

"""ETL transformers: the reference's ``distkeras/transformers.py`` surface
(SURVEY.md §2.1: LabelIndex / OneHot / MinMax / Reshape / Dense) rebuilt
over columnar numpy instead of Spark rows, plus the hash-bucketing the
Criteo Wide&Deep config needs.

Same Spark-ML idiom — objects with ``transform(dataset) -> dataset`` — but
vectorized over whole columns, and with an explicit ``fit`` for the
stateful ones (the reference fused fit into construction or first use).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from distkeras_tpu import native
from distkeras_tpu.data.dataset import Dataset


class Transformer:
    """Spark-ML-style transformer: ``transform(Dataset) -> Dataset``.

    Stateful transformers implement ``fit`` and raise if used unfitted.
    """

    def fit(self, dataset: Dataset) -> "Transformer":
        return self

    def transform(self, dataset: Dataset) -> Dataset:
        raise NotImplementedError

    def fit_transform(self, dataset: Dataset) -> Dataset:
        return self.fit(dataset).transform(dataset)

    def __call__(self, dataset: Dataset) -> Dataset:
        return self.transform(dataset)


class LabelIndexTransformer(Transformer):
    """String/arbitrary labels -> contiguous integer indices.

    Reference: LabelIndexTransformer (SURVEY.md §2.1, name MED).
    """

    def __init__(self, input_col: str, output_col: str | None = None):
        self.input_col = input_col
        self.output_col = output_col or input_col + "_index"
        self.classes_: np.ndarray | None = None

    def fit(self, dataset: Dataset) -> "LabelIndexTransformer":
        self.classes_ = np.unique(dataset[self.input_col])
        return self

    def transform(self, dataset: Dataset) -> Dataset:
        if self.classes_ is None:
            raise RuntimeError("fit() before transform()")
        idx = np.searchsorted(self.classes_, dataset[self.input_col])
        idx = idx.astype(np.int32)
        # reject labels unseen at fit time instead of aliasing them
        if not np.array_equal(
                np.asarray(self.classes_)[np.clip(idx, 0,
                                                  len(self.classes_) - 1)],
                dataset[self.input_col]):
            raise ValueError(f"unseen labels in {self.input_col!r}")
        return dataset.with_column(self.output_col, idx)


class OneHotTransformer(Transformer):
    """Integer index column -> one-hot float32 matrix column.

    Reference: OneHotTransformer; ``utils.to_dense_vector`` per row.
    """

    def __init__(self, input_col: str, num_classes: int,
                 output_col: str | None = None):
        self.input_col = input_col
        self.num_classes = num_classes
        self.output_col = output_col or input_col + "_onehot"

    def transform(self, dataset: Dataset) -> Dataset:
        idx = np.asarray(dataset[self.input_col], dtype=np.int64)
        if idx.size and (idx.min() < 0 or idx.max() >= self.num_classes):
            raise ValueError(
                f"indices outside [0, {self.num_classes})")
        eye = np.eye(self.num_classes, dtype=np.float32)
        return dataset.with_column(self.output_col, eye[idx])


class MinMaxTransformer(Transformer):
    """Scale a numeric column into [new_min, new_max].

    Reference: MinMaxTransformer (per-feature min/max over the DataFrame).
    """

    def __init__(self, input_col: str, output_col: str | None = None,
                 new_min: float = 0.0, new_max: float = 1.0):
        self.input_col = input_col
        self.output_col = output_col or input_col
        self.new_min, self.new_max = new_min, new_max
        self.min_: np.ndarray | None = None
        self.max_: np.ndarray | None = None

    def fit(self, dataset: Dataset) -> "MinMaxTransformer":
        col = np.asarray(dataset[self.input_col], dtype=np.float64)
        self.min_ = col.min(axis=0)
        self.max_ = col.max(axis=0)
        return self

    def transform(self, dataset: Dataset) -> Dataset:
        if self.min_ is None:
            raise RuntimeError("fit() before transform()")
        col = np.asarray(dataset[self.input_col], dtype=np.float32)
        span = np.where(self.max_ > self.min_, self.max_ - self.min_, 1.0)
        if native.available():
            scale = (self.new_max - self.new_min) / span
            out = native.affine_scale(col, scale,
                                      self.new_min - self.min_ * scale)
        else:
            unit = (col - self.min_) / span
            out = (unit * (self.new_max - self.new_min)
                   + self.new_min).astype(np.float32)
        return dataset.with_column(self.output_col, out)


class StandardScaleTransformer(Transformer):
    """Zero-mean unit-variance scaling (common companion to MinMax)."""

    def __init__(self, input_col: str, output_col: str | None = None,
                 epsilon: float = 1e-8):
        self.input_col = input_col
        self.output_col = output_col or input_col
        self.epsilon = epsilon
        self.mean_: np.ndarray | None = None
        self.std_: np.ndarray | None = None

    def fit(self, dataset: Dataset) -> "StandardScaleTransformer":
        col = np.asarray(dataset[self.input_col], dtype=np.float64)
        self.mean_ = col.mean(axis=0)
        self.std_ = col.std(axis=0)
        return self

    def transform(self, dataset: Dataset) -> Dataset:
        if self.mean_ is None:
            raise RuntimeError("fit() before transform()")
        col = np.asarray(dataset[self.input_col], dtype=np.float32)
        if native.available():
            scale = 1.0 / (self.std_ + self.epsilon)
            out = native.affine_scale(col, scale, -self.mean_ * scale)
        else:
            out = ((col - self.mean_)
                   / (self.std_ + self.epsilon)).astype(np.float32)
        return dataset.with_column(self.output_col, out)


class ReshapeTransformer(Transformer):
    """Reshape each row's feature vector (e.g. flat 784 -> 28x28x1).

    Reference: ReshapeTransformer (flat -> image tensor for convnets).
    """

    def __init__(self, input_col: str, shape: Sequence[int],
                 output_col: str | None = None):
        self.input_col = input_col
        self.shape = tuple(shape)
        self.output_col = output_col or input_col

    def transform(self, dataset: Dataset) -> Dataset:
        col = np.asarray(dataset[self.input_col])
        return dataset.with_column(
            self.output_col, col.reshape((len(dataset), *self.shape)))


class DenseTransformer(Transformer):
    """(indices, values) sparse row pairs -> dense float32 vectors.

    Reference: DenseTransformer (Spark sparse Vector -> dense).  Columnar
    encoding: ``indices_col``/``values_col`` are ``[N, nnz]`` padded arrays
    (pad index < 0 ignored).
    """

    def __init__(self, indices_col: str, values_col: str, dim: int,
                 output_col: str = "features"):
        self.indices_col = indices_col
        self.values_col = values_col
        self.dim = dim
        self.output_col = output_col

    def transform(self, dataset: Dataset) -> Dataset:
        idx = np.asarray(dataset[self.indices_col], dtype=np.int64)
        val = np.asarray(dataset[self.values_col], dtype=np.float32)
        if native.available():
            out = native.dense_scatter(idx, val, self.dim)
        else:
            n = len(dataset)
            out = np.zeros((n, self.dim), dtype=np.float32)
            valid = idx >= 0
            rows = np.broadcast_to(np.arange(n)[:, None], idx.shape)
            out[rows[valid], idx[valid]] = val[valid]
        return dataset.with_column(self.output_col, out)


class HashBucketTransformer(Transformer):
    """Hash arbitrary categorical values into ``num_buckets`` int ids —
    the Criteo categorical path (reference handled this in notebook ETL).

    Deterministic FNV-1a over the value's string bytes; no vocabulary
    state, so it needs no ``fit`` and is stable across shards/hosts.
    """

    def __init__(self, input_col: str, num_buckets: int,
                 output_col: str | None = None):
        self.input_col = input_col
        self.num_buckets = num_buckets
        self.output_col = output_col or input_col + "_bucket"

    @staticmethod
    def _fnv1a(data: bytes) -> int:
        """Scalar reference implementation (tests check the vectorized
        path against this)."""
        h = 0xcbf29ce484222325
        for b in data:
            h = ((h ^ b) * 0x100000001b3) & 0xFFFFFFFFFFFFFFFF
        return h

    @staticmethod
    def _fnv1a_vectorized(col: np.ndarray) -> np.ndarray:
        """FNV-1a over each value's UTF-8 bytes, vectorized across rows:
        view the fixed-width byte array as a [N, W] uint8 matrix and fold
        column-by-column (W = max string width, typically tiny), masking
        rows already past their length.  uint64 arithmetic wraps, which is
        exactly FNV's mod-2^64."""
        s = np.char.encode(col.astype(str), "utf-8")
        width = s.dtype.itemsize
        mat = np.frombuffer(s.tobytes(), dtype=np.uint8).reshape(-1, width)
        lengths = np.char.str_len(s)
        h = np.full(len(s), 0xcbf29ce484222325, dtype=np.uint64)
        prime = np.uint64(0x100000001b3)
        with np.errstate(over="ignore"):
            for j in range(width):
                active = j < lengths
                h[active] = (h[active] ^ mat[active, j]) * prime
        return h

    def transform(self, dataset: Dataset) -> Dataset:
        col = np.asarray(dataset[self.input_col])
        if native.available():
            s = np.char.encode(col.astype(str), "utf-8")
            out = native.fnv1a_bucket(s, np.char.str_len(s),
                                      self.num_buckets)
        else:
            h = self._fnv1a_vectorized(col)
            out = (h % np.uint64(self.num_buckets)).astype(np.int32)
        return dataset.with_column(self.output_col, out)


class AssembleTransformer(Transformer):
    """Concatenate numeric columns into one float32 feature matrix — the
    Spark ``VectorAssembler`` idiom the reference notebooks use to build
    ``features_col`` before training.  Scalar columns contribute one
    column each; matrix columns are flattened per row."""

    def __init__(self, input_cols: Sequence[str],
                 output_col: str = "features"):
        self.input_cols = list(input_cols)
        self.output_col = output_col

    def transform(self, dataset: Dataset) -> Dataset:
        parts = []
        n = len(dataset)
        for name in self.input_cols:
            col = np.asarray(dataset[name], dtype=np.float32)
            parts.append(col.reshape(n, -1))
        return dataset.with_column(self.output_col,
                                   np.concatenate(parts, axis=1))


class Pipeline(Transformer):
    """Sequential transformer composition (fit stages in order, each on
    the output of the previous)."""

    def __init__(self, stages: Sequence[Transformer]):
        self.stages = list(stages)

    def fit(self, dataset: Dataset) -> "Pipeline":
        for stage in self.stages:
            dataset = stage.fit(dataset).transform(dataset)
        return self

    def transform(self, dataset: Dataset) -> Dataset:
        for stage in self.stages:
            dataset = stage.transform(dataset)
        return dataset

"""Columnar data layer: the TPU-native replacement for the reference's
Spark-DataFrame substrate (SURVEY.md §1 L0/L6).

The reference's ETL is Spark-ML transformers mapping rows of a DataFrame;
ours is the same *semantics* over a columnar, numpy-backed ``Dataset`` —
vectorized, static-shape, host-side — feeding device-sharded batches
(SURVEY.md §7 "keep the transformer semantics, not the engine").
"""

from distkeras_tpu.data.dataset import Dataset  # noqa: F401
from distkeras_tpu.data.sharded import (  # noqa: F401
    CsvShardedDataset,
    ShardedDataset,
)
from distkeras_tpu.data.transformers import (  # noqa: F401
    AssembleTransformer,
    DenseTransformer,
    HashBucketTransformer,
    LabelIndexTransformer,
    MinMaxTransformer,
    OneHotTransformer,
    Pipeline,
    ReshapeTransformer,
    StandardScaleTransformer,
    Transformer,
)
from distkeras_tpu.data import datasets  # noqa: F401

"""Out-of-core training data: a dataset backed by ``.npz`` shard files.

The reference's substrate (Spark DataFrames, SURVEY.md §1 L0) scaled
past host RAM by construction — partitions lived on the cluster and
flowed through executors.  The rebuild's equivalent is file-granular:
``ShardedDataset`` holds a *list of shard files* plus their row counts
(read from the npy headers, not the data), and materializes one shard
at a time.  Trainers iterate ``epoch_segments`` — shard files in a
seed-permuted order, rows shuffled within each shard ("shuffle what
fits", the standard out-of-core approximation of a global shuffle) —
so peak memory is one shard, not the dataset.

With a single shard file the epoch is bit-identical to the in-memory
path (same ``Dataset.shuffle(seed)`` permutation), which is the
equivalence contract ``tests/test_sharded_data.py`` pins.

Multi-host: every process sees the same deterministic segment order and
slices rows per process inside the trainer (``mesh.process_shard`` /
worker repartition), exactly as the in-memory path does.
"""

from __future__ import annotations

import glob as _glob
import zipfile
from typing import Iterator, Sequence

import numpy as np

from distkeras_tpu.data.dataset import Dataset


def _npz_column_meta(path: str) -> dict[str, tuple[tuple, np.dtype]]:
    """Column name -> (shape, dtype) from an npz's member headers —
    reads a few hundred bytes per member, never the array data."""
    from numpy.lib import format as npf

    meta = {}
    with zipfile.ZipFile(path) as z:
        for name in z.namelist():
            if not name.endswith(".npy"):
                continue
            with z.open(name) as fh:
                version = npf.read_magic(fh)
                if version == (1, 0):
                    shape, _, dtype = npf.read_array_header_1_0(fh)
                else:
                    shape, _, dtype = npf.read_array_header_2_0(fh)
            meta[name[:-4]] = (shape, dtype)
    return meta


class ShardedDataset:
    """A list of ``.npz`` shard files acting as one logical dataset.

    Construct via ``Dataset.from_npz_shards(pattern)`` or directly from
    paths.  Header metadata (row counts, columns, dtypes) is read
    eagerly and validated for consistency; array data is loaded one
    shard at a time by ``load_shard`` / ``epoch_segments``.
    """

    def __init__(self, paths: Sequence[str]):
        paths = [str(p) for p in paths]
        if not paths:
            raise ValueError("ShardedDataset needs at least one shard")
        self.paths = paths
        metas = [_npz_column_meta(p) for p in paths]
        names = set(metas[0])
        for p, m in zip(paths[1:], metas[1:]):
            if set(m) != names:
                raise ValueError(
                    f"shard {p} has columns {sorted(m)}, expected "
                    f"{sorted(names)} (from {paths[0]})")
            for k in names:
                if m[k][0][1:] != metas[0][k][0][1:]:
                    raise ValueError(
                        f"shard {p} column {k!r} has row shape "
                        f"{m[k][0][1:]}, expected {metas[0][k][0][1:]}")
        self._column_names = sorted(names)
        self.shard_rows = []
        for p, m in zip(paths, metas):
            counts = {v[0][0] for v in m.values()}
            if len(counts) != 1:
                raise ValueError(
                    f"shard {p}: column lengths differ: "
                    f"{ {k: v[0][0] for k, v in m.items()} }")
            self.shard_rows.append(counts.pop())

    # -- metadata ----------------------------------------------------------

    @property
    def column_names(self) -> list[str]:
        return list(self._column_names)

    @property
    def num_shards(self) -> int:
        return len(self.paths)

    def __len__(self) -> int:
        return int(sum(self.shard_rows))

    def __repr__(self) -> str:
        return (f"ShardedDataset(shards={self.num_shards}, "
                f"rows={len(self)}, columns={self._column_names})")

    # -- materialization ---------------------------------------------------

    def load_shard(self, index: int) -> Dataset:
        return Dataset.from_npz(self.paths[index])

    def to_dataset(self) -> Dataset:
        """Materialize everything (small sets / tests only)."""
        out = self.load_shard(0)
        for i in range(1, self.num_shards):
            out = out.concat(self.load_shard(i))
        return out

    def map(self, fn) -> "ShardedDataset":
        """A view whose shards pass through ``fn(Dataset) -> Dataset``
        at load time — the out-of-core seam for per-shard ETL (e.g. a
        fitted ``Pipeline.transform`` or an ``AssembleTransformer``,
        fit on a sample shard).  ``fn`` must preserve the row count:
        the epoch plan (resume fast-skip, round prediction) is computed
        from the raw shard metadata."""
        return _MappedShards(self, fn)

    def epoch_segment_loaders(self, seed: int = 0):
        """The epoch plan without the data: yields ``(rows, load)``
        pairs in the seed-permuted shard order, where ``rows`` comes
        from the header metadata and ``load()`` materializes that
        segment (shuffled).  Lets a resuming trainer skip whole shard
        files it has already consumed without reading them."""
        rng = np.random.default_rng(seed)
        order = (rng.permutation(self.num_shards)
                 if self.num_shards > 1 else [0])
        for slot, i in enumerate(order):
            # per-shard seed keeps distinct shards from sharing a
            # permutation; hashing (seed, slot, shard) through
            # SeedSequence avoids the additive-salt collisions where
            # nearby epoch seeds alias across (slot, shard) pairs.
            # Shard count 1 must keep the plain seed for the
            # bit-identity contract with the in-memory path.
            if self.num_shards == 1:
                s = seed
            else:
                s = int(np.random.SeedSequence(
                    [seed % (1 << 63), slot, int(i)]
                ).generate_state(1, dtype=np.uint64)[0])
            yield (int(self.shard_rows[int(i)]),
                   lambda idx=int(i), s=s:
                   self.load_shard(idx).shuffle(seed=s))

    def epoch_segments(self, seed: int = 0) -> Iterator[Dataset]:
        """One training epoch as a stream of in-memory ``Dataset``
        segments: shard files in a seed-permuted order, rows shuffled
        within each shard.  Deterministic in ``seed``; with one shard
        this is exactly ``[full.shuffle(seed)]`` (the in-memory
        trainers' epoch), so single-shard training is bit-identical to
        in-memory training."""
        for _, load in self.epoch_segment_loaders(seed):
            yield load()


class _MappedShards(ShardedDataset):
    """``ShardedDataset.map``'s view: same shard plan, transformed
    loads."""

    def __init__(self, base: ShardedDataset, fn):
        self._base = base
        self._fn = fn
        self.paths = list(base.paths)
        self.shard_rows = list(base.shard_rows)
        # pre-transform names; the transformed columns exist per loaded
        # segment (fn may add/drop columns)
        self._column_names = base.column_names

    def load_shard(self, index: int) -> Dataset:
        out = self._fn(self._base.load_shard(index))
        if len(out) != self.shard_rows[index]:
            raise ValueError(
                f"map fn changed shard {index}'s row count "
                f"({self.shard_rows[index]} -> {len(out)}); the epoch "
                f"plan requires row-preserving transforms")
        return out


class CsvShardedDataset(ShardedDataset):
    """Out-of-core CSV: a list of delimited text files acting as one
    logical dataset — the reference's Criteo/ATLAS ingestion shape
    (Spark read CSVs per partition).  Row counts come from a line scan
    (no parsing); shard 0 is additionally parsed up front as the
    schema anchor, so header mismatches, duplicate columns, and
    non-numeric surprises fail at construction.  Later shards are
    validated against the anchor at load time: row counts must match
    the line scan, dtypes must match shard 0 (integer columns are
    widened to a float anchor automatically; anything else — e.g. a
    stray non-numeric token turning a column into strings — raises
    naming the shard and column).
    """

    def __init__(self, paths: Sequence[str], *, delimiter: str = ",",
                 header: bool = True,
                 names: Sequence[str] | None = None):
        paths = [str(p) for p in paths]
        if not paths:
            raise ValueError("CsvShardedDataset needs at least one "
                             "shard")
        self.paths = paths
        self._delimiter = delimiter
        self._header = header
        if not header and names is None:
            raise ValueError("header=False needs explicit names=")
        self._names = list(names) if names is not None else None
        self.shard_rows = []
        first_header: str | None = None
        for p in paths:
            rows = 0
            seen_header = False
            with open(p) as fh:
                for line in fh:
                    if not line.strip():
                        continue
                    if header and not seen_header:
                        # header = first NON-BLANK line, matching
                        # Dataset.from_csv's reader
                        seen_header = True
                        if first_header is None:
                            first_header = line.strip()
                        elif line.strip() != first_header:
                            raise ValueError(
                                f"shard {p} header {line.strip()!r} "
                                f"differs from {paths[0]}'s "
                                f"{first_header!r}")
                        continue
                    rows += 1
            if rows == 0:
                raise ValueError(f"shard {p} has no data rows")
            self.shard_rows.append(rows)
        # shard 0 is the schema anchor: parsing it here surfaces
        # duplicate columns, ragged rows, and the per-column dtypes
        anchor = self._parse(0)
        if len(anchor) != self.shard_rows[0]:
            raise ValueError(
                f"shard {paths[0]}: line scan found "
                f"{self.shard_rows[0]} data rows but the parser "
                f"yielded {len(anchor)}")
        self._dtypes = {k: v.dtype for k, v in anchor.columns.items()}
        self._column_names = sorted(anchor.column_names)

    def _parse(self, index: int) -> Dataset:
        return Dataset.from_csv(self.paths[index],
                                delimiter=self._delimiter,
                                header=self._header,
                                names=self._names)

    def load_shard(self, index: int) -> Dataset:
        out = self._parse(index)
        if len(out) != self.shard_rows[index]:
            raise ValueError(
                f"shard {self.paths[index]}: line scan found "
                f"{self.shard_rows[index]} data rows but the parser "
                f"yielded {len(out)}")
        cols = out.columns
        for k, want in self._dtypes.items():
            got = cols[k].dtype
            if got == want:
                continue
            if np.issubdtype(want, np.floating) \
                    and np.issubdtype(got, np.integer):
                # a shard whose values happen to all be
                # integer-formatted: widen to the float anchor so the
                # jitted step never retraces on dtype drift
                cols[k] = cols[k].astype(want)
                continue
            if got.kind == want.kind:
                if want.kind in "USO":
                    # string columns whose longest token differs per
                    # shard (<U2 vs <U5), the normal categorical
                    # shape; transformers hash or index per value,
                    # width is irrelevant
                    continue
                # same-kind numeric width drift (int32 vs an int64
                # anchor, say) would retrace the jitted step per
                # shard — cast to the anchor, but never silently: a
                # narrowing cast that changes any value is data
                # corruption, not schema alignment
                cast = cols[k].astype(want)
                if not np.array_equal(cast.astype(got), cols[k]):
                    raise ValueError(
                        f"shard {self.paths[index]} column {k!r} "
                        f"parsed as {got} with values that do not fit "
                        f"shard 0's anchor dtype {want}")
                cols[k] = cast
                continue
            raise ValueError(
                f"shard {self.paths[index]} column {k!r} parsed as "
                f"{got}, but shard 0 anchors it as {want} (a "
                f"non-numeric token turns a numeric column into "
                f"strings; clean the file or pre-bucket it)")
        return Dataset(cols)


def _resolve_paths(pattern_or_paths) -> list[str]:
    if isinstance(pattern_or_paths, (list, tuple)):
        return [str(p) for p in pattern_or_paths]
    paths = sorted(_glob.glob(str(pattern_or_paths)))
    if not paths:
        raise ValueError(f"no files match {pattern_or_paths!r}")
    return paths


def from_csv_shards(pattern_or_paths, *, delimiter: str = ",",
                    header: bool = True,
                    names: Sequence[str] | None = None
                    ) -> CsvShardedDataset:
    """``Dataset.from_csv_shards``: out-of-core dataset over delimited
    text files (glob pattern, sorted, or explicit path list)."""
    return CsvShardedDataset(_resolve_paths(pattern_or_paths),
                             delimiter=delimiter, header=header,
                             names=names)


def from_npz_shards(pattern_or_paths) -> ShardedDataset:
    """``Dataset.from_npz_shards``: build a ShardedDataset from a glob
    pattern (sorted) or an explicit path list."""
    return ShardedDataset(_resolve_paths(pattern_or_paths))


def to_npz_shards(dataset: Dataset, prefix: str,
                  rows_per_shard: int) -> list[str]:
    """Split ``dataset`` into ``.npz`` shard files
    ``{prefix}-00000.npz, ...``; returns the paths (the writer side of
    ``from_npz_shards``, used by tests/examples)."""
    if rows_per_shard < 1:
        raise ValueError(f"rows_per_shard must be >= 1, got "
                         f"{rows_per_shard}")
    n = len(dataset)
    paths = []
    for idx, start in enumerate(range(0, n, rows_per_shard)):
        part = Dataset({k: v[start:start + rows_per_shard]
                        for k, v in dataset.columns.items()})
        paths.append(part.to_npz(f"{prefix}-{idx:05d}.npz"))
    return paths

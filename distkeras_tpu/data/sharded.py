"""Out-of-core training data: a dataset backed by ``.npz`` shard files.

The reference's substrate (Spark DataFrames, SURVEY.md §1 L0) scaled
past host RAM by construction — partitions lived on the cluster and
flowed through executors.  The rebuild's equivalent is file-granular:
``ShardedDataset`` holds a *list of shard files* plus their row counts
(read from the npy headers, not the data), and materializes one shard
at a time.  Trainers iterate ``epoch_segments`` — shard files in a
seed-permuted order, rows shuffled within each shard ("shuffle what
fits", the standard out-of-core approximation of a global shuffle) —
so peak memory is one shard, not the dataset.

With a single shard file the epoch is bit-identical to the in-memory
path (same ``Dataset.shuffle(seed)`` permutation), which is the
equivalence contract ``tests/test_sharded_data.py`` pins.

Multi-host: every process sees the same deterministic segment order and
slices rows per process inside the trainer (``mesh.process_shard`` /
worker repartition), exactly as the in-memory path does.
"""

from __future__ import annotations

import glob as _glob
import zipfile
from typing import Iterator, Sequence

import numpy as np

from distkeras_tpu.data.dataset import Dataset


def _npz_column_meta(path: str) -> dict[str, tuple[tuple, np.dtype]]:
    """Column name -> (shape, dtype) from an npz's member headers —
    reads a few hundred bytes per member, never the array data."""
    from numpy.lib import format as npf

    meta = {}
    with zipfile.ZipFile(path) as z:
        for name in z.namelist():
            if not name.endswith(".npy"):
                continue
            with z.open(name) as fh:
                version = npf.read_magic(fh)
                if version == (1, 0):
                    shape, _, dtype = npf.read_array_header_1_0(fh)
                else:
                    shape, _, dtype = npf.read_array_header_2_0(fh)
            meta[name[:-4]] = (shape, dtype)
    return meta


class ShardedDataset:
    """A list of ``.npz`` shard files acting as one logical dataset.

    Construct via ``Dataset.from_npz_shards(pattern)`` or directly from
    paths.  Header metadata (row counts, columns, dtypes) is read
    eagerly and validated for consistency; array data is loaded one
    shard at a time by ``load_shard`` / ``epoch_segments``.
    """

    def __init__(self, paths: Sequence[str]):
        paths = [str(p) for p in paths]
        if not paths:
            raise ValueError("ShardedDataset needs at least one shard")
        self.paths = paths
        metas = [_npz_column_meta(p) for p in paths]
        names = set(metas[0])
        for p, m in zip(paths[1:], metas[1:]):
            if set(m) != names:
                raise ValueError(
                    f"shard {p} has columns {sorted(m)}, expected "
                    f"{sorted(names)} (from {paths[0]})")
            for k in names:
                if m[k][0][1:] != metas[0][k][0][1:]:
                    raise ValueError(
                        f"shard {p} column {k!r} has row shape "
                        f"{m[k][0][1:]}, expected {metas[0][k][0][1:]}")
        self._column_names = sorted(names)
        self.shard_rows = []
        for p, m in zip(paths, metas):
            counts = {v[0][0] for v in m.values()}
            if len(counts) != 1:
                raise ValueError(
                    f"shard {p}: column lengths differ: "
                    f"{ {k: v[0][0] for k, v in m.items()} }")
            self.shard_rows.append(counts.pop())

    # -- metadata ----------------------------------------------------------

    @property
    def column_names(self) -> list[str]:
        return list(self._column_names)

    @property
    def num_shards(self) -> int:
        return len(self.paths)

    def __len__(self) -> int:
        return int(sum(self.shard_rows))

    def __repr__(self) -> str:
        return (f"ShardedDataset(shards={self.num_shards}, "
                f"rows={len(self)}, columns={self._column_names})")

    # -- materialization ---------------------------------------------------

    def load_shard(self, index: int) -> Dataset:
        return Dataset.from_npz(self.paths[index])

    def to_dataset(self) -> Dataset:
        """Materialize everything (small sets / tests only)."""
        out = self.load_shard(0)
        for i in range(1, self.num_shards):
            out = out.concat(self.load_shard(i))
        return out

    def epoch_segment_loaders(self, seed: int = 0):
        """The epoch plan without the data: yields ``(rows, load)``
        pairs in the seed-permuted shard order, where ``rows`` comes
        from the header metadata and ``load()`` materializes that
        segment (shuffled).  Lets a resuming trainer skip whole shard
        files it has already consumed without reading them."""
        rng = np.random.default_rng(seed)
        order = (rng.permutation(self.num_shards)
                 if self.num_shards > 1 else [0])
        for slot, i in enumerate(order):
            # per-shard salt keeps distinct shards from sharing a
            # permutation; shard count 1 must keep the plain seed for
            # the bit-identity contract
            salt = 0 if self.num_shards == 1 else 1000003 * (slot + 1) + i
            yield (int(self.shard_rows[int(i)]),
                   lambda idx=int(i), s=seed + salt:
                   self.load_shard(idx).shuffle(seed=s))

    def epoch_segments(self, seed: int = 0) -> Iterator[Dataset]:
        """One training epoch as a stream of in-memory ``Dataset``
        segments: shard files in a seed-permuted order, rows shuffled
        within each shard.  Deterministic in ``seed``; with one shard
        this is exactly ``[full.shuffle(seed)]`` (the in-memory
        trainers' epoch), so single-shard training is bit-identical to
        in-memory training."""
        for _, load in self.epoch_segment_loaders(seed):
            yield load()


def from_npz_shards(pattern_or_paths) -> ShardedDataset:
    """``Dataset.from_npz_shards``: build a ShardedDataset from a glob
    pattern (sorted) or an explicit path list."""
    if isinstance(pattern_or_paths, (list, tuple)):
        return ShardedDataset(pattern_or_paths)
    paths = sorted(_glob.glob(str(pattern_or_paths)))
    if not paths:
        raise ValueError(
            f"no files match {pattern_or_paths!r}")
    return ShardedDataset(paths)


def to_npz_shards(dataset: Dataset, prefix: str,
                  rows_per_shard: int) -> list[str]:
    """Split ``dataset`` into ``.npz`` shard files
    ``{prefix}-00000.npz, ...``; returns the paths (the writer side of
    ``from_npz_shards``, used by tests/examples)."""
    if rows_per_shard < 1:
        raise ValueError(f"rows_per_shard must be >= 1, got "
                         f"{rows_per_shard}")
    n = len(dataset)
    paths = []
    for idx, start in enumerate(range(0, n, rows_per_shard)):
        part = Dataset({k: v[start:start + rows_per_shard]
                        for k, v in dataset.columns.items()})
        paths.append(part.to_npz(f"{prefix}-{idx:05d}.npz"))
    return paths

"""Crash flight recorder — a bounded on-disk ring of structured events
that survives the process that wrote it (ISSUE 6 tentpole 2).

The in-memory trace ring (``telemetry.Tracer``) dies with its process:
after a ``PSServer.kill()`` or an engine poison, the events that
explain the crash are exactly the ones that are gone.  This module is
the durable sibling: rare, structured, operationally-significant
events (commits, retries, chaos injections, snapshots, sheds, deadline
expiries, kills, restarts, SLO state flips, the serving gateway's
``replica_down`` / ``failover`` / ``weight_swap`` / ``rollback``
rollout story, and the replicated PS's ``ps_promote`` /
``ps_fenced`` / ``ps_replica_lag`` failover story) are appended as
JSON lines
to a small ring of on-disk segments, so ``scripts/postmortem.py`` can
reconstruct the last N seconds before a crash from the filesystem
alone and cross-check it against the restarted server's state.

Design constraints, in order:

* **Always cheap.**  One ``json.dumps`` + buffered write + ``flush()``
  per event, under one lock.  Events are RARE (per commit / retry /
  shed, never per token or per batch), so the disabled check is the
  only cost on hot paths that gate on ``record()`` — a module-global
  ``None`` test.
* **Bounded.**  Segments rotate after ``segment_events`` lines; at most
  ``segments`` sealed segments are kept (oldest deleted first), so a
  week-long run cannot fill a disk.
* **Atomic rotation.**  The live segment is written as
  ``segment-N.jsonl.open`` and sealed by ``os.replace`` to
  ``segment-N.jsonl`` — a reader never sees a half-renamed file, and a
  crashed writer leaves at most one ``.open`` file (which readers still
  parse, line by line, tolerating a torn final line).
* **Flush on every exit path.**  ``atexit`` closes the active recorder;
  ``PSServer.kill()`` calls ``flush(fsync=True)`` explicitly before the
  listener dies, so the kill-path events are durable even against a
  following hard crash.

Every event carries ``kind`` plus three stamps: ``wall_s``
(``time.time()`` — the cross-process ordering key), ``mono_s``
(``telemetry.now()`` — same clock as the trace spans, so flight events
line up against a merged trace), and ``pid``.

Usage::

    from distkeras_tpu import flight_recorder
    flight_recorder.start("/tmp/fdr")        # enable (off by default)
    ... run trainers / engine / chaos ...
    flight_recorder.record("my_event", detail=1)   # no-op when off
    events = flight_recorder.active().read_events()
    flight_recorder.stop()
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Any

from distkeras_tpu import telemetry
from distkeras_tpu.analysis import racecheck


class FlightRecorder:
    """Bounded JSONL segment ring in ``directory``.

    ``record(kind, **fields)`` appends one event; segments seal by
    atomic rename after ``segment_events`` events and at most
    ``segments`` sealed segments are retained.  ``read_events()``
    replays the surviving window in write order.
    """

    def __init__(self, directory: str | os.PathLike,
                 segment_events: int = 256, segments: int = 8):
        if segment_events < 1 or segments < 1:
            raise ValueError(
                f"segment_events and segments must be >= 1; got "
                f"{segment_events}, {segments}")
        self.directory = os.fspath(directory)
        self.segment_events = int(segment_events)
        self.segments = int(segments)
        os.makedirs(self.directory, exist_ok=True)
        self._lock = racecheck.lock("flight_recorder")
        self._seq = 0  # per-recorder monotone event index
        self._file = None
        self._file_events = 0
        # resume numbering past whatever a previous incarnation left
        self._segment_n = 1 + max(
            [n for n, _ in self._list_segments()], default=-1)

    # -- writing ------------------------------------------------------

    def _open_path(self, n: int) -> str:
        return os.path.join(self.directory, f"segment-{n:06d}.jsonl.open")

    def _sealed_path(self, n: int) -> str:
        return os.path.join(self.directory, f"segment-{n:06d}.jsonl")

    def record(self, kind: str, **fields: Any) -> dict:
        """Append one event (thread-safe); returns the event dict."""
        event = {"kind": kind, "wall_s": time.time(),
                 "mono_s": telemetry.now(), "pid": os.getpid(),
                 **fields}
        with self._lock:
            # the recorder's own index (NOT ``seq`` — that name
            # belongs to callers, e.g. commit events) is assigned
            # under the lock so readers can re-establish write order
            # even across a wall-clock step
            event["rec_seq"] = self._seq
            self._seq += 1
            if self._file is None:
                self._file = open(self._open_path(self._segment_n), "w")
                self._file_events = 0
            self._file.write(json.dumps(event, default=repr) + "\n")
            self._file.flush()
            self._file_events += 1
            if self._file_events >= self.segment_events:
                self._seal_locked()
        return event

    def _seal_locked(self) -> None:
        if self._file is None:
            return
        self._file.close()
        os.replace(self._open_path(self._segment_n),
                   self._sealed_path(self._segment_n))
        self._file = None
        self._segment_n += 1
        # retention: drop oldest sealed segments beyond the ring bound
        sealed = sorted(n for n, p in self._list_segments()
                        if p.endswith(".jsonl"))
        for n in sealed[:max(0, len(sealed) - self.segments)]:
            try:
                os.remove(self._sealed_path(n))
            except OSError:
                pass

    def flush(self, fsync: bool = False) -> None:
        """Push buffered events to the OS; ``fsync=True`` makes them
        durable against a machine-level crash (the kill path uses
        this)."""
        with self._lock:
            if self._file is not None:
                self._file.flush()
                if fsync:
                    os.fsync(self._file.fileno())

    def close(self) -> None:
        """Seal the live segment (idempotent)."""
        with self._lock:
            self._seal_locked()

    # -- reading ------------------------------------------------------

    def _list_segments(self) -> list[tuple[int, str]]:
        out = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return out
        for fn in names:
            if fn.startswith("segment-") and ".jsonl" in fn:
                try:
                    n = int(fn.split("-")[1].split(".")[0])
                except ValueError:
                    continue
                out.append((n, os.path.join(self.directory, fn)))
        return sorted(out)

    def read_events(self) -> list[dict]:
        """Every surviving event, in write order.  Tolerates a torn
        final line in a crashed writer's ``.open`` segment."""
        with self._lock:
            if self._file is not None:
                self._file.flush()
        events = []
        for _, path in self._list_segments():
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            events.append(json.loads(line))
                        except json.JSONDecodeError:
                            pass  # torn tail of a crashed segment
            except FileNotFoundError:
                pass  # rotated away between list and open
        events.sort(key=lambda e: (e.get("wall_s", 0.0),
                                   e.get("pid", 0),
                                   e.get("rec_seq", 0)))
        return events

    def last(self, seconds: float,
             until_wall_s: float | None = None) -> list[dict]:
        """The events of the ``seconds``-wide window ending at
        ``until_wall_s`` (default: the newest recorded event) — the
        postmortem's "last N seconds before the crash"."""
        events = self.read_events()
        if not events:
            return []
        end = (max(e.get("wall_s", 0.0) for e in events)
               if until_wall_s is None else float(until_wall_s))
        return [e for e in events
                if end - float(seconds) <= e.get("wall_s", 0.0) <= end]


# -- the module-global recorder (off by default) -----------------------

_active: FlightRecorder | None = None
_lock = threading.Lock()
_atexit_registered = False


def start(directory: str | os.PathLike, segment_events: int = 256,
          segments: int = 8) -> FlightRecorder:
    """Install (and return) the global recorder.  Replacing an active
    recorder seals its live segment first."""
    global _active, _atexit_registered
    fr = FlightRecorder(directory, segment_events=segment_events,
                        segments=segments)
    with _lock:
        old, _active = _active, fr
        if not _atexit_registered:
            atexit.register(stop)
            _atexit_registered = True
    if old is not None:
        old.close()
    return fr


def stop() -> None:
    """Seal and deactivate the global recorder (idempotent)."""
    global _active
    with _lock:
        old, _active = _active, None
    if old is not None:
        old.close()


def active() -> FlightRecorder | None:
    return _active


def record(kind: str, **fields: Any) -> None:
    """Record onto the global recorder; a no-op (one None test) when
    no recorder is active — safe on every hot-ish path."""
    fr = _active
    if fr is not None:
        fr.record(kind, **fields)


def flush(fsync: bool = False) -> None:
    fr = _active
    if fr is not None:
        fr.flush(fsync=fsync)

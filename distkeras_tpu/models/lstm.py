"""BiLSTM text classifier for the IMDB baseline config (BASELINE.md:
"IMDB BiLSTM with DynSGD").  The reference handles sequence workloads as
plain Keras models inside each worker (SURVEY.md §5 "long-context: absent");
here the recurrence is a ``flax.linen.RNN`` (lax.scan under jit — static
shapes, no per-step Python)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.core import register_model


@register_model("bilstm")
class BiLSTMClassifier(nn.Module):
    """Embed -> BiLSTM -> masked mean-pool -> dense head.

    Token id 0 is treated as padding and masked out of the pool.
    """

    vocab_size: int = 20000
    embed_dim: int = 128
    hidden_dim: int = 128
    num_classes: int = 2
    dropout_rate: float = 0.0
    dtype: str = "float32"

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        dtype = jnp.dtype(self.dtype)
        tokens = tokens.astype(jnp.int32)
        mask = (tokens != 0).astype(dtype)[..., None]  # [B, T, 1]

        x = nn.Embed(self.vocab_size, self.embed_dim, dtype=dtype)(tokens)

        # seq_lengths keeps the recurrence padding-invariant: the reverse
        # pass starts at each sequence's last valid token, not at the pad.
        lengths = jnp.sum(tokens != 0, axis=1)
        fwd = nn.RNN(nn.OptimizedLSTMCell(self.hidden_dim, dtype=dtype))
        bwd = nn.RNN(nn.OptimizedLSTMCell(self.hidden_dim, dtype=dtype),
                     reverse=True, keep_order=True)
        x = jnp.concatenate([fwd(x, seq_lengths=lengths),
                             bwd(x, seq_lengths=lengths)], axis=-1)

        x = jnp.sum(x * mask, axis=1) / jnp.maximum(
            jnp.sum(mask, axis=1), 1.0)
        if self.dropout_rate > 0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.hidden_dim, dtype=dtype)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)

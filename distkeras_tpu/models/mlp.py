"""MLP for the MNIST baseline config (BASELINE.md: "MNIST MLP,
SingleTrainer").  The reference's MNIST notebook builds a small Keras
``Sequential`` dense stack; this is the flax equivalent."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.core import register_model


@register_model("mlp")
class MLP(nn.Module):
    """Dense stack: [hidden...] -> num_classes logits."""

    num_classes: int = 10
    hidden: Sequence[int] = (500, 500)
    dropout_rate: float = 0.0
    dtype: str = "float32"

    @nn.compact
    def __call__(self, x, train: bool = False):
        dtype = jnp.dtype(self.dtype)
        x = x.reshape((x.shape[0], -1)).astype(dtype)
        for width in self.hidden:
            x = nn.Dense(width, dtype=dtype)(x)
            x = nn.relu(x)
            if self.dropout_rate > 0:
                x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)

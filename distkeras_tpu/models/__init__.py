"""Model zoo: pure-JAX/flax models for the five baseline configs.

TPU-native replacement for the reference's model layer (SURVEY.md §2.1: the
reference carries *no* models of its own — it ships serialized Keras graphs
into Spark tasks via ``utils.serialize_keras_model``).  Here models are flax
modules built from a JSON-serializable config dict (``build_model``), which
is the wire-format analogue of the reference's architecture-JSON: the config
travels, not pickled code.
"""

from distkeras_tpu.models.core import (  # noqa: F401
    MODEL_REGISTRY,
    ModelSpec,
    build_model,
    init_model,
    model_config,
    register_model,
)
from distkeras_tpu.models.mlp import MLP  # noqa: F401
from distkeras_tpu.models.convnet import ConvNet  # noqa: F401
from distkeras_tpu.models.resnet import ResNet, ResNet18, ResNet50  # noqa: F401
from distkeras_tpu.models.lstm import BiLSTMClassifier  # noqa: F401
from distkeras_tpu.models.widedeep import WideAndDeep  # noqa: F401
from distkeras_tpu.models.transformer import TransformerLM  # noqa: F401
from distkeras_tpu.models.generate import (  # noqa: F401
    beam_search,
    generate,
)

"""Decoder-only Transformer LM — the long-context flagship.

The reference has no long-sequence story (SURVEY.md §5: "long-context /
sequence parallelism: absent"); the TPU rebuild makes it first-class.  The
attention op is pluggable: dense causal attention on a single device, or
ring attention over a mesh axis (``distkeras_tpu.parallel.ring_attention``)
when ``seq_axis`` is set and the caller shards the time dimension
(``parallel.ring_attention.sequence_sharded_apply``).
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.core import register_model

AttnFn = Callable[..., jnp.ndarray]


def dense_causal_attention(q, k, v, *, scale):
    """Plain causal attention: [B, T, H, D] -> [B, T, H, D]."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    t = q.shape[1]
    causal = jnp.tril(jnp.ones((t, t), dtype=bool))
    logits = jnp.where(causal[None, None], logits, -1e30)
    probs = nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class SelfAttention(nn.Module):
    num_heads: int
    dtype: jnp.dtype
    attn_fn: Optional[AttnFn] = None

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        if d_model % self.num_heads:
            raise ValueError(
                f"d_model={d_model} not divisible by "
                f"num_heads={self.num_heads}")
        head_dim = d_model // self.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.num_heads, head_dim), dtype=self.dtype, name=name)
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)
        attn = self.attn_fn or dense_causal_attention
        out = attn(q, k, v, scale=head_dim ** -0.5)
        return nn.DenseGeneral(d_model, axis=(-2, -1), dtype=self.dtype,
                               name="out")(out)


class Block(nn.Module):
    num_heads: int
    mlp_ratio: int
    dtype: jnp.dtype
    attn_fn: Optional[AttnFn] = None

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        y = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + SelfAttention(self.num_heads, self.dtype, self.attn_fn)(y)
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.Dense(d_model * self.mlp_ratio, dtype=self.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(d_model, dtype=self.dtype)(y)
        return x + y


@register_model("transformer_lm")
class TransformerLM(nn.Module):
    """``seq_axis``: name of a mesh axis the *time* dimension is sharded
    over.  When set, the module is an SPMD program to be applied inside
    ``jax.shard_map`` (see ``parallel.ring_attention.sequence_sharded_
    apply``): positions are offset by the device's ring index and
    attention defaults to ``ring_attention`` over that axis.  Every other
    sublayer is position-wise, so nothing else changes — the same
    parameters run dense or sequence-parallel."""

    vocab_size: int = 32000
    num_layers: int = 4
    d_model: int = 256
    num_heads: int = 4
    mlp_ratio: int = 4
    max_len: int = 2048
    dtype: str = "bfloat16"
    attn_fn: Optional[AttnFn] = None  # None -> dense causal / ring
    seq_axis: Optional[str] = None
    # within-device q block length for ring attention (None = full
    # block); see parallel.ring_attention.ring_attention(q_chunk=)
    attn_q_chunk: Optional[int] = None

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        import jax.lax as lax

        dtype = jnp.dtype(self.dtype)
        tokens = tokens.astype(jnp.int32)
        t = tokens.shape[1]
        attn_fn = self.attn_fn
        if self.seq_axis is not None:
            from distkeras_tpu.parallel.ring_attention import ring_attn_fn

            t_global = t * lax.axis_size(self.seq_axis)
            positions = (lax.axis_index(self.seq_axis) * t
                         + jnp.arange(t))[None, :]
            if attn_fn is None:
                attn_fn = ring_attn_fn(self.seq_axis,
                                       q_chunk=self.attn_q_chunk)
        else:
            t_global = t
            positions = jnp.arange(t)[None, :]
        if t_global > self.max_len:
            raise ValueError(
                f"sequence length {t_global} exceeds "
                f"max_len={self.max_len}")
        x = nn.Embed(self.vocab_size, self.d_model, dtype=dtype)(tokens)
        pos = nn.Embed(self.max_len, self.d_model, dtype=dtype,
                       name="pos_embed")(positions)
        x = x + pos
        for _ in range(self.num_layers):
            x = Block(self.num_heads, self.mlp_ratio, dtype, attn_fn)(x)
        x = nn.LayerNorm(dtype=dtype)(x)
        return nn.Dense(self.vocab_size, dtype=jnp.float32,
                        name="lm_head")(x)

"""Decoder-only Transformer LM — the long-context flagship.

The reference has no long-sequence story (SURVEY.md §5: "long-context /
sequence parallelism: absent"); the TPU rebuild makes it first-class.  The
attention op is pluggable: dense causal attention on a single device, or
ring attention over a mesh axis (``distkeras_tpu.parallel.ring_attention``)
when ``seq_axis`` is set and the caller shards the time dimension
(``parallel.ring_attention.sequence_sharded_apply``).
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.core import register_model
from distkeras_tpu.parallel.moe import expert_capacity, routing

AttnFn = Callable[..., jnp.ndarray]


def dense_causal_attention(q, k, v, *, scale):
    """Plain causal attention: [B, T, H, D] -> [B, T, H, D]."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    t = q.shape[1]
    causal = jnp.tril(jnp.ones((t, t), dtype=bool))
    logits = jnp.where(causal[None, None], logits, -1e30)
    probs = nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class SelfAttention(nn.Module):
    """``cache_len > 0`` switches on autoregressive decode mode: K/V
    projections of every token seen so far persist in a ``"cache"``
    variable collection (``cached_key``/``cached_value`` sized
    ``[B, cache_len, H, D]`` plus an insertion ``cache_index``), and
    each call appends its T tokens and attends back over the whole
    prefix.  No counterpart in the reference — it predates
    autoregressive serving entirely (SURVEY.md §0: MLP/CNN-era
    workloads; predictors are one batched forward)."""

    num_heads: int
    dtype: jnp.dtype
    attn_fn: Optional[AttnFn] = None
    cache_len: int = 0

    @nn.compact
    def __call__(self, x):
        import jax.lax as lax

        d_model = x.shape[-1]
        if d_model % self.num_heads:
            raise ValueError(
                f"d_model={d_model} not divisible by "
                f"num_heads={self.num_heads}")
        head_dim = d_model // self.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.num_heads, head_dim), dtype=self.dtype, name=name)
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)
        if self.cache_len > 0:
            b, t = x.shape[0], x.shape[1]
            shape = (b, self.cache_len, self.num_heads, head_dim)
            ck = self.variable("cache", "cached_key", jnp.zeros, shape,
                               k.dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros,
                               shape, v.dtype)
            ci = self.variable("cache", "cache_index",
                               lambda: jnp.zeros((), jnp.int32))
            idx = ci.value
            ck.value = lax.dynamic_update_slice(ck.value, k,
                                                (0, idx, 0, 0))
            cv.value = lax.dynamic_update_slice(cv.value, v,
                                                (0, idx, 0, 0))
            ci.value = idx + t
            # q rows sit at global positions idx..idx+t-1; causal mask
            # over the full cache (future slots are zeros AND masked)
            q_pos = idx + jnp.arange(t)
            k_pos = jnp.arange(self.cache_len)
            mask = k_pos[None, :] <= q_pos[:, None]         # [t, L]
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, ck.value) \
                * head_dim ** -0.5
            logits = jnp.where(mask[None, None], logits, -1e30)
            probs = nn.softmax(logits.astype(jnp.float32),
                               axis=-1).astype(q.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, cv.value)
            # Overflow is a traced condition (cache_index is dynamic),
            # so it cannot raise; dynamic_update_slice would silently
            # CLAMP the write and corrupt the cache.  Poison the
            # output with NaN instead — loud under jit, and it
            # propagates to any downstream logit/metric.
            ok = idx + t <= self.cache_len
            out = jnp.where(ok, out, jnp.nan)
        else:
            attn = self.attn_fn or dense_causal_attention
            out = attn(q, k, v, scale=head_dim ** -0.5)
        return nn.DenseGeneral(d_model, axis=(-2, -1), dtype=self.dtype,
                               name="out")(out)


class MoEFFN(nn.Module):
    """Mixture-of-experts FFN in the dense einsum (GShard/Mesh-TF)
    form: every expert-dim op is a batched matmul over ``E``, so
    sharding the parameters' leading expert axis (see
    ``parallel.tensor_parallel.TRANSFORMER_TP_RULES``) makes GSPMD
    derive the expert-parallel communication — no ``shard_map``
    needed, and the same module runs replicated on one device.

    Routing reuses ``parallel.moe._routing`` (top-k, capacity
    bucketing, f32 bookkeeping).  The load-balancing auxiliary loss is
    sown into the ``"losses"`` collection, which
    ``workers.make_train_step`` adds to the objective."""

    num_experts: int
    mlp_ratio: int
    dtype: jnp.dtype
    capacity_factor: float = 1.25
    top_k: int = 1
    aux_loss_weight: float = 0.01

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        h = d * self.mlp_ratio
        e = self.num_experts
        if not 1 <= self.top_k <= e:
            raise ValueError(
                f"expert_top_k={self.top_k} out of range [1, {e}]")
        tokens = x.reshape(b * t, d)
        capacity = expert_capacity(b * t, e, self.capacity_factor,
                                   self.top_k)
        router = self.param(
            "router", nn.initializers.normal(d ** -0.5), (d, e))
        w_in = self.param(
            "w_in", nn.initializers.normal(d ** -0.5), (e, d, h))
        b_in = self.param("b_in", nn.initializers.zeros, (e, h))
        w_out = self.param(
            "w_out", nn.initializers.normal(h ** -0.5), (e, h, d))
        b_out = self.param("b_out", nn.initializers.zeros, (e, d))

        dispatch, combine, aux = routing(
            tokens.astype(self.dtype), router, e, capacity, self.top_k)
        expert_in = jnp.einsum("tec,td->ecd", dispatch,
                               tokens.astype(self.dtype))
        hidden = nn.gelu(
            jnp.einsum("ecd,edh->ech", expert_in,
                       w_in.astype(self.dtype))
            + b_in.astype(self.dtype)[:, None])
        out = (jnp.einsum("ech,ehd->ecd", hidden,
                          w_out.astype(self.dtype))
               + b_out.astype(self.dtype)[:, None])
        y = jnp.einsum("tec,ecd->td", combine, out)
        self.sow("losses", "moe_load_balance",
                 self.aux_loss_weight * aux.load_balance_loss)
        return y.reshape(b, t, d)


class Block(nn.Module):
    num_heads: int
    mlp_ratio: int
    dtype: jnp.dtype
    attn_fn: Optional[AttnFn] = None
    num_experts: int = 0  # 0 = dense MLP; >0 = MoE FFN
    expert_capacity_factor: float = 1.25
    expert_top_k: int = 1
    cache_len: int = 0  # >0 = autoregressive decode (KV cache)

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        y = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + SelfAttention(self.num_heads, self.dtype, self.attn_fn,
                              cache_len=self.cache_len)(y)
        y = nn.LayerNorm(dtype=self.dtype)(x)
        if self.num_experts > 0:
            y = MoEFFN(self.num_experts, self.mlp_ratio, self.dtype,
                       self.expert_capacity_factor, self.expert_top_k,
                       name="moe")(y)
        else:
            y = nn.Dense(d_model * self.mlp_ratio, dtype=self.dtype)(y)
            y = nn.gelu(y)
            y = nn.Dense(d_model, dtype=self.dtype)(y)
        return x + y


class _BlockScanBody(nn.Module):
    """``nn.scan``-compatible wrapper: ``(carry, _) -> (carry, None)``
    around one ``Block`` so the layer stack's parameters materialize as
    one stacked pytree (leading axis = layers) — the homogeneous form
    pipeline parallelism slices per stage (``parallel.pipeline``)."""

    num_heads: int
    mlp_ratio: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, carry, _):
        return Block(self.num_heads, self.mlp_ratio, self.dtype,
                     name="layer")(carry), None


@register_model("transformer_lm")
class TransformerLM(nn.Module):
    """``seq_axis``: name of a mesh axis the *time* dimension is sharded
    over.  When set, the module is an SPMD program to be applied inside
    ``jax.shard_map`` (see ``parallel.ring_attention.sequence_sharded_
    apply``): positions are offset by the device's ring index and
    attention defaults to ``ring_attention`` over that axis.  Every other
    sublayer is position-wise, so nothing else changes — the same
    parameters run dense or sequence-parallel."""

    vocab_size: int = 32000
    num_layers: int = 4
    d_model: int = 256
    num_heads: int = 4
    mlp_ratio: int = 4
    max_len: int = 2048
    dtype: str = "bfloat16"
    attn_fn: Optional[AttnFn] = None  # None -> dense causal / ring
    seq_axis: Optional[str] = None
    # within-device q block length for ring/blockwise attention (None =
    # full block); see parallel.ring_attention.ring_attention(q_chunk=)
    attn_q_chunk: Optional[int] = None
    #: single-device flash-style attention (JSON-able spelling of
    #: attn_fn=blockwise_attn_fn(...)): online-softmax q-chunking, the
    #: [T, T] logits never materialize — the long-T device-local path
    #: (PERF.md §13).  q chunk length = attn_q_chunk (default 128, the
    #: measured v5e optimum).
    blockwise_attn: bool = False
    #: hand-written Pallas flash-attention kernels (JSON-able spelling
    #: of attn_fn=ops.attention.flash_attn_fn()): same online-
    #: softmax algorithm as blockwise_attn but as one Mosaic kernel per
    #: pass — accumulators VMEM-resident, k/v blocks pipelined, causal
    #: blocks grid-skipped.  The fastest long-T path on the v5e
    #: (PERF.md §17).  Always uses the kernel's measured block defaults
    #: (512/1024, auto-clamped to divisors of T); attn_q_chunk applies
    #: to the blockwise/ring paths only — its tuned values (~128) sit
    #: in the kernel's WORST regime, so it is deliberately not reused
    #: here.  To tune blocks, pass attn_fn=flash_attn_fn(block_q=...).
    flash_attn: bool = False
    # >0 replaces every block's MLP with a mixture-of-experts FFN
    # (dense einsum form — shard the expert axes via the TP rules for
    # expert parallelism); the load-balance aux loss rides the
    # "losses" collection into the training objective
    num_experts: int = 0
    expert_capacity_factor: float = 1.25
    expert_top_k: int = 1
    #: stack the layer parameters [num_layers, ...] via nn.scan (same
    #: math per layer; different param-tree layout).  Required by the
    #: pipeline-parallel trainer path, which shards the layer stack's
    #: leading axis across stages.  Incompatible with attn_fn/seq_axis/
    #: MoE (those paths keep per-layer modules).
    scan_blocks: bool = False
    #: rematerialize each Block in the backward pass
    #: (``jax.checkpoint``): activations inside a block are recomputed
    #: from its input instead of stored, trading ~1 extra forward of
    #: FLOPs for O(layers) less activation memory — the lever that
    #: fits batches past the HBM envelope at long T (measured: b8 at
    #: T=8192 OOMs by 2.4 GB without it; PERF.md §19).
    remat_blocks: bool = False
    #: autoregressive decode mode for serving (``models.generate``):
    #: every attention layer keeps a ``max_len``-slot KV cache in the
    #: ``"cache"`` variable collection and calls append to it, so the
    #: prompt is processed once and each new token costs one T=1 step.
    #: Apply with ``mutable=["cache"]`` and thread the returned cache.
    #: Returns logits for the LAST input position only ([B, 1, V]) —
    #: the one generation consumes; full-vocab f32 logits over a whole
    #: prompt would dominate prefill activations for nothing.  Same
    #: parameters as the training-mode model (``decode`` changes
    #: execution, not the param tree).  Incompatible with seq_axis /
    #: blockwise_attn / flash_attn / attn_fn / scan_blocks (decode
    #: attention is one row against the cache — nothing to block).
    decode: bool = False

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        import jax.lax as lax

        dtype = jnp.dtype(self.dtype)
        tokens = tokens.astype(jnp.int32)
        t = tokens.shape[1]
        attn_fn = self.attn_fn
        cache_len = 0
        if self.decode:
            if (self.seq_axis is not None or self.blockwise_attn
                    or self.flash_attn or self.attn_fn is not None
                    or self.scan_blocks):
                raise ValueError(
                    "decode=True is the KV-cache serving path: "
                    "attention is one query row against the cache, so "
                    "seq_axis/blockwise_attn/flash_attn/attn_fn/"
                    "scan_blocks do not apply")
            if self.num_experts > 0:
                raise ValueError(
                    "decode=True cannot serve MoE models: capacity-"
                    "bucketed routing over a short decode step "
                    "diverges from the full-forward routing the model "
                    "trained with (different tokens overflow and "
                    "drop) — serve MoE via the dense full-forward "
                    "path (predictors) instead")
            if t > self.max_len:
                raise ValueError(
                    f"decode chunk length {t} exceeds the cache size "
                    f"max_len={self.max_len}")
            cache_len = self.max_len
        if self.blockwise_attn and self.flash_attn:
            raise ValueError(
                "blockwise_attn and flash_attn are mutually exclusive "
                "spellings of the device-local flash-style attention "
                "path")
        if self.seq_axis is not None and (self.blockwise_attn
                                          or self.flash_attn):
            raise ValueError(
                "blockwise_attn/flash_attn are device-local attention "
                "paths; with seq_axis the attention is ring attention "
                "over the mesh — use attn_q_chunk to bound its "
                "within-device blocks instead")
        if self.seq_axis is not None:
            from distkeras_tpu.parallel.ring_attention import ring_attn_fn

            t_global = t * lax.axis_size(self.seq_axis)
            positions = (lax.axis_index(self.seq_axis) * t
                         + jnp.arange(t))[None, :]
            if attn_fn is None:
                attn_fn = ring_attn_fn(self.seq_axis,
                                       q_chunk=self.attn_q_chunk)
        elif self.decode:
            t_global = t  # chunk length; prefix bound checked above
            pos_var = self.variable("cache", "pos_index",
                                    lambda: jnp.zeros((), jnp.int32))
            positions = (pos_var.value + jnp.arange(t))[None, :]
            pos_var.value = pos_var.value + t
        else:
            t_global = t
            positions = jnp.arange(t)[None, :]
            if attn_fn is None and self.blockwise_attn:
                from distkeras_tpu.parallel.ring_attention import \
                    blockwise_attn_fn

                attn_fn = blockwise_attn_fn(
                    q_chunk=self.attn_q_chunk or 128)
            elif attn_fn is None and self.flash_attn:
                from distkeras_tpu.ops.attention import \
                    flash_attn_fn

                attn_fn = flash_attn_fn()
        if t_global > self.max_len:
            raise ValueError(
                f"sequence length {t_global} exceeds "
                f"max_len={self.max_len}")
        x = nn.Embed(self.vocab_size, self.d_model, dtype=dtype)(tokens)
        pos = nn.Embed(self.max_len, self.d_model, dtype=dtype,
                       name="pos_embed")(positions)
        x = x + pos
        if self.scan_blocks:
            if (self.num_experts > 0 or self.attn_fn is not None
                    or self.seq_axis is not None or self.blockwise_attn
                    or self.flash_attn or self.remat_blocks):
                raise ValueError(
                    "scan_blocks=True supports the dense-attention, "
                    "dense-FFN transformer only (MoE / custom attn / "
                    "seq_axis / remat_blocks keep per-layer modules)")
            scanned = nn.scan(
                _BlockScanBody,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=self.num_layers)(
                    self.num_heads, self.mlp_ratio, dtype,
                    name="blocks")
            x, _ = scanned(x, None)
        else:
            block_cls = nn.remat(Block) if self.remat_blocks else Block
            for i in range(self.num_layers):
                # explicit names keep the param tree identical whether
                # or not remat wraps the block (nn.remat's auto-name
                # would be CheckpointBlock_i) — remat_blocks can be
                # toggled on existing checkpoints
                x = block_cls(self.num_heads, self.mlp_ratio, dtype,
                              attn_fn, self.num_experts,
                              self.expert_capacity_factor,
                              self.expert_top_k,
                              cache_len=cache_len,
                              name=f"Block_{i}")(x)
        if self.decode:
            # serving returns next-token logits only: the f32
            # full-vocab lm_head over every prompt position would be
            # the prefill's dominant activation for nothing (only the
            # last row seeds generation)
            x = x[:, -1:]
        x = nn.LayerNorm(dtype=dtype)(x)
        return nn.Dense(self.vocab_size, dtype=jnp.float32,
                        name="lm_head")(x)

"""Decoder-only Transformer LM — the long-context flagship.

The reference has no long-sequence story (SURVEY.md §5: "long-context /
sequence parallelism: absent"); the TPU rebuild makes it first-class.  The
attention op is pluggable: dense causal attention on a single device, or
ring attention over a mesh axis (``distkeras_tpu.parallel.ring_attention``)
when ``seq_axis`` is set and the caller shards the time dimension
(``parallel.ring_attention.sequence_sharded_apply``).

By default (``attn="auto"``) the device-local attention spelling is
selected per shape from the measured recipe (PERF.md §17): Pallas flash
kernels at T >= 2048 (on TPU), the scan-composed blockwise path at
T=1024-class shapes, dense below — so an untuned model gets the fastest
measured execution for its sequence length.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from distkeras_tpu.models.core import register_model
from distkeras_tpu.parallel.moe import expert_capacity, routing
from distkeras_tpu.utils import axis_size

AttnFn = Callable[..., jnp.ndarray]

_ATTN_CHOICES = ("auto", "dense", "blockwise", "flash")


def _committed_platform(x) -> Optional[str]:
    """Platform of the devices ``x`` is committed to, when knowable.

    Eager calls on placed arrays resolve against the ACTUAL placement
    (ADVICE r5: a CPU-forced debugging run on a TPU host must not pick
    the Pallas path).  Under ``jit`` the input is a tracer with no
    committed devices; returns None so callers fall back to the
    repo-wide ``jax.devices()[0]`` convention — the default backend's
    first device, which is where an unpinned trace executes."""
    try:
        platforms = {d.platform for d in x.devices()}
        if len(platforms) == 1:
            return platforms.pop()
    except Exception:
        pass
    return None


def dense_causal_attention(q, k, v, *, scale):
    """Plain causal attention: [B, T, H, D] -> [B, T, H, D]."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    t = q.shape[1]
    causal = jnp.tril(jnp.ones((t, t), dtype=bool))
    logits = jnp.where(causal[None, None], logits, -1e30)
    probs = nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _quantize_kv(x):
    """Symmetric per-(batch, position, head) int8 quantization of a
    K/V chunk: returns ``(int8 values, f32 scales [..., 1])``.  The
    scale is the row's abs-max over head_dim / 127, so dequantization
    (``int8 * scale``) is error-bounded by amax/254 per element."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = amax / 127.0
    q = jnp.where(scale > 0.0, xf / jnp.maximum(scale, 1e-30), 0.0)
    return jnp.clip(jnp.round(q), -127, 127).astype(jnp.int8), scale


class SelfAttention(nn.Module):
    """``cache_len > 0`` switches on autoregressive decode mode: K/V
    projections of every token seen so far persist in a ``"cache"``
    variable collection (``cached_key``/``cached_value`` sized
    ``[B, KVH, cache_len, D]`` — length contiguous, the measured
    decode-bandwidth layout — plus an insertion ``cache_index``), and
    each call appends its T tokens and attends back over the whole
    prefix.  A multi-token call (prefill) with an ``attn_fn`` runs the
    chunk through that kernel instead of the dense cache read — exact
    iff the cache was empty (poisoned loud otherwise).  No counterpart
    in the reference — it predates autoregressive serving entirely
    (SURVEY.md §0: MLP/CNN-era workloads; predictors are one batched
    forward).

    ``num_kv_heads`` (GQA): K/V project to fewer heads than Q; groups
    of ``num_heads/num_kv_heads`` query heads share a K/V head.  The
    decode-time win is the KV cache — its size and per-token HBM read
    shrink by the group factor (PERF.md §18: decode is cache+weight
    bandwidth-bound).  Training-path attention repeats K/V up to the
    full head count (the kernels expect matched heads).

    ``kv_cache_dtype="int8"`` stores the cache quantized (symmetric
    per-position-per-head scales in f32) — halving the bf16 cache's
    HBM traffic — and dequantizes on read.

    ``slot_pos`` (call-time, ``[B]`` int32) switches the T=1 step to
    SLOT mode for continuous-batching serving (``serving.DecodeEngine``):
    each batch row is an independent request at its OWN cache position,
    so the K/V write is a per-row scatter at ``slot_pos[b]`` and the
    causal mask is per-row (``k <= slot_pos[b]``).  The scalar
    ``cache_index`` is left untouched — slot state lives with the
    engine, which admits/evicts rows between steps.
    """

    num_heads: int
    dtype: jnp.dtype
    attn_fn: Optional[AttnFn] = None
    cache_len: int = 0
    num_kv_heads: Optional[int] = None
    kv_cache_dtype: Optional[str] = None

    @nn.compact
    def __call__(self, x, slot_pos=None):
        import jax.lax as lax

        d_model = x.shape[-1]
        if d_model % self.num_heads:
            raise ValueError(
                f"d_model={d_model} not divisible by "
                f"num_heads={self.num_heads}")
        head_dim = d_model // self.num_heads
        kvh = self.num_kv_heads or self.num_heads
        if self.num_heads % kvh:
            raise ValueError(
                f"num_heads={self.num_heads} not divisible by "
                f"num_kv_heads={kvh}")
        group = self.num_heads // kvh
        dense = lambda name, heads: nn.DenseGeneral(  # noqa: E731
            (heads, head_dim), dtype=self.dtype, name=name)
        q = dense("query", self.num_heads)(x)
        k = dense("key", kvh)(x)
        v = dense("value", kvh)(x)
        scale = head_dim ** -0.5
        if self.cache_len > 0:
            b, t = x.shape[0], x.shape[1]
            quant = self.kv_cache_dtype == "int8"
            store = jnp.int8 if quant else k.dtype
            # [B, KVH, L, D]: the per-step attention contracts over L,
            # so L must be the contiguous-row axis — the round-5
            # decode roofline measured the [B, L, KVH, D] layout's
            # strided reads at ~1/4 effective HBM bandwidth (PERF.md
            # §18 addendum)
            shape = (b, kvh, self.cache_len, head_dim)
            ck = self.variable("cache", "cached_key", jnp.zeros, shape,
                               store)
            cv = self.variable("cache", "cached_value", jnp.zeros,
                               shape, store)
            ci = self.variable("cache", "cache_index",
                               lambda: jnp.zeros((), jnp.int32))
            idx = ci.value
            if slot_pos is not None and t != 1:
                raise ValueError(
                    "slot_pos is the continuous-batching T=1 step "
                    f"contract (per-row cache positions); got a T={t} "
                    "chunk — prefill a slot through the scalar-index "
                    "path instead")
            rows = jnp.arange(b)

            def write(cache, chunk):
                # chunk: [B, T, ...] -> cache [B, KVH, L, ...]
                chunk = jnp.swapaxes(chunk, 1, 2)
                if slot_pos is not None:
                    # per-row scatter: row b writes its single token at
                    # its OWN position (OOB positions drop the update;
                    # the ok-poison below keeps that loud)
                    return cache.at[rows, :, slot_pos, :].set(
                        chunk[:, :, 0])
                return lax.dynamic_update_slice(cache, chunk,
                                                (0, 0, idx, 0))

            if quant:
                sshape = (b, kvh, self.cache_len, 1)
                ks = self.variable("cache", "key_scale", jnp.zeros,
                                   sshape, jnp.float32)
                vs = self.variable("cache", "value_scale", jnp.zeros,
                                   sshape, jnp.float32)
                k_w, k_s = _quantize_kv(k)
                v_w, v_s = _quantize_kv(v)
                ks.value = write(ks.value, k_s)
                vs.value = write(vs.value, v_s)
            else:
                k_w, v_w = k, v
            ck.value = write(ck.value, k_w)
            cv.value = write(cv.value, v_w)
            # Overflow is a traced condition (cache_index is dynamic),
            # so it cannot raise; dynamic_update_slice would silently
            # CLAMP the write and corrupt the cache.  Poison the
            # output with NaN instead — loud under jit, and it
            # propagates to any downstream logit/metric.
            if slot_pos is not None:
                ok = slot_pos + t <= self.cache_len        # [B]
            else:
                ci.value = idx + t
                ok = idx + t <= self.cache_len
            if t > 1 and self.attn_fn is not None:
                # Prefill through the block-attention kernel: causal
                # attention WITHIN the chunk, on the raw (pre-
                # quantization) projections.  Exact iff the cache was
                # empty (idx == 0) — which generate()'s prompt pass
                # guarantees; a mid-stream multi-token chunk needs
                # cross-chunk attention, so poison that loud too.
                kf, vf = k, v
                if group > 1:
                    kf = jnp.repeat(kf, group, axis=2)
                    vf = jnp.repeat(vf, group, axis=2)
                out = self.attn_fn(q, kf, vf, scale=scale)
                ok = jnp.logical_and(ok, idx == 0)
            else:
                # q rows sit at global positions idx..idx+t-1; causal
                # mask over the full cache (future slots are zeros AND
                # masked).  The grouped einsum attends each query-head
                # group to its shared K/V head without materializing a
                # repeated cache; the cache's [B, KVH, L, D] layout
                # keeps the L contraction contiguous.  For the int8
                # cache the per-row scales FACTOR OUT of both
                # contractions (they are constant over the contracted
                # d axis / ride the k axis), so the quantized cache
                # feeds the einsum through a fusable cast — never a
                # materialized dequantized copy (the round-5 measured
                # pitfall: dequantize-then-einsum was SLOWER than the
                # bf16 cache, PERF.md §18 addendum).
                keys, vals = ck.value, cv.value
                if quant:
                    keys = keys.astype(q.dtype)
                    vals = vals.astype(q.dtype)
                if slot_pos is not None:
                    q_pos = slot_pos[:, None]               # [B, 1]
                else:
                    q_pos = (idx + jnp.arange(t))[None, :]  # [1, t]
                k_pos = jnp.arange(self.cache_len)
                # [B|1, t, L]: per-row causal horizon in slot mode
                mask = k_pos[None, None, :] <= q_pos[:, :, None]
                qg = q.reshape(b, t, kvh, group, head_dim)
                logits = jnp.einsum("bqhgd,bhkd->bhgqk", qg, keys) \
                    * scale
                if quant:
                    # ks: [B, KVH, L, 1] -> broadcast over (g, q)
                    logits = logits * ks.value[:, :, None, None, :, 0]
                logits = jnp.where(mask[:, None, None], logits,
                                   -1e30)
                probs = nn.softmax(logits.astype(jnp.float32),
                                   axis=-1).astype(q.dtype)
                if quant:
                    probs = (probs.astype(jnp.float32)
                             * vs.value[:, :, None, None, :, 0]
                             ).astype(q.dtype)
                out = jnp.einsum("bhgqk,bhkd->bqhgd", probs, vals)
                out = out.reshape(b, t, self.num_heads, head_dim)
            if jnp.ndim(ok):          # slot mode: per-row poison only
                ok = ok[:, None, None, None]
            out = jnp.where(ok, out, jnp.nan)
        else:
            attn = self.attn_fn or dense_causal_attention
            if group > 1:
                # attention fns expect matched head counts; GQA's win
                # is the serving-time cache, so training repeats K/V
                k = jnp.repeat(k, group, axis=2)
                v = jnp.repeat(v, group, axis=2)
            out = attn(q, k, v, scale=scale)
        return nn.DenseGeneral(d_model, axis=(-2, -1), dtype=self.dtype,
                               name="out")(out)


class MoEFFN(nn.Module):
    """Mixture-of-experts FFN in the dense einsum (GShard/Mesh-TF)
    form: every expert-dim op is a batched matmul over ``E``, so
    sharding the parameters' leading expert axis (see
    ``parallel.tensor_parallel.TRANSFORMER_TP_RULES``) makes GSPMD
    derive the expert-parallel communication — no ``shard_map``
    needed, and the same module runs replicated on one device.

    Routing reuses ``parallel.moe._routing`` (top-k, capacity
    bucketing, f32 bookkeeping).  The load-balancing auxiliary loss is
    sown into the ``"losses"`` collection, which
    ``workers.make_train_step`` adds to the objective."""

    num_experts: int
    mlp_ratio: int
    dtype: jnp.dtype
    capacity_factor: float = 1.25
    top_k: int = 1
    aux_loss_weight: float = 0.01

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        h = d * self.mlp_ratio
        e = self.num_experts
        if not 1 <= self.top_k <= e:
            raise ValueError(
                f"expert_top_k={self.top_k} out of range [1, {e}]")
        tokens = x.reshape(b * t, d)
        capacity = expert_capacity(b * t, e, self.capacity_factor,
                                   self.top_k)
        router = self.param(
            "router", nn.initializers.normal(d ** -0.5), (d, e))
        w_in = self.param(
            "w_in", nn.initializers.normal(d ** -0.5), (e, d, h))
        b_in = self.param("b_in", nn.initializers.zeros, (e, h))
        w_out = self.param(
            "w_out", nn.initializers.normal(h ** -0.5), (e, h, d))
        b_out = self.param("b_out", nn.initializers.zeros, (e, d))

        dispatch, combine, aux = routing(
            tokens.astype(self.dtype), router, e, capacity, self.top_k)
        expert_in = jnp.einsum("tec,td->ecd", dispatch,
                               tokens.astype(self.dtype))
        hidden = nn.gelu(
            jnp.einsum("ecd,edh->ech", expert_in,
                       w_in.astype(self.dtype))
            + b_in.astype(self.dtype)[:, None])
        out = (jnp.einsum("ech,ehd->ecd", hidden,
                          w_out.astype(self.dtype))
               + b_out.astype(self.dtype)[:, None])
        y = jnp.einsum("tec,ecd->td", combine, out)
        self.sow("losses", "moe_load_balance",
                 self.aux_loss_weight * aux.load_balance_loss)
        return y.reshape(b, t, d)


class Block(nn.Module):
    num_heads: int
    mlp_ratio: int
    dtype: jnp.dtype
    attn_fn: Optional[AttnFn] = None
    num_experts: int = 0  # 0 = dense MLP; >0 = MoE FFN
    expert_capacity_factor: float = 1.25
    expert_top_k: int = 1
    cache_len: int = 0  # >0 = autoregressive decode (KV cache)
    num_kv_heads: Optional[int] = None
    kv_cache_dtype: Optional[str] = None

    @nn.compact
    def __call__(self, x, slot_pos=None):
        d_model = x.shape[-1]
        y = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + SelfAttention(self.num_heads, self.dtype, self.attn_fn,
                              cache_len=self.cache_len,
                              num_kv_heads=self.num_kv_heads,
                              kv_cache_dtype=self.kv_cache_dtype)(
                                  y, slot_pos)
        y = nn.LayerNorm(dtype=self.dtype)(x)
        if self.num_experts > 0:
            y = MoEFFN(self.num_experts, self.mlp_ratio, self.dtype,
                       self.expert_capacity_factor, self.expert_top_k,
                       name="moe")(y)
        else:
            y = nn.Dense(d_model * self.mlp_ratio, dtype=self.dtype)(y)
            y = nn.gelu(y)
            y = nn.Dense(d_model, dtype=self.dtype)(y)
        return x + y


class _BlockScanBody(nn.Module):
    """``nn.scan``-compatible wrapper: ``(carry, _) -> (carry, None)``
    around one ``Block`` so the layer stack's parameters materialize as
    one stacked pytree (leading axis = layers) — the homogeneous form
    pipeline parallelism slices per stage (``parallel.pipeline``)."""

    num_heads: int
    mlp_ratio: int
    dtype: Any = jnp.bfloat16
    num_kv_heads: Optional[int] = None

    @nn.compact
    def __call__(self, carry, _):
        return Block(self.num_heads, self.mlp_ratio, self.dtype,
                     num_kv_heads=self.num_kv_heads,
                     name="layer")(carry), None


@register_model("transformer_lm")
class TransformerLM(nn.Module):
    """``seq_axis``: name of a mesh axis the *time* dimension is sharded
    over.  When set, the module is an SPMD program to be applied inside
    ``jax.shard_map`` (see ``parallel.ring_attention.sequence_sharded_
    apply``): positions are offset by the device's ring index and
    attention defaults to ``ring_attention`` over that axis.  Every other
    sublayer is position-wise, so nothing else changes — the same
    parameters run dense or sequence-parallel."""

    vocab_size: int = 32000
    num_layers: int = 4
    d_model: int = 256
    num_heads: int = 4
    mlp_ratio: int = 4
    max_len: int = 2048
    dtype: str = "bfloat16"
    attn_fn: Optional[AttnFn] = None  # None -> auto / dense / ring
    seq_axis: Optional[str] = None
    # within-device q block length for ring/blockwise attention (None =
    # full block); see parallel.ring_attention.ring_attention(q_chunk=)
    attn_q_chunk: Optional[int] = None
    #: device-local attention spelling.  The default ``"auto"`` applies
    #: the measured per-shape recipe (PERF.md §17): ``"flash"`` at
    #: T >= 2048 (on TPU, where the Mosaic kernels run; elsewhere the
    #: blockwise path substitutes), ``"blockwise"`` at T=1024-class
    #: shapes, ``"dense"`` below — the regime boundary tracks the
    #: quadratic term's share of the step (§17 addendum), so small B·H
    #: long-T shapes sit exactly where the measured rows put them.
    #: T must be a multiple of 128 for the blocked spellings (else
    #: auto falls back to dense).  Explicit values force one spelling;
    #: the ``flash_attn``/``blockwise_attn`` booleans and ``attn_fn``
    #: (strongest) override this field.  Under ``scan_blocks`` /
    #: ``decode`` T=1 steps, auto resolves to dense.
    attn: str = "auto"
    #: single-device flash-style attention (JSON-able spelling of
    #: attn_fn=blockwise_attn_fn(...)): online-softmax q-chunking, the
    #: [T, T] logits never materialize — the long-T device-local path
    #: (PERF.md §13).  q chunk length = attn_q_chunk (default 128, the
    #: measured v5e optimum).
    blockwise_attn: bool = False
    #: hand-written Pallas flash-attention kernels (JSON-able spelling
    #: of attn_fn=ops.attention.flash_attn_fn()): same online-
    #: softmax algorithm as blockwise_attn but as one Mosaic kernel per
    #: pass — accumulators VMEM-resident, k/v blocks pipelined, causal
    #: blocks grid-skipped.  The fastest long-T path on the v5e
    #: (PERF.md §17).  Always uses the kernel's measured block defaults
    #: (512/1024, auto-clamped to divisors of T); attn_q_chunk applies
    #: to the blockwise/ring paths only — its tuned values (~128) sit
    #: in the kernel's WORST regime, so it is deliberately not reused
    #: here.  To tune blocks, pass attn_fn=flash_attn_fn(block_q=...).
    flash_attn: bool = False
    #: GQA (grouped-query attention): number of K/V heads; must divide
    #: num_heads.  None = one K/V head per query head (MHA).  Shrinks
    #: the decode-time KV cache — the dominant per-token HBM read at
    #: batch (PERF.md §18) — by num_heads/num_kv_heads; training-path
    #: kernels see K/V repeated to the full head count.
    num_kv_heads: Optional[int] = None
    #: storage dtype of the serving KV cache (decode=True only).
    #: None = the activation dtype; "int8" = symmetric per-position-
    #: per-head quantization (f32 scales) — halves the bf16 cache's
    #: per-token HBM traffic at an error bounded by amax/254 per
    #: element (tolerance-tested in tests/test_generate.py).
    kv_cache_dtype: Optional[str] = None
    # >0 replaces every block's MLP with a mixture-of-experts FFN
    # (dense einsum form — shard the expert axes via the TP rules for
    # expert parallelism); the load-balance aux loss rides the
    # "losses" collection into the training objective
    num_experts: int = 0
    expert_capacity_factor: float = 1.25
    expert_top_k: int = 1
    #: stack the layer parameters [num_layers, ...] via nn.scan (same
    #: math per layer; different param-tree layout).  Required by the
    #: pipeline-parallel trainer path, which shards the layer stack's
    #: leading axis across stages.  Incompatible with attn_fn/seq_axis/
    #: MoE (those paths keep per-layer modules); attn="auto" resolves
    #: to dense under scan.
    scan_blocks: bool = False
    #: rematerialize each Block in the backward pass
    #: (``jax.checkpoint``): activations inside a block are recomputed
    #: from its input instead of stored, trading ~1 extra forward of
    #: FLOPs for O(layers) less activation memory — the lever that
    #: fits batches past the HBM envelope at long T (measured: b8 at
    #: T=8192 OOMs by 2.4 GB without it; PERF.md §19).
    remat_blocks: bool = False
    #: autoregressive decode mode for serving (``models.generate``):
    #: every attention layer keeps a ``max_len``-slot KV cache in the
    #: ``"cache"`` variable collection and calls append to it, so the
    #: prompt is processed once and each new token costs one T=1 step.
    #: Apply with ``mutable=["cache"]`` and thread the returned cache.
    #: Returns logits for the LAST input position only ([B, 1, V]) —
    #: the one generation consumes; full-vocab f32 logits over a whole
    #: prompt would dominate prefill activations for nothing (pass
    #: ``last_index`` to select a different single position — the
    #: right-padded-prompt contract of ``serving.DecodeEngine``).  Same
    #: parameters as the training-mode model (``decode`` changes
    #: execution, not the param tree).  The attention spelling
    #: (attn/flash_attn/blockwise_attn/attn_fn) selects the PREFILL
    #: attention: a multi-token chunk at cache position 0 runs through
    #: that kernel instead of a dense read of the whole cache (the
    #: round-4 gap: a T=4096 prompt paid O(T·max_len) dense prefill
    #: while training the same shape got the flash kernels).  T=1
    #: steps always use the cached dense row.  Incompatible with
    #: seq_axis / scan_blocks.
    decode: bool = False
    #: size of the per-layer KV cache in decode mode (default:
    #: ``max_len``).  PERF.md §18 proved every T=1 step pays for the
    #: STATIC cache envelope, not the live prefix — so a serving slot
    #: pool whose requests fit 512 positions should carry a 512-slot
    #: cache even when the model's position table (``max_len``) is
    #: 2048.  Must be <= max_len (positions are still embedded from
    #: the full table, so the params are unchanged).  Decode-only.
    cache_envelope: Optional[int] = None

    def _local_attn_fn(self, t: int,
                       platform: Optional[str] = None) -> Optional[AttnFn]:
        """Resolve the device-local attention spelling for sequence
        length ``t`` (None = dense).  Precedence: attn_fn > the
        boolean spellings > ``attn`` (whose "auto" applies the
        measured PERF.md §17 recipe).

        ``platform`` is where the computation runs — taken from the
        devices the input is committed to when that is knowable
        (eager calls on placed arrays), else the repo-wide
        ``jax.devices()[0]`` convention: under ``jit`` the input is a
        tracer with no committed devices, and the default backend's
        first device is where an unpinned trace executes.  A
        CPU-forced debugging run on a TPU host therefore resolves
        "auto" against CPU when the arrays are committed there; pin
        ``attn=`` explicitly to override either way."""
        if self.attn_fn is not None:
            return self.attn_fn
        spelling = self.attn
        if self.flash_attn:
            spelling = "flash"
        elif self.blockwise_attn:
            spelling = "blockwise"
        if spelling == "auto":
            # measured recipe: flash at T>=2048 (TPU), blockwise at
            # T=1024-class, dense below; blocked spellings need
            # 128-aligned T (Mosaic tiling / chunk divisibility)
            if t < 1024 or t % 128:
                return None
            if platform is None:
                platform = jax.devices()[0].platform
            if t >= 2048 and platform == "tpu":
                spelling = "flash"
            else:
                spelling = "blockwise"
        if spelling == "dense":
            return None
        if spelling == "flash":
            from distkeras_tpu.ops.attention import flash_attn_fn

            return flash_attn_fn()
        from distkeras_tpu.parallel.ring_attention import \
            blockwise_attn_fn

        return blockwise_attn_fn(q_chunk=self.attn_q_chunk or 128)

    @nn.compact
    def __call__(self, tokens, train: bool = False, *,
                 slot_pos=None, last_index=None,
                 logits_all: bool = False):
        import jax.lax as lax

        dtype = jnp.dtype(self.dtype)
        tokens = tokens.astype(jnp.int32)
        t = tokens.shape[1]
        platform = _committed_platform(tokens)
        if self.attn not in _ATTN_CHOICES:
            raise ValueError(
                f"attn={self.attn!r} not one of {_ATTN_CHOICES}")
        if self.kv_cache_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_cache_dtype={self.kv_cache_dtype!r} must be None "
                "(activation dtype) or 'int8'")
        attn_fn = self.attn_fn
        cache_len = 0
        if self.decode:
            if self.seq_axis is not None or self.scan_blocks:
                raise ValueError(
                    "decode=True is the KV-cache serving path: "
                    "seq_axis/scan_blocks do not apply (the attention "
                    "spellings select the PREFILL kernel; generated "
                    "tokens are cached T=1 steps)")
            if self.num_experts > 0:
                raise ValueError(
                    "decode=True cannot serve MoE models: capacity-"
                    "bucketed routing over a short decode step "
                    "diverges from the full-forward routing the model "
                    "trained with (different tokens overflow and "
                    "drop) — serve MoE via the dense full-forward "
                    "path (predictors) instead")
            cache_len = self.cache_envelope or self.max_len
            if not 0 < cache_len <= self.max_len:
                raise ValueError(
                    f"cache_envelope={self.cache_envelope} outside "
                    f"(0, max_len={self.max_len}]: the envelope is a "
                    "slot-pool cache SIZE; positions still embed from "
                    "the max_len table")
            if t > cache_len:
                raise ValueError(
                    f"decode chunk length {t} exceeds the cache size "
                    f"{cache_len}")
        if self.cache_envelope is not None and not self.decode:
            raise ValueError(
                "cache_envelope sizes the decode-mode KV cache; it "
                "has no meaning without decode=True")
        if (slot_pos is not None or last_index is not None
                or logits_all) and not self.decode:
            raise ValueError(
                "slot_pos/last_index/logits_all are decode-mode "
                "serving contracts (per-slot cache positions / "
                "right-padded prompt logit row / speculative verify); "
                "set decode=True")
        if logits_all and last_index is not None:
            raise ValueError(
                "logits_all returns every position's logits; "
                "last_index selects one — pass at most one of them")
        if slot_pos is not None and t != 1:
            raise ValueError(
                "slot_pos advances every live slot by ONE token; got "
                f"a T={t} chunk — prefill new slots through the "
                "scalar-index path (serving.DecodeEngine does)")
        if self.blockwise_attn and self.flash_attn:
            raise ValueError(
                "blockwise_attn and flash_attn are mutually exclusive "
                "spellings of the device-local flash-style attention "
                "path")
        if self.seq_axis is not None and (
                self.blockwise_attn or self.flash_attn
                or self.attn != "auto"):
            raise ValueError(
                "blockwise_attn/flash_attn/attn are device-local "
                "attention spellings; with seq_axis the attention is "
                "ring attention over the mesh — use attn_q_chunk to "
                "bound its within-device blocks instead")
        if self.seq_axis is not None:
            from distkeras_tpu.parallel.ring_attention import ring_attn_fn

            t_global = t * axis_size(self.seq_axis)
            positions = (lax.axis_index(self.seq_axis) * t
                         + jnp.arange(t))[None, :]
            if attn_fn is None:
                attn_fn = ring_attn_fn(self.seq_axis,
                                       q_chunk=self.attn_q_chunk)
        elif self.decode:
            t_global = t  # chunk length; prefix bound checked above
            pos_var = self.variable("cache", "pos_index",
                                    lambda: jnp.zeros((), jnp.int32))
            if slot_pos is not None:
                # continuous batching: each slot is at its OWN
                # position; the engine owns slot state, so the scalar
                # pos_index is left untouched
                positions = slot_pos[:, None]
            else:
                positions = (pos_var.value + jnp.arange(t))[None, :]
                pos_var.value = pos_var.value + t
            # multi-token chunks (prefill) run the resolved kernel
            # inside SelfAttention; T=1 steps use the cached row.
            # Serving prompts have ARBITRARY lengths and the blocked
            # kernels reject unaligned ones (q_chunk divisibility /
            # Mosaic tiling), so every spelling falls back to the
            # dense cache read off the 128-aligned grid — a slower
            # prefill must never be a serving error.  A custom
            # attn_fn is honored as given (the caller owns its
            # shape contract; generate() clears it).
            if t > 1 and (self.attn_fn is not None or t % 128 == 0):
                attn_fn = self._local_attn_fn(t, platform)
            else:
                attn_fn = None
        else:
            t_global = t
            positions = jnp.arange(t)[None, :]
            if not self.scan_blocks:
                attn_fn = self._local_attn_fn(t, platform)
        if t_global > self.max_len:
            raise ValueError(
                f"sequence length {t_global} exceeds "
                f"max_len={self.max_len}")
        x = nn.Embed(self.vocab_size, self.d_model, dtype=dtype)(tokens)
        pos = nn.Embed(self.max_len, self.d_model, dtype=dtype,
                       name="pos_embed")(positions)
        x = x + pos
        if self.scan_blocks:
            if (self.num_experts > 0 or self.attn_fn is not None
                    or self.seq_axis is not None or self.blockwise_attn
                    or self.flash_attn or self.remat_blocks
                    or self.attn not in ("auto", "dense")):
                raise ValueError(
                    "scan_blocks=True supports the dense-attention, "
                    "dense-FFN transformer only (MoE / custom attn / "
                    "seq_axis / remat_blocks keep per-layer modules)")
            scanned = nn.scan(
                _BlockScanBody,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=self.num_layers)(
                    self.num_heads, self.mlp_ratio, dtype,
                    num_kv_heads=self.num_kv_heads,
                    name="blocks")
            x, _ = scanned(x, None)
        else:
            block_cls = nn.remat(Block) if self.remat_blocks else Block
            for i in range(self.num_layers):
                # explicit names keep the param tree identical whether
                # or not remat wraps the block (nn.remat's auto-name
                # would be CheckpointBlock_i) — remat_blocks can be
                # toggled on existing checkpoints
                x = block_cls(self.num_heads, self.mlp_ratio, dtype,
                              attn_fn, self.num_experts,
                              self.expert_capacity_factor,
                              self.expert_top_k,
                              cache_len=cache_len,
                              num_kv_heads=self.num_kv_heads,
                              kv_cache_dtype=self.kv_cache_dtype,
                              name=f"Block_{i}")(x, slot_pos)
        if self.decode:
            # serving returns next-token logits only: the f32
            # full-vocab lm_head over every prompt position would be
            # the prefill's dominant activation for nothing (only the
            # last row seeds generation).  last_index selects a
            # different single row — the right-padded-prompt prefill
            # contract (pad rows trail the real last token, so -1
            # would read a pad position's logits).
            if last_index is not None:
                x = lax.dynamic_slice_in_dim(x, last_index, 1, 1)
            elif not logits_all:
                x = x[:, -1:]
            # logits_all: the speculative-verify contract — every
            # position's logits survive to the lm_head (T is the
            # small proposal window k+1 there, so the full-vocab f32
            # head stays cheap)
        x = nn.LayerNorm(dtype=dtype)(x)
        return nn.Dense(self.vocab_size, dtype=jnp.float32,
                        name="lm_head")(x)

"""Wide & Deep for the Criteo baseline config (BASELINE.md: "Criteo
Wide&Deep — DataFrame ETL -> TPU train/predict").

Input is a single float matrix ``[B, num_dense + num_categorical]`` as
produced by the ETL transformer pipeline (``distkeras_tpu.data``): the first
``num_dense`` columns are normalized dense features, the rest are integer
category ids (already hash-bucketed by ``HashBucketTransformer``).  One
matrix in, logits out, so the trainer/predictor surface is identical to the
other model families.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.core import register_model


@register_model("wide_deep")
class WideAndDeep(nn.Module):
    num_dense: int = 13
    num_categorical: int = 26
    vocab_size: int = 10000       # per-feature hash bucket count
    embed_dim: int = 16
    deep: Sequence[int] = (256, 128, 64)
    num_classes: int = 2
    dtype: str = "float32"

    @nn.compact
    def __call__(self, x, train: bool = False):
        dtype = jnp.dtype(self.dtype)
        dense = x[:, :self.num_dense].astype(dtype)
        cats = x[:, self.num_dense:].astype(jnp.int32)  # [B, C]
        cats = jnp.clip(cats, 0, self.vocab_size - 1)

        # Wide arm: linear over one-hot categoricals == per-feature scalar
        # embedding lookup (avoids materializing the one-hot).
        wide_tab = nn.Embed(self.num_categorical * self.vocab_size,
                            self.num_classes, dtype=dtype,
                            name="wide_table")
        offsets = jnp.arange(self.num_categorical) * self.vocab_size
        wide = jnp.sum(wide_tab(cats + offsets[None, :]), axis=1)
        wide = wide + nn.Dense(self.num_classes, dtype=dtype,
                               name="wide_dense")(dense)

        # Deep arm: concatenated embeddings + dense features -> MLP.
        deep_tab = nn.Embed(self.num_categorical * self.vocab_size,
                            self.embed_dim, dtype=dtype, name="deep_table")
        emb = deep_tab(cats + offsets[None, :])  # [B, C, E]
        h = jnp.concatenate(
            [emb.reshape((x.shape[0], -1)), dense], axis=-1)
        for width in self.deep:
            h = nn.Dense(width, dtype=dtype)(h)
            h = nn.relu(h)
        deep = nn.Dense(self.num_classes, dtype=jnp.float32)(h)
        return wide.astype(jnp.float32) + deep

"""ConvNet for the CIFAR-10 baseline config (BASELINE.md: "CIFAR-10 ConvNet
with ADAG").  The reference's convnet examples use small Keras
Conv2D/MaxPool stacks; this is a configurable flax equivalent whose conv
widths stay MXU-friendly (multiples of 128 at the widest)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.core import register_model


@register_model("convnet")
class ConvNet(nn.Module):
    """Conv blocks (conv-relu-conv-relu-pool) + dense head."""

    num_classes: int = 10
    widths: Sequence[int] = (64, 128, 256)
    dense: int = 256
    dropout_rate: float = 0.0
    dtype: str = "float32"

    @nn.compact
    def __call__(self, x, train: bool = False):
        dtype = jnp.dtype(self.dtype)
        x = x.astype(dtype)
        for width in self.widths:
            x = nn.Conv(width, (3, 3), padding="SAME", dtype=dtype)(x)
            x = nn.relu(x)
            x = nn.Conv(width, (3, 3), padding="SAME", dtype=dtype)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.dense, dtype=dtype)(x)
        x = nn.relu(x)
        if self.dropout_rate > 0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)

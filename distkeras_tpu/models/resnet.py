"""ResNet family for the ImageNet baseline config (BASELINE.md: "ResNet-50 /
ImageNet with AEASGD on v4-32 … ≥60% MFU").

TPU-first choices:
  * compute dtype defaults to bfloat16 (MXU-native), params stay float32;
  * norm defaults to GroupNorm — stateless, so parameters are a pure pytree
    and every PS update rule applies unchanged.  BatchNorm is available
    (``norm='batch'``) and its running stats ride the ``batch_stats``
    collection, which trainers keep worker-local (SURVEY.md §7 L1).
  * NHWC layout throughout (XLA:TPU's preferred conv layout).
"""

from __future__ import annotations

import functools
import math
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.core import register_model

ModuleDef = Any


class AdaptiveGroupNorm(nn.Module):
    """GroupNorm with group count adapted to the channel width (gcd with 32)
    so narrow stems/test widths still divide evenly.

    ``impl`` selects the lowering: ``'flax'`` (default) is
    ``nn.GroupNorm``, which XLA fuses well (PERF.md §4 measured it
    fastest end-to-end); ``'pallas'`` is the hand-written single-pass
    kernel in ``ops/pallas_kernels.py`` — measured SLOWER on v5e (the
    opaque custom call breaks XLA's conv↔norm fusion and half-fills the
    lanes at the 64-channel stem), kept as a tested opt-in for future
    tuning.  ``relu=True`` fuses the following activation.  NOTE: the
    two impls produce different parameter-tree nesting; pick one per
    model lifetime.
    """

    dtype: Any = jnp.float32
    scale_init: Any = nn.initializers.ones_init()
    relu: bool = False
    impl: str = "flax"  # 'flax' | 'pallas'

    @nn.compact
    def __call__(self, x):
        channels = x.shape[-1]
        groups = math.gcd(32, channels)
        if self.impl == "pallas":
            from distkeras_tpu.ops.pallas_kernels import fused_group_norm

            gamma = self.param("scale", self.scale_init, (channels,),
                               jnp.float32)
            beta = self.param("bias", nn.initializers.zeros_init(),
                              (channels,), jnp.float32)
            return fused_group_norm(x, gamma, beta, groups=groups,
                                    relu=self.relu)
        y = nn.GroupNorm(num_groups=groups, dtype=self.dtype,
                         scale_init=self.scale_init)(x)
        return nn.relu(y) if self.relu else y


class _Identity(nn.Module):
    """No-op norm (perf ablation / fully-stateless configs)."""

    scale_init: Any = None
    relu: bool = False

    @nn.compact
    def __call__(self, x):
        return nn.relu(x) if self.relu else x


class _BatchNormRelu(nn.Module):
    """BatchNorm with the same (scale_init, relu) factory surface as
    AdaptiveGroupNorm so block code is norm-flavor-agnostic.

    NOTE: wrapping nests the variable paths one level deeper than a bare
    ``nn.BatchNorm`` (``.../_BatchNormRelu_0/BatchNorm_0/...``) —
    variables exported from a pre-wrapper ``norm='batch'`` model do not
    load into post-wrapper models."""

    dtype: Any
    use_running_average: bool
    scale_init: Any = nn.initializers.ones_init()
    relu: bool = False

    @nn.compact
    def __call__(self, x):
        y = nn.BatchNorm(use_running_average=self.use_running_average,
                         momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                         scale_init=self.scale_init)(x)
        return nn.relu(y) if self.relu else y


def _norm(norm: str, dtype, train: bool) -> Callable:
    if norm == "batch":
        return functools.partial(_BatchNormRelu,
                                 dtype=dtype,
                                 use_running_average=not train)
    if norm == "group":
        return functools.partial(AdaptiveGroupNorm, dtype=dtype)
    if norm == "group_pallas":
        return functools.partial(AdaptiveGroupNorm, dtype=dtype,
                                 impl="pallas")
    if norm == "none":
        return _Identity
    raise ValueError(f"unknown norm {norm!r}")


def s2d_input(x: jnp.ndarray) -> jnp.ndarray:
    """Space-to-depth, block 2: ``[N,H,W,C] -> [N,H/2,W/2,4C]`` with
    channel layout ``(s, t, c)`` (row offset slowest)."""
    n, h, w, c = x.shape
    x = x.reshape(n, h // 2, 2, w // 2, 2, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2,
                                                 4 * c)


def s2d_stem_kernel(w7: jnp.ndarray) -> jnp.ndarray:
    """Transform a ``[7,7,C,O]`` stride-2 stem kernel into the
    equivalent ``[4,4,4C,O]`` stride-1 kernel over an ``s2d_input``
    image: zero-pad to 8x8 at the front, then fold each 2x2 spatial
    phase into the channel axis (same ``(s, t, c)`` layout)."""
    w8 = jnp.pad(w7, ((1, 0), (1, 0), (0, 0), (0, 0)))
    c_in, c_out = w7.shape[2], w7.shape[3]
    w = w8.reshape(4, 2, 4, 2, c_in, c_out)
    return w.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c_in, c_out)


class BasicBlock(nn.Module):
    filters: int
    strides: tuple[int, int]
    norm: ModuleDef
    dtype: Any

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        y = self.norm(relu=True)(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME",
                    use_bias=False, dtype=self.dtype)(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class FusedBottleneckBlock(nn.Module):
    """Bottleneck block lowered through the block-granular Pallas
    kernels (``ops/fused_block.py``): the first 1x1 conv + GN + ReLU is
    one kernel; the 3x3 conv's GN, second 1x1 conv, its GN, residual
    add, and final ReLU are a second kernel; the downsample projection
    (conv1x1 + GN) is a third.  Only the 3x3 conv itself stays with
    XLA.  Same math as ``BottleneckBlock`` with ``norm='group'`` —
    parity-tested in ``tests/test_fused_block.py`` — but each
    activation tensor crosses HBM once per direction instead of
    3-4 times (PERF.md §11).

    Parameter tree is flat (``conv1``/``gn1_scale``/...), not the
    nested flax-module layout — fused and unfused checkpoints are not
    interchangeable.
    """

    filters: int
    strides: tuple[int, int]
    dtype: Any
    fuse_op1: bool = True  # False: op1/downsample stay XLA, tail fused

    @nn.compact
    def __call__(self, x):
        w = self.filters
        cin = x.shape[-1]
        cout = 4 * w
        g_mid = math.gcd(32, w)
        g_out = math.gcd(32, cout)
        from distkeras_tpu.ops.fused_block import (fused_bottleneck_tail,
                                                   fused_conv1x1_gn)

        init = nn.initializers.lecun_normal()
        ones = nn.initializers.ones_init()
        zeros = nn.initializers.zeros_init()
        if self.fuse_op1:
            k1 = self.param("conv1", init, (cin, w), jnp.float32)
            y = fused_conv1x1_gn(
                x, k1.astype(self.dtype),
                self.param("gn1_scale", ones, (w,), jnp.float32),
                self.param("gn1_bias", zeros, (w,), jnp.float32),
                groups=g_mid, relu=True)
        else:
            y = nn.Conv(w, (1, 1), use_bias=False, dtype=self.dtype,
                        name="conv1u")(x)
            y = AdaptiveGroupNorm(dtype=self.dtype, relu=True,
                                  name="gn1u")(y)
        y = nn.Conv(w, (3, 3), self.strides, padding="SAME",
                    use_bias=False, dtype=self.dtype, name="conv2")(y)
        if cin != cout or self.strides != (1, 1):
            if self.fuse_op1:
                xs = x[:, ::self.strides[0], ::self.strides[1], :]
                kd = self.param("convd", init, (cin, cout), jnp.float32)
                residual = fused_conv1x1_gn(
                    xs, kd.astype(self.dtype),
                    self.param("gnd_scale", ones, (cout,), jnp.float32),
                    self.param("gnd_bias", zeros, (cout,), jnp.float32),
                    groups=g_out, relu=False)
            else:
                residual = nn.Conv(cout, (1, 1), self.strides,
                                   use_bias=False, dtype=self.dtype,
                                   name="convdu")(x)
                residual = AdaptiveGroupNorm(dtype=self.dtype,
                                             name="gndu")(residual)
        else:
            residual = x
        k3 = self.param("conv3", init, (w, cout), jnp.float32)
        # zero-init the last norm's scale so blocks start as identity
        return fused_bottleneck_tail(
            y, k3.astype(self.dtype),
            self.param("gn2_scale", ones, (w,), jnp.float32),
            self.param("gn2_bias", zeros, (w,), jnp.float32),
            self.param("gn3_scale", zeros, (cout,), jnp.float32),
            self.param("gn3_bias", zeros, (cout,), jnp.float32),
            residual, groups2=g_mid, groups3=g_out)


class BottleneckBlock(nn.Module):
    filters: int
    strides: tuple[int, int]
    norm: ModuleDef
    dtype: Any

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype)(x)
        y = self.norm(relu=True)(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=False, dtype=self.dtype)(y)
        y = self.norm(relu=True)(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        # zero-init the last norm's scale so blocks start as identity
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters * 4, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


@register_model("resnet")
class ResNet(nn.Module):
    """Configurable ResNet; ``stage_sizes=(3,4,6,3), bottleneck=True`` is
    ResNet-50."""

    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    bottleneck: bool = True
    width: int = 64
    norm: str = "group"
    dtype: str = "bfloat16"
    stem: str = "conv"  # 'conv' | 'space_to_depth'
    #: 'none' | 'pallas_block' (op1+tail+downsample kernels) |
    #: 'pallas_tail' (tail kernel only; op1/downsample stay XLA)
    fusion: str = "none"
    #: stages (0-based) the fusion applies to; None = all stages.
    fusion_stages: Sequence[int] | None = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        dtype = jnp.dtype(self.dtype)
        norm = _norm(self.norm, dtype, train)
        if self.fusion in ("pallas_block", "pallas_tail"):
            if not self.bottleneck or self.norm != "group":
                raise ValueError(
                    f"fusion={self.fusion!r} implements the GroupNorm "
                    f"bottleneck block only (norm='group', "
                    f"bottleneck=True)")
        elif self.fusion != "none":
            raise ValueError(f"unknown fusion {self.fusion!r}")
        block = BottleneckBlock if self.bottleneck else BasicBlock

        x = x.astype(dtype)
        if self.stem == "space_to_depth":
            if x.shape[1] % 2 or x.shape[2] % 2:
                raise ValueError(
                    f"stem='space_to_depth' needs even input height/"
                    f"width (the 2x2 phase fold), got {x.shape[1:3]}; "
                    f"use stem='conv' for odd sizes")
            # Exact re-layout of the 7x7/s2 stem (see s2d_stem_kernel):
            # the 3-channel 7x7 conv half-starves the MXU's input lanes;
            # folding the 2x2 spatial phases into channels gives an
            # MXU-friendlier 12-channel 4x4/s1 conv with identical math.
            x = s2d_input(x)
            x = nn.Conv(self.width, (4, 4), padding=[(2, 1), (2, 1)],
                        use_bias=False, dtype=dtype)(x)
        elif self.stem == "conv":
            x = nn.Conv(self.width, (7, 7), (2, 2),
                        padding=[(3, 3), (3, 3)],
                        use_bias=False, dtype=dtype)(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")
        x = norm(relu=True)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, size in enumerate(self.stage_sizes):
            fuse_here = self.fusion != "none" and (
                self.fusion_stages is None
                or stage in self.fusion_stages)
            for i in range(size):
                strides = (2, 2) if stage > 0 and i == 0 else (1, 1)
                if fuse_here:
                    x = FusedBottleneckBlock(
                        filters=self.width * 2 ** stage,
                        strides=strides, dtype=dtype,
                        fuse_op1=self.fusion == "pallas_block")(x)
                else:
                    x = block(filters=self.width * 2 ** stage,
                              strides=strides, norm=norm, dtype=dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def ResNet18(**kw) -> ResNet:
    kw.setdefault("stage_sizes", (2, 2, 2, 2))
    kw.setdefault("bottleneck", False)
    return ResNet(**kw)


def ResNet50(**kw) -> ResNet:
    kw.setdefault("stage_sizes", (3, 4, 6, 3))
    kw.setdefault("bottleneck", True)
    return ResNet(**kw)

"""ResNet family for the ImageNet baseline config (BASELINE.md: "ResNet-50 /
ImageNet with AEASGD on v4-32 … ≥60% MFU").

TPU-first choices:
  * compute dtype defaults to bfloat16 (MXU-native), params stay float32;
  * norm defaults to GroupNorm — stateless, so parameters are a pure pytree
    and every PS update rule applies unchanged.  BatchNorm is available
    (``norm='batch'``) and its running stats ride the ``batch_stats``
    collection, which trainers keep worker-local (SURVEY.md §7 L1).
  * NHWC layout throughout (XLA:TPU's preferred conv layout).
"""

from __future__ import annotations

import functools
import math
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.core import register_model

ModuleDef = Any


class AdaptiveGroupNorm(nn.Module):
    """GroupNorm with group count adapted to the channel width (gcd with 32)
    so narrow stems/test widths still divide evenly.

    ``impl`` selects the lowering: ``'flax'`` (default) is
    ``nn.GroupNorm``, which XLA fuses well (PERF.md §4 measured it
    fastest end-to-end); ``'pallas'`` is the hand-written single-pass
    kernel in ``ops/pallas_kernels.py`` — measured SLOWER on v5e (the
    opaque custom call breaks XLA's conv↔norm fusion and half-fills the
    lanes at the 64-channel stem), kept as a tested opt-in for future
    tuning.  ``relu=True`` fuses the following activation.  NOTE: the
    two impls produce different parameter-tree nesting; pick one per
    model lifetime.
    """

    dtype: Any = jnp.float32
    scale_init: Any = nn.initializers.ones_init()
    relu: bool = False
    impl: str = "flax"  # 'flax' | 'pallas'

    @nn.compact
    def __call__(self, x):
        channels = x.shape[-1]
        groups = math.gcd(32, channels)
        if self.impl == "pallas":
            from distkeras_tpu.ops.pallas_kernels import fused_group_norm

            gamma = self.param("scale", self.scale_init, (channels,),
                               jnp.float32)
            beta = self.param("bias", nn.initializers.zeros_init(),
                              (channels,), jnp.float32)
            return fused_group_norm(x, gamma, beta, groups=groups,
                                    relu=self.relu)
        y = nn.GroupNorm(num_groups=groups, dtype=self.dtype,
                         scale_init=self.scale_init)(x)
        return nn.relu(y) if self.relu else y


class _Identity(nn.Module):
    """No-op norm (perf ablation / fully-stateless configs)."""

    scale_init: Any = None
    relu: bool = False

    @nn.compact
    def __call__(self, x):
        return nn.relu(x) if self.relu else x


class _BatchNormRelu(nn.Module):
    """BatchNorm with the same (scale_init, relu) factory surface as
    AdaptiveGroupNorm so block code is norm-flavor-agnostic.

    NOTE: wrapping nests the variable paths one level deeper than a bare
    ``nn.BatchNorm`` (``.../_BatchNormRelu_0/BatchNorm_0/...``) —
    variables exported from a pre-wrapper ``norm='batch'`` model do not
    load into post-wrapper models."""

    dtype: Any
    use_running_average: bool
    scale_init: Any = nn.initializers.ones_init()
    relu: bool = False

    @nn.compact
    def __call__(self, x):
        y = nn.BatchNorm(use_running_average=self.use_running_average,
                         momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                         scale_init=self.scale_init)(x)
        return nn.relu(y) if self.relu else y


def _norm(norm: str, dtype, train: bool) -> Callable:
    if norm == "batch":
        return functools.partial(_BatchNormRelu,
                                 dtype=dtype,
                                 use_running_average=not train)
    if norm == "group":
        return functools.partial(AdaptiveGroupNorm, dtype=dtype)
    if norm == "group_pallas":
        return functools.partial(AdaptiveGroupNorm, dtype=dtype,
                                 impl="pallas")
    if norm == "none":
        return _Identity
    raise ValueError(f"unknown norm {norm!r}")


class BasicBlock(nn.Module):
    filters: int
    strides: tuple[int, int]
    norm: ModuleDef
    dtype: Any

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        y = self.norm(relu=True)(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME",
                    use_bias=False, dtype=self.dtype)(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    strides: tuple[int, int]
    norm: ModuleDef
    dtype: Any

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype)(x)
        y = self.norm(relu=True)(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=False, dtype=self.dtype)(y)
        y = self.norm(relu=True)(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        # zero-init the last norm's scale so blocks start as identity
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters * 4, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


@register_model("resnet")
class ResNet(nn.Module):
    """Configurable ResNet; ``stage_sizes=(3,4,6,3), bottleneck=True`` is
    ResNet-50."""

    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    bottleneck: bool = True
    width: int = 64
    norm: str = "group"
    dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, x, train: bool = False):
        dtype = jnp.dtype(self.dtype)
        norm = _norm(self.norm, dtype, train)
        block = BottleneckBlock if self.bottleneck else BasicBlock

        x = x.astype(dtype)
        x = nn.Conv(self.width, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=dtype)(x)
        x = norm(relu=True)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, size in enumerate(self.stage_sizes):
            for i in range(size):
                strides = (2, 2) if stage > 0 and i == 0 else (1, 1)
                x = block(filters=self.width * 2 ** stage, strides=strides,
                          norm=norm, dtype=dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def ResNet18(**kw) -> ResNet:
    kw.setdefault("stage_sizes", (2, 2, 2, 2))
    kw.setdefault("bottleneck", False)
    return ResNet(**kw)


def ResNet50(**kw) -> ResNet:
    kw.setdefault("stage_sizes", (3, 4, 6, 3))
    kw.setdefault("bottleneck", True)
    return ResNet(**kw)

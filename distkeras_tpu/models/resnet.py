"""ResNet family for the ImageNet baseline config (BASELINE.md: "ResNet-50 /
ImageNet with AEASGD on v4-32 … ≥60% MFU").

TPU-first choices:
  * compute dtype defaults to bfloat16 (MXU-native), params stay float32;
  * norm defaults to GroupNorm — stateless, so parameters are a pure pytree
    and every PS update rule applies unchanged.  BatchNorm is available
    (``norm='batch'``) and its running stats ride the ``batch_stats``
    collection, which trainers keep worker-local (SURVEY.md §7 L1).
  * NHWC layout throughout (XLA:TPU's preferred conv layout).
"""

from __future__ import annotations

import functools
import math
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.models.core import register_model

ModuleDef = Any


class AdaptiveGroupNorm(nn.Module):
    """GroupNorm with group count adapted to the channel width (gcd with 32)
    so narrow stems/test widths still divide evenly."""

    dtype: Any = jnp.float32
    scale_init: Any = nn.initializers.ones_init()

    @nn.compact
    def __call__(self, x):
        groups = math.gcd(32, x.shape[-1])
        return nn.GroupNorm(num_groups=groups, dtype=self.dtype,
                            scale_init=self.scale_init)(x)


class _Identity(nn.Module):
    """No-op norm (perf ablation / fully-stateless configs)."""

    scale_init: Any = None

    @nn.compact
    def __call__(self, x):
        return x


def _norm(norm: str, dtype, train: bool) -> Callable:
    if norm == "batch":
        return functools.partial(nn.BatchNorm, use_running_average=not train,
                                 momentum=0.9, epsilon=1e-5, dtype=dtype)
    if norm == "group":
        return functools.partial(AdaptiveGroupNorm, dtype=dtype)
    if norm == "none":
        return _Identity
    raise ValueError(f"unknown norm {norm!r}")


class BasicBlock(nn.Module):
    filters: int
    strides: tuple[int, int]
    norm: ModuleDef
    dtype: Any

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME",
                    use_bias=False, dtype=self.dtype)(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    strides: tuple[int, int]
    norm: ModuleDef
    dtype: Any

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=False, dtype=self.dtype)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        # zero-init the last norm's scale so blocks start as identity
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters * 4, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


@register_model("resnet")
class ResNet(nn.Module):
    """Configurable ResNet; ``stage_sizes=(3,4,6,3), bottleneck=True`` is
    ResNet-50."""

    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    bottleneck: bool = True
    width: int = 64
    norm: str = "group"
    dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, x, train: bool = False):
        dtype = jnp.dtype(self.dtype)
        norm = _norm(self.norm, dtype, train)
        block = BottleneckBlock if self.bottleneck else BasicBlock

        x = x.astype(dtype)
        x = nn.Conv(self.width, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=dtype)(x)
        x = norm()(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, size in enumerate(self.stage_sizes):
            for i in range(size):
                strides = (2, 2) if stage > 0 and i == 0 else (1, 1)
                x = block(filters=self.width * 2 ** stage, strides=strides,
                          norm=norm, dtype=dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def ResNet18(**kw) -> ResNet:
    kw.setdefault("stage_sizes", (2, 2, 2, 2))
    kw.setdefault("bottleneck", False)
    return ResNet(**kw)


def ResNet50(**kw) -> ResNet:
    kw.setdefault("stage_sizes", (3, 4, 6, 3))
    kw.setdefault("bottleneck", True)
    return ResNet(**kw)

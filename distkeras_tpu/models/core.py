"""Model registry + config (de)serialization.

The reference serializes Keras graphs as architecture-JSON + weights
(SURVEY.md §3.5, ``distkeras/utils.py: serialize_keras_model``).  The
TPU-native analogue: a model *family name* + kwargs dict, JSON-serializable,
resolved through a registry to a flax module.  No code travels; rebuilds are
deterministic; weights are a separate msgpack pytree
(``distkeras_tpu.utils.serialize_params``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping

import jax
import numpy as np

MODEL_REGISTRY: dict[str, Callable[..., Any]] = {}


def register_model(family: str):
    """Class decorator: register a flax module under ``family``."""

    def wrap(cls):
        MODEL_REGISTRY[family] = cls
        cls.family = family
        return cls

    return wrap


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """A serializable model description: family + constructor kwargs +
    an example input shape (without the batch dim) used for init."""

    family: str
    kwargs: Mapping[str, Any]
    input_shape: tuple[int, ...]
    input_dtype: str = "float32"

    def to_config(self) -> dict:
        return {
            "family": self.family,
            "kwargs": dict(self.kwargs),
            "input_shape": list(self.input_shape),
            "input_dtype": self.input_dtype,
        }

    @staticmethod
    def from_config(config: Mapping[str, Any]) -> "ModelSpec":
        if "family" not in config:
            raise KeyError(
                "model config missing 'family' key; build configs with "
                "distkeras_tpu.models.model_config")
        # JSON turns tuples into lists; normalize back so a config that
        # traveled rebuilds a module equal (and hashable) to the original.
        kwargs = {k: tuple(v) if isinstance(v, list) else v
                  for k, v in config.get("kwargs", {}).items()}
        return ModelSpec(
            family=config["family"],
            kwargs=kwargs,
            input_shape=tuple(config["input_shape"]),
            input_dtype=config.get("input_dtype", "float32"),
        )

    def build(self):
        return build_model(self.to_config())

    def example_input(self, batch_size: int = 2):
        return np.zeros((batch_size, *self.input_shape),
                        dtype=self.input_dtype)


def model_config(family: str, input_shape: tuple[int, ...],
                 input_dtype: str = "float32", **kwargs) -> dict:
    return ModelSpec(family, kwargs, tuple(input_shape),
                     input_dtype).to_config()


def build_model(config: Mapping[str, Any]):
    """Config dict -> flax module (the ``model_from_json`` analogue)."""
    family = config["family"]
    if family not in MODEL_REGISTRY:
        raise KeyError(
            f"unknown model family {family!r}; known: "
            f"{sorted(MODEL_REGISTRY)}")
    return MODEL_REGISTRY[family](**config.get("kwargs", {}))


def init_model(model, rng: jax.Array, sample_input, train: bool = False):
    """Initialize variables. Returns the full variable dict
    (``{'params': ..., possibly 'batch_stats': ...}``)."""
    return model.init(rng, sample_input, train=train)

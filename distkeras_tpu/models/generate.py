"""Autoregressive generation for ``TransformerLM`` — the LM family's
serving path.

The reference predates autoregressive serving entirely (SURVEY.md §0:
MLP/CNN-era workloads; its ``predictors.py`` is one batched forward per
row partition), so this surface has no counterpart to mirror — it is
the natural completion of the rebuild's LM family: training
(``trainers``), batch scoring (``predictors.ModelPredictor``), and now
token generation.

TPU-native shape: the prompt is processed in ONE forward pass that
fills every layer's KV cache (``TransformerLM(decode=True)`` — same
parameters, ``"cache"`` variable collection), then each new token is a
T=1 step inside a ``lax.scan``, so the whole generation compiles to a
single XLA program with static shapes — no per-token Python dispatch,
no retracing across steps.  Greedy (``temperature=0``), temperature,
top-k, and top-p (nucleus) sampling; ``beam_search`` decodes the
highest-scoring continuation over the same machinery.
"""

from __future__ import annotations

from typing import Mapping

import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.models.core import ModelSpec
from distkeras_tpu.models.transformer import TransformerLM


def _decode_model(model) -> TransformerLM:
    if isinstance(model, Mapping):
        model = ModelSpec.from_config(model).build()
    elif isinstance(model, ModelSpec):
        model = model.build()
    if not isinstance(model, TransformerLM):
        raise TypeError(
            "generate() serves TransformerLM models; got "
            f"{type(model).__name__}")
    if model.scan_blocks:
        raise ValueError(
            "generate() cannot serve scan_blocks=True models: the "
            "stacked param layout differs from the per-layer one the "
            "decode path walks.  Un-stack the params (or train "
            "without scan_blocks) to serve this model.")
    if model.num_experts > 0:
        raise ValueError(
            "generate() cannot serve MoE models yet: capacity-"
            "bucketed routing over a T=1 decode step diverges from "
            "the full-forward routing (different tokens overflow and "
            "drop), so cached decode would silently differ from what "
            "the trained model predicts.  Serve via the dense "
            "full-forward path (predictors) instead.")
    # The attention spellings (attn="auto"/flash_attn/blockwise_attn)
    # are KEPT: decode mode uses them as the prefill kernel, so a long
    # prompt runs the same flash/blockwise path training uses instead
    # of a dense O(T·max_len) read of the cache; each generated token
    # is a cached T=1 step either way.  Custom attn_fn and ring
    # (seq_axis) are cleared — their contracts are training-path
    # shapes.  remat_blocks off: decode never runs a backward pass,
    # so rematerializing every step is pure overhead (ADVICE r4).
    return model.clone(decode=True, attn_fn=None, seq_axis=None,
                       remat_blocks=False)


def _select(logits, temperature, top_k, top_p, rng):
    """Next-token choice from ``[B, V]`` logits (f32).

    Tie behavior of the ``top_p`` filter: every token whose logit
    equals the nucleus-threshold logit is kept, so exact ties can
    admit slightly more than ``top_p`` probability mass (the common
    implementation choice — the kept set is threshold-defined, not
    count-defined)."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        # lax.top_k for the kth-largest threshold, not a full-vocab
        # sort — this runs once per decode step
        kth = lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
    if top_p is not None:
        # nucleus: keep the smallest prefix of the sorted distribution
        # whose mass reaches top_p (the threshold token included)
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # mask tokens whose PRECEDING cumulative mass already >= top_p
        cut = jnp.sum((cum - probs) < top_p, axis=-1,
                      keepdims=True)                  # tokens kept
        kth = jnp.take_along_axis(sorted_logits, cut - 1, axis=-1)
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def decode_step(dec, params: Mapping, cache, tok, *, slot_pos=None,
                temperature: float = 0.0, top_k: int | None = None,
                top_p: float | None = None, rng=None):
    """One cached T=1 decode step, factored OUT of ``generate``'s
    ``lax.scan`` so a host scheduler can interleave admissions between
    steps (``serving.DecodeEngine``'s continuous-batching contract).

    Args:
      dec: a decode-mode model (``_decode_model`` output or an
        equivalent ``clone(decode=True)``).
      params: ``{"params": ...}`` (cache NOT included).
      cache: the ``"cache"`` collection to advance.
      tok: ``[B]`` int32 — the token each row feeds this step.
      slot_pos: optional ``[B]`` int32 per-slot cache positions
        (continuous batching); None = the scalar-index contract.
      rng: key for sampling (``temperature > 0``).

    Returns ``(new_cache, next_tok)`` with ``next_tok`` ``[B]`` int32.
    Jit-compatible; ``generate`` runs exactly this inside its scan.
    """
    logits, state = dec.apply({**params, "cache": cache}, tok[:, None],
                              slot_pos=slot_pos, mutable=["cache"])
    nxt = _select(logits[:, -1].astype(jnp.float32), temperature,
                  top_k, top_p, rng)
    return state["cache"], nxt


def generate(model, variables: Mapping, prompt, *,
             max_new_tokens: int, temperature: float = 0.0,
             top_k: int | None = None, top_p: float | None = None,
             rng=None, eos_id: int | None = None, pad_id: int = 0):
    """Generate ``max_new_tokens`` continuations of ``prompt``.

    Args:
      model: a ``TransformerLM``, its ``ModelSpec``, or a model config
        dict.  Parameters are shared with training: pass the trained
        ``variables`` unchanged.  The model's attention spelling
        (``attn``/``flash_attn``/``blockwise_attn``) selects the
        PREFILL kernel for 128-aligned prompt lengths — a long prompt
        runs the same flash/blockwise path training used; unaligned
        prompts and every generated token use cached dense attention
        (never an error).  Custom ``attn_fn`` and ``seq_axis`` are
        training-path contracts and are cleared for serving.
      variables: ``{"params": ...}`` as returned by init/training.
      prompt: ``[B, T_prompt]`` int32 token ids (``T_prompt >= 1``).
      max_new_tokens: number of tokens to append; ``T_prompt +
        max_new_tokens`` must fit the model's ``max_len`` (the KV
        cache and position table size).
      temperature: 0 = greedy argmax; > 0 = softmax sampling.
      top_k: optional sampling restriction to the k highest logits.
      top_p: optional nucleus sampling — restrict to the smallest set
        of tokens whose probability mass reaches ``top_p`` (0, 1];
        composes with ``top_k`` (both filters apply).
      rng: ``jax.random`` key, required when ``temperature > 0``.
      eos_id: optional stop token: rows that emit it are finished —
        the ``eos_id`` itself appears in the output and every later
        position is ``pad_id``.  Shapes stay static (the scan always
        runs ``max_new_tokens`` steps; finished rows just decode
        ignored padding), which is the jit-compatible contract.
      pad_id: filler for positions after ``eos_id`` (default 0).

    Returns:
      ``[B, T_prompt + max_new_tokens]`` int32 — prompt + generated.

    Jit-compatible (wrap in ``jax.jit`` with ``max_new_tokens`` etc.
    closed over); the decode loop is a ``lax.scan`` either way.
    """
    dec = _decode_model(model)
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim != 2 or prompt.shape[1] < 1:
        raise ValueError(
            f"prompt must be [B, T_prompt>=1]; got {prompt.shape}")
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1; got {max_new_tokens}")
    total = prompt.shape[1] + int(max_new_tokens)
    if total > dec.max_len:
        raise ValueError(
            f"prompt ({prompt.shape[1]}) + max_new_tokens "
            f"({max_new_tokens}) = {total} exceeds max_len="
            f"{dec.max_len}")
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 requires an rng key")
    if top_k is not None and not 1 <= top_k <= dec.vocab_size:
        raise ValueError(
            f"top_k={top_k} out of range [1, {dec.vocab_size}]")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p={top_p} out of range (0, 1]")
    if eos_id is not None and not 0 <= eos_id < dec.vocab_size:
        raise ValueError(
            f"eos_id={eos_id} outside vocab [0, {dec.vocab_size})")
    if eos_id is not None and not 0 <= pad_id < dec.vocab_size:
        # the pad token is fed back through the embedding on every
        # post-eos step — an OOB id would be silently gather-clamped
        raise ValueError(
            f"pad_id={pad_id} outside vocab [0, {dec.vocab_size})")
    if rng is None:
        rng = jax.random.key(0)  # unused on the greedy path
    params = {"params": variables["params"]}

    # One pass over the prompt creates and fills every layer's cache.
    logits, state = dec.apply(params, prompt, mutable=["cache"])
    rng, sub = jax.random.split(rng)
    tok = _select(logits[:, -1].astype(jnp.float32), temperature,
                  top_k, top_p, sub)
    done = (jnp.zeros(tok.shape, bool) if eos_id is None
            else tok == eos_id)

    def step(carry, _):
        cache, tok, rng, done = carry
        rng, sub = jax.random.split(rng)
        cache, nxt = decode_step(dec, params, cache, tok,
                                 temperature=temperature, top_k=top_k,
                                 top_p=top_p, rng=sub)
        if eos_id is not None:
            nxt = jnp.where(done, pad_id, nxt)
            done = done | (nxt == eos_id)
        return (cache, nxt, rng, done), tok

    if max_new_tokens > 1:
        (_, last, _, _), toks = lax.scan(
            step, (state["cache"], tok, rng, done), None,
            length=max_new_tokens - 1)
        new = jnp.concatenate([toks.T, last[:, None]], axis=1)
    else:
        new = tok[:, None]
    return jnp.concatenate([prompt, new], axis=1)


def _gather_beams(tree, flat_idx):
    """Reindex the batch-leading leaves of a cache/state pytree by
    ``flat_idx`` (scalar leaves — cache_index/pos_index — are shared
    across the batch and pass through)."""
    return jax.tree_util.tree_map(
        lambda x: x[flat_idx] if getattr(x, "ndim", 0) >= 1 else x,
        tree)


def beam_search(model, variables: Mapping, prompt, *,
                max_new_tokens: int, num_beams: int = 4,
                length_penalty: float = 0.0,
                eos_id: int | None = None, pad_id: int = 0):
    """Beam-search decoding: the highest-scoring continuation under
    the model's own log-probabilities.

    Same contract as ``generate`` (KV-cache decode, one compiled
    program, static shapes) with a beam dimension folded into the
    batch: the prompt is prefetched once per beam, every step scores
    ``[B, W*V]`` candidates, keeps the top ``W``, and reorders the
    KV caches and token histories by the surviving beams' parents.
    ``num_beams=1`` reduces exactly to greedy ``generate``.

    Args:
      length_penalty: final scores are divided by
        ``(length ** length_penalty)`` (0 = pure log-prob; > 0 favors
        longer finished sequences, the usual knob when ``eos_id``
        stops beams at different lengths).
      eos_id / pad_id: as in ``generate`` — a beam that emits
        ``eos_id`` is finished: its score freezes and it emits
        ``pad_id`` from then on.

    Returns:
      ``(sequences, scores)``: ``[B, T_prompt + max_new_tokens]``
      int32 and ``[B]`` f32 — the best beam per batch row and its
      (length-penalized) cumulative log-probability.
    """
    dec = _decode_model(model)
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim != 2 or prompt.shape[1] < 1:
        raise ValueError(
            f"prompt must be [B, T_prompt>=1]; got {prompt.shape}")
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1; got {max_new_tokens}")
    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1; got {num_beams}")
    if length_penalty < 0:
        raise ValueError(
            f"length_penalty must be >= 0; got {length_penalty}")
    total = prompt.shape[1] + int(max_new_tokens)
    if total > dec.max_len:
        raise ValueError(
            f"prompt ({prompt.shape[1]}) + max_new_tokens "
            f"({max_new_tokens}) = {total} exceeds max_len="
            f"{dec.max_len}")
    if eos_id is not None and not (0 <= eos_id < dec.vocab_size
                                   and 0 <= pad_id < dec.vocab_size):
        raise ValueError(
            f"eos_id={eos_id}/pad_id={pad_id} outside vocab "
            f"[0, {dec.vocab_size})")
    params = {"params": variables["params"]}
    b, w, v = prompt.shape[0], int(num_beams), dec.vocab_size
    if w > v:
        raise ValueError(f"num_beams={w} exceeds vocab_size={v}")
    n_new = int(max_new_tokens)

    # Prefill ONCE per batch row, then replicate the cache per beam
    # (identical rows would just waste (W-1)/W of the prompt FLOPs).
    logits, state = dec.apply(params, prompt, mutable=["cache"])
    cache0 = jax.tree_util.tree_map(
        lambda x: (jnp.repeat(x, w, axis=0)
                   if getattr(x, "ndim", 0) >= 1 else x),
        state["cache"])
    state = {"cache": cache0}
    logp = jax.nn.log_softmax(
        logits[:, -1].astype(jnp.float32))               # [B, V]
    # first pick: the top-W first tokens of each row's distribution
    scores, tok = lax.top_k(logp, w)                     # [B, W]
    tok = tok.astype(jnp.int32)
    # parents are all beam 0; caches are identical — no gather needed
    done = (tok == eos_id) if eos_id is not None \
        else jnp.zeros((b, w), bool)
    history = jnp.full((b, w, n_new), pad_id, jnp.int32)
    history = history.at[:, :, 0].set(tok)
    length = jnp.ones((b, w), jnp.int32)  # real tokens incl. eos

    def step(carry, t):
        cache, tok, scores, done, history, length = carry
        logits, state = dec.apply({**params, "cache": cache},
                                  tok.reshape(b * w, 1),
                                  mutable=["cache"])
        logp = jax.nn.log_softmax(
            logits[:, -1].astype(jnp.float32)).reshape(b, w, v)
        if eos_id is not None:
            # finished beams propose exactly one candidate: pad at
            # unchanged score (0 logprob), everything else -inf
            frozen = jnp.full((v,), -jnp.inf
                              ).at[pad_id].set(0.0)
            logp = jnp.where(done[..., None], frozen[None, None], logp)
        cand = scores[..., None] + logp                  # [B, W, V]
        scores, idx = lax.top_k(cand.reshape(b, w * v), w)
        parent = idx // v                                # [B, W]
        tok = (idx % v).astype(jnp.int32)
        flat_parent = (jnp.arange(b)[:, None] * w + parent).reshape(-1)
        cache = _gather_beams(state["cache"], flat_parent)
        history = jnp.take_along_axis(history, parent[..., None],
                                      axis=1)
        done = jnp.take_along_axis(done, parent, axis=1)
        length = jnp.take_along_axis(length, parent, axis=1)
        if eos_id is not None:
            tok = jnp.where(done, pad_id, tok)
            length = jnp.where(done, length, t + 1)
            done = done | (tok == eos_id)
        else:
            length = length + 1
        history = history.at[:, :, t].set(tok)
        return (cache, tok, scores, done, history, length), None

    if n_new > 1:
        (cache, tok, scores, done, history, length), _ = lax.scan(
            step, (state["cache"], tok, scores, done, history,
                   length),
            jnp.arange(1, n_new))  # noqa: F841 (cache/tok unused)

    if length_penalty > 0.0:
        final = scores / jnp.maximum(length, 1) ** length_penalty
    else:
        final = scores
    best = jnp.argmax(final, axis=1)                     # [B]
    seq = jnp.take_along_axis(
        history, best[:, None, None], axis=1)[:, 0]      # [B, n_new]
    return (jnp.concatenate([prompt, seq], axis=1),
            jnp.take_along_axis(final, best[:, None], axis=1)[:, 0])

"""Distributed batch inference — the reference's ``distkeras/predictors.py``
(SURVEY.md §3.3: ``ModelPredictor.predict(df)`` maps the deserialized model
over partitions, appending a prediction column).

TPU-native: one jitted forward pass over batches whose leading axis is
sharded across the mesh's worker axis (XLA shards the matmuls; no per-row
Python).  Appends the prediction column to the ``Dataset`` and returns it —
same DataFrame-in, DataFrame-out idiom.
"""

from __future__ import annotations

from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distkeras_tpu import mesh as mesh_lib
from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.core import ModelSpec
from distkeras_tpu.utils import pad_to_multiple


class ModelPredictor:
    """Append a prediction column (logits, probabilities, or class ids).

    ``output`` selects the column semantics: ``"logits"``, ``"prob"``
    (softmax), or ``"class"`` (argmax int32).

    A MULTI-OUTPUT model (e.g. an ingested two-head keras DAG —
    ``compat.keras``) appends one column per head, named
    ``{output_col}_{i}`` in the model's output order, with the same
    ``output`` transform applied per head.
    """

    def __init__(self, model, variables: Mapping, *,
                 features_col: str = "features",
                 output_col: str = "prediction",
                 output: str = "logits",
                 batch_size: int = 512,
                 num_shards: int | None = None,
                 model_parallel: int = 1, tp_rules=None):
        if isinstance(model, ModelSpec):
            self.spec = model
        elif isinstance(model, Mapping):
            self.spec = ModelSpec.from_config(model)  # raises if malformed
        else:
            self.spec = None
            if not hasattr(model, "apply"):
                raise TypeError(
                    "model must be a ModelSpec, a model config dict, or a "
                    f"flax module; got {type(model).__name__}")
        self.model = self.spec.build() if self.spec is not None else model
        self.variables = dict(variables)
        self.features_col = features_col
        self.output_col = output_col
        if output not in ("logits", "prob", "class"):
            raise ValueError(f"unknown output {output!r}")
        self.output = output
        self.batch_size = int(batch_size)
        self.model_parallel = int(model_parallel)
        if self.model_parallel < 1:
            raise ValueError(
                f"model_parallel must be >= 1, got {model_parallel}")
        if tp_rules is not None and self.model_parallel == 1:
            raise ValueError(
                "tp_rules given but model_parallel=1 — pass "
                "model_parallel>1 to shard parameters")

        devices = jax.devices()
        mp = self.model_parallel
        self.num_shards = (num_shards
                           or max(1, len(devices) // mp))
        if mp > 1:
            # create_mesh validates the device budget and raises its
            # own (identical) error when devices are short
            self._mesh = mesh_lib.create_mesh(self.num_shards,
                                              model_parallel=mp)
        else:
            self._mesh = (mesh_lib.create_mesh(self.num_shards)
                          if self.num_shards > 1
                          and len(devices) >= self.num_shards
                          else None)

        def transform(logits):
            if self.output == "prob":
                return jax.nn.softmax(logits, axis=-1)
            if self.output == "class":
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return logits

        def forward(variables, x):
            out = self.model.apply(variables, x, train=False)
            if isinstance(out, tuple):  # multi-output head per column
                return tuple(transform(o) for o in out)
            return transform(out)

        if self._mesh is not None:
            row = NamedSharding(self._mesh, P(mesh_lib.WORKER_AXIS))
            if mp > 1:
                # Megatron-sharded params over the model axis; GSPMD
                # derives the TP collectives (same rules the trainers
                # use — see parallel.tensor_parallel)
                from distkeras_tpu.parallel import tensor_parallel as tp

                if tp_rules is None:
                    if self.spec is None:
                        raise ValueError(
                            "model_parallel>1 with a bare flax module "
                            "needs explicit tp_rules (a ModelSpec "
                            "carries the family to look them up)")
                    tp_rules = tp.rules_for(self.spec.family)
                var_sharding = tp.tree_shardings(self._mesh,
                                                 self.variables,
                                                 tp_rules)
                self.variables = jax.device_put(self.variables,
                                                var_sharding)
            else:
                var_sharding = NamedSharding(self._mesh, P())
            self._forward = jax.jit(forward,
                                    in_shardings=(var_sharding, row),
                                    out_shardings=row)
        else:
            self._forward = jax.jit(forward)

    def predict(self, dataset: Dataset) -> Dataset:
        n = len(dataset)
        x = np.asarray(dataset[self.features_col])
        # Pad to a full (sharded) batch so every device call has one static
        # shape; strip padding after.
        chunk = self.batch_size * max(self.num_shards, 1)
        x = pad_to_multiple(x, chunk, axis=0)
        outs = []
        for lo in range(0, len(x), chunk):
            out = self._forward(self.variables,
                                jnp.asarray(x[lo:lo + chunk]))
            outs.append(tuple(np.asarray(o) for o in out)
                        if isinstance(out, tuple) else np.asarray(out))
        if isinstance(outs[0], tuple):
            for i in range(len(outs[0])):
                pred = np.concatenate([o[i] for o in outs])[:n]
                dataset = dataset.with_column(
                    f"{self.output_col}_{i}", pred)
            return dataset
        pred = np.concatenate(outs)[:n]
        return dataset.with_column(self.output_col, pred)

    # Spark-ML idiom alias (reference uses transformer-style `.predict`;
    # pipelines compose via __call__)
    def __call__(self, dataset: Dataset) -> Dataset:
        return self.predict(dataset)

"""Block-paged KV memory for the decode engine (vLLM's PagedAttention
idea under XLA's static-shape constraint) + the QoS bookkeeping the
paged pool makes possible.

``DecodeEngine``'s envelope pools pay the §18 cost law twice: every
slot reserves ``cache_envelope`` rows of K/V up front, so concurrency
is provisioned for the worst case (``cache_envelope x slots`` bytes)
while most requests use a fraction of it.  PagedAttention (Kwon et
al., SOSP '23) breaks the reservation: KV lives in fixed-size PAGES
(here ``page_size`` tokens, one device pool per cache leaf), each
request holds a PAGE TABLE, and a slot's cost is its actual token
count rounded up to a page.

XLA cannot index a cache through a dynamic page table inside the
attention kernel without a custom pager, so the lowering here keeps
the *compute* byte-identical to the envelope path instead of
rewriting it: each compiled program GATHERS a bucket's slot pages into
the exact envelope layout (``[slots, KVH, env, D]``), runs the
UNCHANGED legacy step/prefill body, and SCATTERS the envelope back
into the pages.  Greedy parity with the envelope pool is therefore
structural, not numerical — the attention sees the same unmasked rows
bit-for-bit (masked rows differ — stale page garbage vs zeros — but
both contribute exactly ``exp(-1e30 - max) == 0.0`` after the f32
softmax, see ``models.transformer``).

Page id 0 is RESERVED as a garbage/scratch page: unallocated page-
table entries point at it, so the envelope-wide scatter is always
well-formed (writes land on page 0 and are never read back for live
rows) and the gather never faults.  ``PageAllocator`` hands out ids
``1..n_pages`` from a host-side free list with per-tenant quotas —
the admission-time substrate for the engine's QoS scheduler
(priority classes, preemption, readmission).

The pool layout per 4-D cache leaf is ``[n_pages + 1, KVH,
page_size, D]`` — envelope-free, exactly ``_PrefixStore``'s segment
shape batched over pages — so with ``page_size == prefill_align``
prefix sharing and paging are one mechanism: a prefix-cache hit is a
device copy into a page, donation is a page slice out.
"""

from __future__ import annotations

from typing import Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

#: the reserved garbage/scratch page id (never allocated; the page-
#: table filler for unallocated entries)
GARBAGE_PAGE = 0


def pages_for(tokens: int, page_size: int) -> int:
    """Pages covering ``tokens`` rows (ceil division)."""
    return -(-int(tokens) // int(page_size))


def build_pool(cache_shapes, n_pages: int, page_size: int) -> list:
    """Zeroed device page pool: one ``[n_pages + 1, KVH, page, D]``
    leaf per 4-D cache leaf of ``cache_shapes`` (an ``eval_shape``
    cache template), in flatten order — scalar cache/pos-index leaves
    are skipped, exactly like ``_PrefixStore`` segments.  Row 0 is the
    garbage page.  Zero-init keeps every pool value finite from the
    start: the masked-row exactness argument needs finite garbage,
    never NaN."""
    out = []
    for leaf in jax.tree_util.tree_leaves(cache_shapes):
        if len(leaf.shape) == 0:
            continue
        out.append(jnp.zeros(
            (n_pages + 1, leaf.shape[1], page_size, leaf.shape[3]),
            leaf.dtype))
    return out


def pool_nbytes(pages: list) -> int:
    return sum(int(p.nbytes) for p in pages)


def leaf_templates(segments) -> list[dict]:
    """Self-describing ``{"shape", "dtype"}`` descriptors for one KV
    block's segment leaves — the wire meta the disaggregated handoff
    ships ahead of the raw page bytes, so the receiver can slice a
    gather-sent frame back into typed arrays without any per-leaf
    framing (``serving.pack_kv_blocks`` / ``unpack_kv_blocks``).
    Every block of one export shares these templates: blocks are
    ``[1, KVH, page, D]`` slices of the same pool leaves."""
    return [{"shape": [int(d) for d in np.asarray(s).shape],
             "dtype": str(np.asarray(s).dtype)} for s in segments]


def gather_cache(cache_shapes, pages: list, table):
    """Materialize the envelope-layout cache pytree from the page pool
    (traced; runs inside the compiled program).  ``table`` is the
    ``[slots, env // page]`` int32 page table; scalar template leaves
    come back as zeros (slot state owns positions — the legacy
    programs never read them)."""
    leaves, treedef = jax.tree_util.tree_flatten(cache_shapes)
    segs = iter(pages)
    out = []
    for tmpl in leaves:
        if len(tmpl.shape) == 0:
            out.append(jnp.zeros((), tmpl.dtype))
            continue
        p = next(segs)                     # [P+1, KVH, page, D]
        x = p[table]                       # [S, MB, KVH, page, D]
        x = jnp.moveaxis(x, 1, 2)          # [S, KVH, MB, page, D]
        out.append(x.reshape(table.shape[0], p.shape[1],
                             table.shape[1] * p.shape[2], p.shape[3]))
    return jax.tree_util.tree_unflatten(treedef, out)


def scatter_cache(pages: list, cache, table) -> list:
    """Write the envelope-layout cache back into the page pool
    (traced).  Every unallocated table entry is ``GARBAGE_PAGE``, so
    the scatter's duplicate indices all land on page 0 — which slot's
    garbage wins is unspecified and irrelevant (page 0 is never read
    for a live row, and cache values are always finite)."""
    flat = table.reshape(-1)
    segs = iter(pages)
    out = []
    for leaf in jax.tree_util.tree_leaves(cache):
        if jnp.ndim(leaf) == 0:
            continue
        p = next(segs)
        s, kvh, env, d = leaf.shape
        mb, page = table.shape[1], p.shape[2]
        x = leaf.reshape(s, kvh, mb, page, d)
        x = jnp.moveaxis(x, 2, 1).reshape(s * mb, kvh, page, d)
        out.append(p.at[flat].set(x))
    return out


class PageAllocator:
    """Host-side free-list allocator over page ids ``1..n_pages`` with
    per-tenant quotas.

    Mutated only on the engine's stepping thread (the same ownership
    discipline as ``_PrefixStore``); ``n_free`` is a plain int read
    and safe to sample from other threads (the gateway's
    ``free_pages`` load signal).

    ``tenant_quota`` caps the pages any one tenant may hold at once:
    an int applies to every tenant, a mapping caps listed tenants and
    leaves the rest unbounded, ``None`` disables quotas.  Quota is
    enforced at allocation time — the admission scheduler skips a
    quota-blocked request instead of letting it starve the pool.
    """

    def __init__(self, n_pages: int, page_size: int,
                 tenant_quota=None):
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        # pop() order: 1, 2, ... — deterministic page ids for a
        # deterministic workload (the seeded preemption drill relies
        # on reproducible allocation)
        self._free = list(range(self.n_pages, 0, -1))
        self.tenant_quota = tenant_quota
        self.used: dict = {}          # tenant -> pages held
        self.allocated_total = 0
        self.freed_total = 0

    @property
    def n_free(self) -> int:
        return len(self._free)

    def quota_for(self, tenant) -> Optional[int]:
        if self.tenant_quota is None:
            return None
        if isinstance(self.tenant_quota, Mapping):
            q = self.tenant_quota.get(tenant)
            return None if q is None else int(q)
        return int(self.tenant_quota)

    def fits_quota(self, n: int, tenant) -> bool:
        q = self.quota_for(tenant)
        return q is None or self.used.get(tenant, 0) + n <= q

    def alloc(self, n: int, tenant=None) -> Optional[list]:
        """``n`` page ids, or None if capacity or the tenant's quota
        is short (the caller distinguishes via ``fits_quota`` —
        preemption can fix capacity, never quota)."""
        if n > len(self._free) or not self.fits_quota(n, tenant):
            return None
        pids = [self._free.pop() for _ in range(n)]
        self.used[tenant] = self.used.get(tenant, 0) + n
        self.allocated_total += n
        return pids

    def free(self, pids: list, tenant=None) -> None:
        self._free.extend(reversed(pids))
        left = self.used.get(tenant, 0) - len(pids)
        if left > 0:
            self.used[tenant] = left
        else:
            self.used.pop(tenant, None)
        self.freed_total += len(pids)

    def stats(self) -> dict:
        return {"n_pages": self.n_pages, "page_size": self.page_size,
                "free": self.n_free,
                "allocated_total": self.allocated_total,
                "freed_total": self.freed_total,
                "tenants": dict(self.used)}

"""Unified telemetry — one metrics registry and one trace timeline for
every concurrent layer of the stack (SURVEY.md §5 "honest
observability": the reference records only wall-clock ``training_time``).

Before this module the repo's telemetry was fragmented: trainers
appended to per-instance ``history`` dicts under a hand-rolled lock, the
decode engine stamped raw ``t_submit/t_first/t_finish`` floats onto
requests with ``time.perf_counter``, and the host PS tracked heartbeats
privately with ``time.monotonic`` — three bookkeeping systems on two
clocks, none able to answer "what was queue depth when p99 TTFT
spiked?".  This module is the one place all of that lands:

* ``now()`` — THE host-side monotonic clock.  Every host timestamp in
  the repo (serving request stamps, PS heartbeats, span boundaries,
  stall timers) reads this single source, so durations computed across
  subsystems are always on one clock.
* ``MetricsRegistry`` — thread-safe counters, gauges, fixed-bucket
  histograms, and append-only series (the trainer-``history`` backing).
  ``snapshot()`` for programmatic reads, ``write_jsonl()`` for logs,
  ``prometheus_text()`` + an opt-in background ``http.server`` thread
  (``serve()``) for live ``/metrics`` scraping.
* ``Tracer`` — ``with span("commit", worker=i):`` records thread-aware
  complete events into a bounded in-memory ring; ``write_chrome_trace``
  dumps Chrome trace-event JSON loadable in Perfetto, so the racing
  host-PS arm (handler threads, worker threads, retry/idle events),
  trainer rounds, and ``DecodeEngine`` admissions / prefills /
  step-quanta / evictions all land on one timeline with one thread
  track each.

Disabled-by-default fast path: the module-level singleton starts as a
no-op ``Telemetry`` whose metric handles and spans are shared inert
objects — an instrumented hot path pays one attribute lookup and one
no-op call (measured sub-microsecond; PERF.md §24) — so tier-1 numerics
and perf rows are untouched until ``enable()`` is called.  Trainer
``history`` uses private always-on registries (a ``MetricsRegistry`` is
just objects + a lock), independent of the global switch.

Everything here is stdlib-only by design: no prometheus_client, no
opentelemetry — the export FORMATS are the interop point.

Usage::

    from distkeras_tpu import telemetry
    tel = telemetry.enable()              # flip the global switch
    ... run trainers / engine ...
    tel.metrics.write_jsonl("metrics.jsonl")
    tel.tracer.write_chrome_trace("trace.json")   # open in Perfetto
    telemetry.disable()
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
from typing import Any, Iterator, Mapping

#: THE host-side monotonic clock (satellite: serving ``t_submit`` /
#: ``t_first`` / ``t_finish``, host-PS ``_last_seen``, and every span
#: boundary read this one source).  ``perf_counter`` is monotonic with
#: the highest available resolution; its origin is arbitrary, so values
#: are only meaningful as differences — never persist them as wall
#: times.
now = time.perf_counter

#: Default histogram bucket upper bounds, in seconds — latency-shaped
#: (1 ms .. 60 s).  Counts accumulate cumulatively per Prometheus
#: convention; values above the last edge land in +Inf only.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

#: Staleness-shaped buckets (commit depths, not seconds).
STALENESS_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64, 128)


def _escape_label_value(v: Any) -> str:
    """Prometheus exposition escaping for label VALUES: backslash,
    double-quote, and newline must be escaped or the emitted line is
    invalid exposition text (a label value containing ``"`` would
    terminate the value early; a newline would split the sample)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_key(name: str, labels: Mapping[str, Any]) -> str:
    """Prometheus-style series key: ``name{a="1",b="x"}`` (labels
    sorted, values escaped per the exposition format)."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{_escape_label_value(labels[k])}"'
                     for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonically increasing count (thread-safe)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-set value (thread-safe); ``inc``/``dec`` for level-style
    gauges (queue depth, slot occupancy)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram (thread-safe): cumulative bucket counts
    per Prometheus convention, plus count/sum/min/max for snapshot
    consumers that want quick percentile estimates."""

    __slots__ = ("buckets", "_lock", "_counts", "_count", "_sum",
                 "_min", "_max")

    def __init__(self, buckets=DEFAULT_BUCKETS):
        edges = tuple(float(b) for b in buckets)
        if not edges or any(nxt <= prev
                            for nxt, prev in zip(edges[1:], edges)):
            raise ValueError(
                f"histogram buckets must be strictly increasing and "
                f"non-empty; got {buckets!r}")
        self.buckets = edges
        self._lock = threading.Lock()
        self._counts = [0] * (len(edges) + 1)  # +1: the +Inf bucket
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, v: float) -> None:
        v = float(v)
        i = 0
        for edge in self.buckets:
            if v <= edge:
                break
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
            lo, hi = self._min, self._max
        cum, cumulative = 0, {}
        for edge, n in zip(self.buckets, counts):
            cum += n
            cumulative[edge] = cum
        return {"count": total, "sum": s,
                "min": None if total == 0 else lo,
                "max": None if total == 0 else hi,
                "buckets": cumulative}

    def percentile(self, q: float) -> float | None:
        """Bucket-resolution estimate of the q-th percentile (q in
        [0, 1]): the upper edge of the first bucket whose cumulative
        count covers q — an over-estimate by at most one bucket width,
        the standard fixed-bucket tradeoff.  None when empty."""
        snap = self.snapshot()
        if snap["count"] == 0:
            return None
        need = q * snap["count"]
        for edge, cum in snap["buckets"].items():
            if cum >= need:
                return edge
        return snap["max"]


class Series:
    """Thread-safe append-only value log — the backing store for
    trainer ``history`` keys (per-round losses, staleness lists,
    failure records): things that are a sequence of observations, not a
    counter or a distribution."""

    __slots__ = ("_lock", "_values")

    def __init__(self):
        self._lock = threading.Lock()
        self._values: list = []

    def append(self, v) -> None:
        with self._lock:
            self._values.append(v)

    def extend(self, vs) -> None:
        with self._lock:
            self._values.extend(vs)

    def replace(self, vs) -> None:
        with self._lock:
            self._values = list(vs)

    def values(self) -> list:
        with self._lock:
            return list(self._values)

    def __len__(self) -> int:
        with self._lock:
            return len(self._values)


class _NoopMetric:
    """Shared inert metric handle: every mutator is a no-op, every read
    is empty/zero.  One instance serves every disabled call site."""

    __slots__ = ()

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def append(self, v) -> None:
        pass

    def extend(self, vs) -> None:
        pass

    value = 0.0
    count = 0

    def values(self) -> list:
        return []

    def snapshot(self) -> dict:
        return {}


_NOOP_METRIC = _NoopMetric()


class MetricsRegistry:
    """Thread-safe name+labels -> metric store.

    ``counter``/``gauge``/``histogram``/``series`` are get-or-create:
    the first call materializes the metric, later calls (any thread)
    return the same object, so hot paths may either cache the handle or
    re-look it up.  Export three ways: ``snapshot()`` (one nested
    dict), ``write_jsonl(path)`` (one JSON object per metric, greppable
    logs), ``prometheus_text()`` (text exposition; pair with
    ``serve()`` for a live ``/metrics`` endpoint).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, tuple[str, str, dict, Any]] = {}
        self._httpd = None
        self._http_thread = None
        self._watchdog: "SLOWatchdog | None" = None

    # -- get-or-create ------------------------------------------------

    def _get(self, kind: str, name: str, labels: dict, make):
        key = _label_key(name, labels)
        with self._lock:
            got = self._metrics.get(key)
            if got is None:
                got = (kind, name, {k: str(v)
                                    for k, v in labels.items()}, make())
                self._metrics[key] = got
            elif got[0] != kind:
                raise ValueError(
                    f"metric {key!r} already registered as {got[0]}, "
                    f"not {kind}")
            return got[3]

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels, Counter)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels, Gauge)

    def histogram(self, name: str, buckets=None, **labels) -> Histogram:
        make = (Histogram if buckets is None
                else lambda: Histogram(buckets))
        return self._get("histogram", name, labels, make)

    def series(self, name: str, **labels) -> Series:
        return self._get("series", name, labels, Series)

    # -- queries ------------------------------------------------------

    def collect(self, name: str, **label_filter
                ) -> list[tuple[dict, Any]]:
        """All (labels, metric) pairs for ``name`` whose labels are a
        superset of ``label_filter`` — e.g. every per-padded-length
        prefill compile counter of one bucket."""
        want = {k: str(v) for k, v in label_filter.items()}
        with self._lock:
            items = list(self._metrics.values())
        return [(labels, m) for kind, n, labels, m in items
                if n == name and all(labels.get(k) == v
                                     for k, v in want.items())]

    def sum_counter(self, name: str, **label_filter) -> float:
        return sum(m.value
                   for _, m in self.collect(name, **label_filter))

    def snapshot(self) -> dict:
        """``{"counters": {key: value}, "gauges": {key: value},
        "histograms": {key: {...}}, "series": {key: [...]}}`` — keys
        are Prometheus-style ``name{label="v"}`` strings."""
        with self._lock:
            items = list(self._metrics.items())
        out: dict = {"counters": {}, "gauges": {}, "histograms": {},
                     "series": {}}
        for key, (kind, _, _, m) in items:
            if kind == "counter":
                out["counters"][key] = m.value
            elif kind == "gauge":
                out["gauges"][key] = m.value
            elif kind == "histogram":
                out["histograms"][key] = m.snapshot()
            else:
                out["series"][key] = m.values()
        return out

    def write_jsonl(self, path: str | os.PathLike) -> str:
        """One JSON object per metric: ``{"kind", "name", "labels",
        ...kind-specific payload}``.  Series values must be
        JSON-encodable (trainer history already is — it rides the
        msgpack checkpoint cursor as JSON)."""
        with self._lock:
            items = list(self._metrics.items())
        lines = []
        for key, (kind, name, labels, m) in items:
            rec = {"kind": kind, "name": name, "labels": labels,
                   "key": key}
            if kind == "histogram":
                snap = m.snapshot()
                snap["buckets"] = {str(k): v
                                   for k, v in snap["buckets"].items()}
                rec.update(snap)
            elif kind == "series":
                rec["values"] = m.values()
            else:
                rec["value"] = m.value
            lines.append(json.dumps(rec))
        p = os.fspath(path)
        with open(p, "w") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))
        return p

    def prometheus_text(self) -> str:
        """Prometheus text exposition (format 0.0.4): counters and
        gauges verbatim; histograms as cumulative ``_bucket{le=}`` +
        ``_sum``/``_count``; series as an untyped last-value sample
        plus a ``_total`` observation count (full series history is a
        log concern — ``write_jsonl`` — not a scrape concern)."""
        with self._lock:
            items = list(self._metrics.items())
        by_name: dict[str, list] = {}
        kinds: dict[str, str] = {}
        for key, (kind, name, labels, m) in items:
            by_name.setdefault(name, []).append((labels, m))
            kinds[name] = kind
        out: list[str] = []
        for name in sorted(by_name):
            kind = kinds[name]
            ptype = {"counter": "counter", "gauge": "gauge",
                     "histogram": "histogram",
                     "series": "untyped"}[kind]
            out.append(f"# TYPE {name} {ptype}")
            for labels, m in by_name[name]:
                if kind in ("counter", "gauge"):
                    out.append(f"{_label_key(name, labels)} {m.value}")
                elif kind == "histogram":
                    snap = m.snapshot()
                    for edge, cum in snap["buckets"].items():
                        out.append(_label_key(
                            name + "_bucket",
                            {**labels, "le": edge}) + f" {cum}")
                    out.append(_label_key(
                        name + "_bucket", {**labels, "le": "+Inf"})
                        + f" {snap['count']}")
                    out.append(f"{_label_key(name + '_sum', labels)} "
                               f"{snap['sum']}")
                    out.append(f"{_label_key(name + '_count', labels)} "
                               f"{snap['count']}")
                else:
                    vals = m.values()
                    last = vals[-1] if vals else float("nan")
                    if not isinstance(last, (int, float, bool)):
                        last = float("nan")  # structured series entry
                    out.append(f"{_label_key(name, labels)} "
                               f"{float(last)}")
                    out.append(
                        f"{_label_key(name + '_observations', labels)}"
                        f" {len(vals)}")
        return "\n".join(out) + "\n"

    # -- health -------------------------------------------------------

    def attach_watchdog(self, watchdog: "SLOWatchdog") -> None:
        """Make ``watchdog`` the registry's health evaluator: its last
        (or on-demand) evaluation backs ``health()`` and the
        ``/healthz`` endpoint."""
        self._watchdog = watchdog

    def health(self) -> dict:
        """The current SLO health verdict over this registry — the
        attached watchdog's evaluation, or a one-shot default-threshold
        ``SLOWatchdog`` pass when none is attached."""
        w = self._watchdog
        if w is None:
            w = SLOWatchdog(self)
        return w.evaluate()

    # -- the opt-in /metrics thread -----------------------------------

    def serve(self, host: str = "127.0.0.1", port: int = 0
              ) -> tuple[str, int]:
        """Start a background daemon thread serving ``GET /metrics``
        (Prometheus text), ``GET /metrics.json`` (the snapshot), and
        ``GET /healthz`` (the SLO watchdog verdict; HTTP 503 when
        critical).  Returns the bound ``(host, port)``; ``port=0``
        picks a free one.  Call ``stop_serving()`` to shut it down."""
        if self._httpd is not None:
            return self._httpd.server_address[:2]
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        registry = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                status = 200
                if self.path.split("?")[0] == "/metrics":
                    body = registry.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path.split("?")[0] == "/metrics.json":
                    body = json.dumps(registry.snapshot()).encode()
                    ctype = "application/json"
                elif self.path.split("?")[0] == "/healthz":
                    verdict = registry.health()
                    body = json.dumps(verdict).encode()
                    ctype = "application/json"
                    if verdict["state"] == "critical":
                        status = 503
                else:
                    self.send_error(404)
                    return
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not stdout news
                pass

        import errno
        try:
            self._httpd = ThreadingHTTPServer((host, port), Handler)
        except OSError as e:
            if e.errno != errno.EADDRINUSE:
                raise
            raise OSError(
                e.errno,
                f"metrics endpoint cannot bind {host}:{port}: the "
                f"port is already in use — pass port=0 to let the OS "
                f"pick a free one, or stop the other listener "
                f"first") from e
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dkt-metrics-http")
        self._http_thread.start()
        return self._httpd.server_address[:2]

    def stop_serving(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._http_thread.join()
            self._httpd = self._http_thread = None


class NullRegistry:
    """Disabled-path registry: every lookup returns the shared inert
    metric, every export is empty.  Keeps instrumented call sites
    branch-free."""

    def counter(self, name: str, **labels) -> _NoopMetric:
        return _NOOP_METRIC

    def gauge(self, name: str, **labels) -> _NoopMetric:
        return _NOOP_METRIC

    def histogram(self, name: str, buckets=None,
                  **labels) -> _NoopMetric:
        return _NOOP_METRIC

    def series(self, name: str, **labels) -> _NoopMetric:
        return _NOOP_METRIC

    def collect(self, name: str, **label_filter) -> list:
        return []

    def sum_counter(self, name: str, **label_filter) -> float:
        return 0.0

    def snapshot(self) -> dict:
        return {"counters": {}, "gauges": {}, "histograms": {},
                "series": {}}

    def prometheus_text(self) -> str:
        return ""

    def health(self) -> dict:
        # no signals → every threshold is "absent" → "ok"
        return SLOWatchdog(self).evaluate()


# -- trace context (cross-process propagation) -------------------------
#
# Every live ``_Span`` gets a PROCESS-UNIQUE 64-bit span id (the pid in
# the high bits disambiguates ids minted by different processes, so a
# merged multi-process trace never aliases two spans) and pushes
# ``(trace_id, span_id)`` onto a thread-local stack.  A root span's id
# doubles as the trace id; nested spans inherit the trace id, so a
# retry storm inside one ``ps_op`` span shares one trace.  Wire clients
# read ``current_trace()`` to stamp the 17-byte header the PS server
# links back to (see ``parallel.transport.trace_header``).

_span_id_lock = threading.Lock()
_span_id_next = [1]
_trace_ctx = threading.local()


def _new_span_id() -> int:
    with _span_id_lock:
        n = _span_id_next[0]
        _span_id_next[0] += 1
    # 24 pid bits | 40 counter bits: unique within a process for 2^40
    # spans, and across processes for merged traces
    return ((os.getpid() & 0xFFFFFF) << 40) | (n & 0xFFFFFFFFFF)


def current_trace() -> tuple[int, int] | None:
    """``(trace_id, span_id)`` of this thread's innermost live span —
    ``None`` when no span is open (always the case while telemetry is
    disabled: only real spans push context)."""
    stack = getattr(_trace_ctx, "stack", None)
    if not stack:
        return None
    return stack[-1]


class _Span:
    """One ``with``-scoped trace span: ts taken at enter, a Chrome
    complete ("X") event appended to the ring at exit.  Exceptions
    inside the span mark ``args["error"]`` and re-raise.  Enter pushes
    ``(trace_id, span_id)`` onto the thread's trace-context stack (for
    wire propagation); exit pops it and stamps both ids into the
    event's args."""

    __slots__ = ("_tracer", "name", "args", "_t0", "trace_id",
                 "span_id")

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        stack = getattr(_trace_ctx, "stack", None)
        if stack is None:
            stack = _trace_ctx.stack = []
        sid = _new_span_id()
        self.span_id = sid
        self.trace_id = stack[-1][0] if stack else sid
        stack.append((self.trace_id, sid))
        self._t0 = now()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = now()
        _trace_ctx.stack.pop()
        args = {**self.args, "trace_id": format(self.trace_id, "x"),
                "span_id": format(self.span_id, "x")}
        if exc_type is not None:
            args["error"] = exc_type.__name__
        self._tracer._complete(self.name, self._t0, t1, args)
        return False


class _NoopSpan:
    """Shared reusable disabled span — ``with`` costs two no-op calls.
    Safe to share across threads and nestings: enter/exit carry no
    state."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SPAN = _NoopSpan()

# Trace-track thread ids: ``threading.get_ident()`` values are REUSED
# once a thread exits, which would merge sequential threads onto one
# Perfetto track under the first thread's name.  Stamp each thread with
# a process-unique id instead (module-global so every Tracer agrees).
_tid_lock = threading.Lock()
_tid_next = [1]


def _thread_trace_id() -> int:
    t = threading.current_thread()
    tid = getattr(t, "_dkt_trace_tid", None)
    if tid is None:
        with _tid_lock:
            tid = getattr(t, "_dkt_trace_tid", None)
            if tid is None:
                tid = _tid_next[0]
                _tid_next[0] += 1
                t._dkt_trace_tid = tid
    return tid


class Tracer:
    """Bounded in-memory ring of Chrome trace events.

    ``span(name, **args)`` records a complete ("X") event per thread;
    ``instant(name, **args)`` a thread-scoped instant ("i") event.
    The ring (``collections.deque(maxlen=capacity)``) keeps the LAST
    ``capacity`` events — a long run keeps its newest window, which is
    the window you are debugging.  ``write_chrome_trace(path)`` dumps
    the Chrome trace-event JSON object format (``{"traceEvents":
    [...]}``) with thread-name metadata, loadable in Perfetto /
    ``chrome://tracing``.
    """

    def __init__(self, capacity: int = 65536):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1; got {capacity}")
        self.capacity = capacity
        self._ring: collections.deque = collections.deque(
            maxlen=capacity)
        self._lock = threading.Lock()
        self._thread_names: dict[int, str] = {}
        self._pid = os.getpid()

    # -- recording ----------------------------------------------------

    def _note_thread(self) -> int:
        tid = _thread_trace_id()
        if tid not in self._thread_names:
            with self._lock:
                self._thread_names[tid] = \
                    threading.current_thread().name
        return tid

    def _complete(self, name: str, t0: float, t1: float,
                  args: dict) -> None:
        tid = self._note_thread()
        # deque.append is atomic under the GIL; events land in ring
        # order per thread (append happens at span exit)
        self._ring.append({
            "name": name, "ph": "X", "ts": t0 * 1e6,
            "dur": max(t1 - t0, 0.0) * 1e6,
            "pid": self._pid, "tid": tid, "args": args})

    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args)

    def complete(self, name: str, t0: float, t1: float,
                 **args) -> None:
        """Record a complete event from explicit ``now()`` stamps —
        the minimal-diff alternative to ``with span(...)`` for long
        loop bodies that would otherwise re-indent wholesale."""
        self._complete(name, t0, t1, args)

    def instant(self, name: str, **args) -> None:
        tid = self._note_thread()
        self._ring.append({
            "name": name, "ph": "i", "ts": now() * 1e6, "s": "t",
            "pid": self._pid, "tid": tid, "args": args})

    def flow_start(self, name: str, flow_id: int, **args) -> None:
        """Chrome flow-start ("s"): the tail of a client→server arrow.
        ``flow_id`` must be process-unique (a span id); the matching
        ``flow_end`` on the server side completes the arrow in the
        merged trace."""
        tid = self._note_thread()
        self._ring.append({
            "name": name, "cat": "wire", "ph": "s",
            "id": format(flow_id, "x"), "ts": now() * 1e6,
            "pid": self._pid, "tid": tid, "args": args})

    def flow_end(self, name: str, flow_id: int, **args) -> None:
        """Chrome flow-finish ("f", binding point "e"): the head of the
        arrow, emitted inside the server's handler span."""
        tid = self._note_thread()
        self._ring.append({
            "name": name, "cat": "wire", "ph": "f", "bp": "e",
            "id": format(flow_id, "x"), "ts": now() * 1e6,
            "pid": self._pid, "tid": tid, "args": args})

    # -- export -------------------------------------------------------

    def events(self) -> list[dict]:
        return list(self._ring)

    def __len__(self) -> int:
        return len(self._ring)

    def clear(self) -> None:
        self._ring.clear()

    def chrome_trace(self) -> dict:
        """The Chrome trace-event JSON object: ring events plus
        process/thread-name metadata records."""
        with self._lock:
            names = dict(self._thread_names)
        meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                 "tid": 0, "args": {"name": "distkeras_tpu"}}]
        for tid, tname in sorted(names.items()):
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": self._pid, "tid": tid,
                         "args": {"name": tname}})
        # wall↔mono anchor taken at DUMP time: ``merge_traces`` uses it
        # to shift each process's arbitrary-origin perf_counter
        # timestamps onto one shared timeline
        return {"traceEvents": meta + self.events(),
                "displayTimeUnit": "ms",
                "wallAnchor": {"wall_s": time.time(),
                               "mono_s": now(), "pid": self._pid}}

    def write_chrome_trace(self, path: str | os.PathLike) -> str:
        p = os.fspath(path)
        with open(p, "w") as f:
            json.dump(self.chrome_trace(), f)
        return p


class NullTracer:
    """Disabled-path tracer: spans are the shared no-op span."""

    capacity = 0

    def span(self, name: str, **args) -> _NoopSpan:
        return _NOOP_SPAN

    def complete(self, name: str, t0: float, t1: float,
                 **args) -> None:
        pass

    def instant(self, name: str, **args) -> None:
        pass

    def flow_start(self, name: str, flow_id: int, **args) -> None:
        pass

    def flow_end(self, name: str, flow_id: int, **args) -> None:
        pass

    def events(self) -> list:
        return []

    def __len__(self) -> int:
        return 0

    def clear(self) -> None:
        pass


class Telemetry:
    """One metrics registry + one tracer, the pair ``enable()``
    installs globally."""

    def __init__(self, metrics: MetricsRegistry | None = None,
                 tracer: Tracer | None = None):
        self.metrics = MetricsRegistry() if metrics is None else metrics
        self.tracer = Tracer() if tracer is None else tracer

    enabled = True

    def span(self, name: str, **args):
        return self.tracer.span(name, **args)

    def instant(self, name: str, **args) -> None:
        self.tracer.instant(name, **args)


class _NullTelemetry:
    enabled = False

    def __init__(self):
        self.metrics = NullRegistry()
        self.tracer = NullTracer()

    def span(self, name: str, **args) -> _NoopSpan:
        return _NOOP_SPAN

    def instant(self, name: str, **args) -> None:
        pass


_NULL = _NullTelemetry()
_active: Any = _NULL
_active_lock = threading.Lock()


def get() -> Any:
    """The active ``Telemetry`` (or the shared no-op when disabled).
    Hot paths may cache ``get().metrics`` handles only for the scope of
    one operation — the switch can flip between operations."""
    return _active


def enabled() -> bool:
    return _active.enabled


def metrics() -> Any:
    """The active metrics registry (Null when disabled)."""
    return _active.metrics


def tracer() -> Any:
    return _active.tracer


def span(name: str, **args):
    """``with telemetry.span("commit", worker=i):`` — no-op (one shared
    inert context manager) while disabled."""
    return _active.tracer.span(name, **args)


def instant(name: str, **args) -> None:
    _active.tracer.instant(name, **args)


def complete(name: str, t0: float, **args) -> None:
    """Record a complete event from ``t0`` (a ``now()`` stamp the
    caller took at the start of the bracketed work) to now."""
    _active.tracer.complete(name, t0, now(), **args)


def flow_start(name: str, flow_id: int, **args) -> None:
    _active.tracer.flow_start(name, flow_id, **args)


def flow_end(name: str, flow_id: int, **args) -> None:
    _active.tracer.flow_end(name, flow_id, **args)


def merge_traces(*traces: Mapping | list) -> dict:
    """Stitch per-process Chrome trace dumps into ONE timeline.

    Each argument is a ``chrome_trace()``-shaped dict (or a bare event
    list).  Two alignments happen:

    * **Clock**: ``perf_counter`` origins are arbitrary per process, so
      each trace's ``wallAnchor`` (wall + mono stamp taken at dump
      time) shifts its timestamps onto the FIRST anchored trace's
      timeline.  Traces without an anchor pass through unshifted.
    * **Pid collision**: two dumps claiming one pid (e.g. a tracer
      dumped twice, or pid reuse across hosts) get the later dump
      remapped to a fresh synthetic pid so Perfetto renders them as
      distinct process tracks.

    Flow events ("s"/"f") survive untouched — their ids were minted
    process-unique — so client→server arrows span process boundaries
    in the merged view."""
    merged: list[dict] = []
    used_pids: set[int] = set()
    base_offset: float | None = None  # wall_s - mono_s of first anchor
    for t in traces:
        if isinstance(t, Mapping):
            events = list(t.get("traceEvents", []))
            anchor = t.get("wallAnchor")
        else:
            events, anchor = list(t), None
        shift_us = 0.0
        if anchor is not None:
            offset = float(anchor["wall_s"]) - float(anchor["mono_s"])
            if base_offset is None:
                base_offset = offset
            shift_us = (offset - base_offset) * 1e6
        pids = sorted({e["pid"] for e in events if "pid" in e})
        remap: dict[int, int] = {}
        for p in pids:
            q = p
            while q in used_pids:
                q += 1_000_000  # synthetic pid for the colliding dump
            remap[p] = q
            used_pids.add(q)
        for e in events:
            if shift_us and "ts" in e:
                e = {**e, "ts": e["ts"] + shift_us}
            p = e.get("pid")
            if p is not None and remap.get(p) != p:
                e = {**e, "pid": remap[p]}
            merged.append(e)
    merged.sort(key=lambda e: (0 if e.get("ph") == "M" else 1,
                               e.get("ts", 0.0)))
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


def load_device_trace(path: str, wall_s: float | None = None) -> dict:
    """Load an XLA device-profiler Chrome trace (the
    ``*.trace.json.gz`` a ``jax.profiler`` capture writes) into a
    ``merge_traces``-compatible dict.

    Device timestamps are microseconds RELATIVE to ``start_trace``, not
    a wall or monotonic clock, so alignment needs the wall time of the
    capture start: ``profiling.profiler_trace`` drops it as
    ``wall_anchor.json`` next to the capture, and this loader finds it
    by walking up from ``path`` (or takes it explicitly via
    ``wall_s``).  The synthesized ``wallAnchor`` sets ``mono_s=0.0`` —
    the trace's own zero — so ``merge_traces``' shift formula lands
    device events on the host tracer's monotonic timeline.  Without an
    anchor the trace passes through unshifted (still mergeable, just
    not aligned)."""
    import gzip

    p = os.fspath(path)
    opener = gzip.open if p.endswith(".gz") else open
    with opener(p, "rt") as f:
        raw = json.load(f)
    events = (raw.get("traceEvents", [])
              if isinstance(raw, Mapping) else list(raw))
    if wall_s is None:
        probe = os.path.dirname(os.path.abspath(p))
        for _ in range(8):
            cand = os.path.join(probe, "wall_anchor.json")
            if os.path.exists(cand):
                with open(cand) as f:
                    wall_s = json.load(f)["wall_s"]
                break
            parent = os.path.dirname(probe)
            if parent == probe:
                break
            probe = parent
    out: dict = {"traceEvents": events, "displayTimeUnit": "ms"}
    if wall_s is not None:
        out["wallAnchor"] = {"wall_s": float(wall_s), "mono_s": 0.0}
    return out


def enable(ring_capacity: int = 65536,
           telemetry: Telemetry | None = None) -> Telemetry:
    """Install (and return) the global ``Telemetry``.  Idempotent-ish:
    enabling while enabled replaces the active instance (pass an
    existing ``Telemetry`` to install a pre-built one).  NOTE —
    compile-event counters are recorded at program TRACE time, so
    enable telemetry before constructing the engine/trainer whose
    compiles you want counted."""
    global _active
    with _active_lock:
        tel = telemetry if telemetry is not None else Telemetry(
            tracer=Tracer(capacity=ring_capacity))
        _active = tel
    return tel


def disable() -> None:
    """Restore the no-op fast path (stops the /metrics thread if the
    active registry started one).  Existing handles into the old
    registry stay valid — they just stop being globally visible."""
    global _active
    with _active_lock:
        old, _active = _active, _NULL
    if isinstance(getattr(old, "metrics", None), MetricsRegistry):
        old.metrics.stop_serving()


# -- SLO watchdog ------------------------------------------------------

#: ``signal -> (degraded_at, critical_at)`` — inclusive lower bounds;
#: a signal at/above ``degraded_at`` degrades the verdict, at/above
#: ``critical_at`` makes it critical.  Signals with no samples in the
#: registry are skipped (absence of traffic is not an outage).
DEFAULT_SLO_THRESHOLDS: dict[str, tuple[float, float]] = {
    "staleness_p99": (16.0, 64.0),        # commits of center drift
    "retry_rate": (0.5, 2.0),             # client retries per commit
    "shed_rate": (0.05, 0.25),            # sheds per submitted request
    "queue_depth": (64.0, 256.0),         # queued requests, all buckets
    "ttft_p95_s": (1.0, 10.0),            # seconds to first token
    "ttft_p99": (2.0, 20.0),              # tail seconds to first token
    "inter_token_p99": (0.25, 2.5),       # tail decode gap, seconds
    "idle_worker_fraction": (0.34, 0.75),  # silent / registered
    "ps_lock_wait": (0.005, 0.05),        # lock-wait s / shard commit
    "failover_rate": (0.05, 0.5),         # gateway failovers / request
    "leader_failover_rate": (0.05, 0.5),  # leader deaths / upstream
    "prefix_hit_rate": (0.10, 0.01),      # prefix-cache hits / lookup
    "ps_standby_lag": (32.0, 256.0),      # commit-log entries behind
    "preemption_rate": (0.25, 2.0),       # preemptions per request
    "spec_accept_rate": (0.20, 0.05),     # accepted / proposed tokens
    "mfu_gap": (0.5, 0.9),                # 1 - observed/roofline MFU
}

#: Signals where LOW is bad: the comparison inverts (breach at/below
#: the threshold) and a threshold pair must satisfy
#: ``degraded_at >= critical_at``.  A collapsed prefix hit rate on a
#: shared-prompt workload means admissions silently pay full prefill
#: again (store thrash, post-swap cold start, or misrouted affinity).
#: A collapsed speculative accept rate means every engine step pays
#: the proposer AND the wide verify for baseline-or-worse throughput
#: — the workload stopped matching the proposer (turn speculation
#: off, shrink k, or switch proposers).
LOWER_IS_WORSE_SLO_SIGNALS = frozenset({"prefix_hit_rate",
                                        "spec_accept_rate"})


def _merged_percentile(registry, name: str, q: float) -> float | None:
    """Bucket-resolution percentile over EVERY histogram instance named
    ``name`` (all label sets merged); None when there are no samples.
    Instances of one name share bucket edges by construction."""
    snaps = [m.snapshot() for _, m in registry.collect(name)]
    snaps = [s for s in snaps if s.get("count")]
    if not snaps:
        return None
    total = sum(s["count"] for s in snaps)
    need = q * total
    for edge in sorted(snaps[0]["buckets"]):
        if sum(s["buckets"].get(edge, 0) for s in snaps) >= need:
            return float(edge)
    return float(max(s["max"] for s in snaps))


class SLOWatchdog:
    """Declarative health evaluator over a ``MetricsRegistry``.

    The signals (PS staleness p99, client retry rate, serving shed
    rate, queue depth, TTFT p95/p99, inter-token p99, idle-worker
    fraction, gateway
    failover rate, hier leader failover rate, prefix hit rate, PS
    standby replication lag,
    KV-page preemption rate, speculative accept rate, mesh-round MFU
    gap) are computed
    from the registry's live metrics and compared against ``(degraded_at, critical_at)``
    thresholds — inverted for ``LOWER_IS_WORSE_SLO_SIGNALS``, where a
    LOW value breaches; the worst breach decides
    the ``ok`` / ``degraded`` / ``critical`` state.  ``evaluate()`` is
    a cheap one-shot pass (the ``/healthz`` endpoint calls it per
    request); ``start()`` adds a background thread that re-evaluates
    every ``interval_s`` and drops an ``slo_state`` instant on the
    trace (plus a flight-recorder event) whenever the state changes.

    ``sustain_secs > 0`` arms hysteresis: a state TRANSITION (in
    either direction — breach and recovery alike) must hold for that
    long across consecutive evaluations before it commits; a single
    noisy sample can no longer flip the state, which is what lets the
    ``Autoscaler`` act on transitions without flapping.  The default
    ``sustain_secs=0`` preserves the original edge-trigger exactly.
    Each verdict carries both the committed ``state`` and the
    instantaneous ``raw_state``.
    """

    def __init__(self, registry,
                 thresholds: Mapping[str, tuple] | None = None,
                 interval_s: float = 1.0,
                 sustain_secs: float = 0.0):
        self.registry = registry
        self.thresholds = dict(DEFAULT_SLO_THRESHOLDS)
        if thresholds:
            for k, pair in thresholds.items():
                if k not in DEFAULT_SLO_THRESHOLDS:
                    raise ValueError(
                        f"unknown SLO signal {k!r}; expected one of "
                        f"{sorted(DEFAULT_SLO_THRESHOLDS)}")
                d, c = float(pair[0]), float(pair[1])
                if k in LOWER_IS_WORSE_SLO_SIGNALS:
                    if d < c:
                        raise ValueError(
                            f"SLO signal {k!r} breaches LOW: "
                            f"degraded_at ({d}) must not be below "
                            f"critical_at ({c})")
                elif d > c:
                    raise ValueError(
                        f"SLO signal {k!r}: degraded_at ({d}) must "
                        f"not exceed critical_at ({c})")
                self.thresholds[k] = (d, c)
        self.interval_s = float(interval_s)
        self.sustain_secs = float(sustain_secs)
        if self.sustain_secs < 0:
            raise ValueError(
                f"sustain_secs must be >= 0, got {sustain_secs}")
        # hysteresis: the candidate state waiting out its sustain
        # window, and when it first appeared (both under _lock)
        self._pending_state: str | None = None
        self._pending_since = 0.0
        # violation accounting: clock stamp of the previous evaluate
        # (guarded-by _lock); the interval since it is attributed to
        # the state that was COMMITTED across it
        self._accrual_t: float | None = None
        self._lock = threading.Lock()
        self._last: dict = {"state": "ok", "signals": {},
                            "breaches": {}}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- signal extraction --------------------------------------------

    def signals(self) -> dict[str, float]:
        """The subset of the signals the registry has samples for."""
        r = self.registry
        out: dict[str, float] = {}
        p99 = _merged_percentile(r, "ps_commit_staleness", 0.99)
        if p99 is not None:
            out["staleness_p99"] = p99
        commits = r.sum_counter("ps_commits_total")
        retries = r.sum_counter("ps_client_retries_total")
        if commits or retries:
            out["retry_rate"] = retries / max(commits, 1.0)
        reqs = r.sum_counter("serving_requests_total")
        sheds = r.sum_counter("serving_shed_total")
        if reqs or sheds:
            out["shed_rate"] = sheds / max(reqs, 1.0)
        depth = r.collect("serving_queue_depth")
        if depth:
            out["queue_depth"] = float(sum(m.value for _, m in depth))
        p95 = _merged_percentile(r, "serving_ttft_seconds", 0.95)
        if p95 is not None:
            out["ttft_p95_s"] = p95
        tp99 = _merged_percentile(r, "serving_ttft_seconds", 0.99)
        if tp99 is not None:
            out["ttft_p99"] = tp99
        # decode-cadence tail: the disaggregation drill's headline —
        # a prefill flood on a monolithic fleet shows up here first,
        # while TTFT alone can look healthy
        itp99 = _merged_percentile(r, "serving_inter_token_seconds",
                                   0.99)
        if itp99 is not None:
            out["inter_token_p99"] = itp99
        registered = sum(m.value for _, m
                         in r.collect("ps_registered_workers"))
        if registered > 0:
            idle = sum(m.value for _, m in r.collect("ps_idle_workers"))
            out["idle_worker_fraction"] = idle / registered
        shard_commits = r.sum_counter("ps_shard_commits_total")
        if shard_commits:
            # mean seconds a commit spent WAITING for its shard lock:
            # the PS contention signal — rising wait at flat commit
            # rate means workers are convoying on too few shards
            # (the autoscaler's split trigger)
            out["ps_lock_wait"] = (
                r.sum_counter("ps_lock_wait_seconds_total")
                / max(shard_commits, 1.0))
        groutes = r.sum_counter("gateway_requests_total")
        gfails = r.sum_counter("gateway_failovers_total")
        if groutes or gfails:
            # failovers per routed request: a replica flapping under
            # the gateway shows up here even while every request still
            # completes (the gateway hides the failures it absorbs)
            out["failover_rate"] = gfails / max(groutes, 1.0)
        ups = r.sum_counter("ps_upstream_commits_total")
        lfails = r.sum_counter("ps_leader_failovers_total")
        if ups or lfails:
            # workers degraded to direct-to-root mode per upstream
            # window: the aggregation tier is alive but leaking its
            # fan-in reduction — each degraded worker adds a full
            # root commit per round the tier was built to absorb
            out["leader_failover_rate"] = lfails / max(ups, 1.0)
        phits = r.sum_counter("serving_prefix_hits_total")
        pmiss = r.sum_counter("serving_prefix_misses_total")
        if phits or pmiss:
            # fraction of prefix-store lookups that reused cached KV;
            # inverted signal (see LOWER_IS_WORSE_SLO_SIGNALS) — a
            # LOW rate on a shared-prefix workload is the breach
            out["prefix_hit_rate"] = phits / max(phits + pmiss, 1.0)
        sprop = r.sum_counter("serving_spec_proposed_total")
        sacc = r.sum_counter("serving_spec_accepted_total")
        if sprop:
            # fraction of speculative proposals the target model
            # accepted; inverted signal — a LOW rate means the
            # engine burns proposer+verify work for baseline-or-
            # worse token throughput
            out["spec_accept_rate"] = sacc / max(sprop, 1.0)
        preempts = r.sum_counter("serving_preemptions_total")
        if preempts:
            # KV-page preemptions per submitted request: sustained
            # thrash means the paged pool is undersized for the
            # offered load (requests still finish — swap/recompute
            # readmission hides the churn, at a latency cost)
            out["preemption_rate"] = preempts / max(reqs, 1.0)
        obs = r.collect("mfu_observed")
        roof = r.collect("mfu_roofline")
        if obs and roof:
            # fraction of the roofline-predicted round throughput the
            # measured round is LEAVING on the table (1 - obs/roof,
            # from the driver's sampled attribution gauges).  The
            # inversion is baked into the gap itself, so thresholds
            # read the standard way: a HIGH gap is the breach — the
            # round loop regressed against its own cost model.
            o = obs[-1][1].value
            f = roof[-1][1].value
            if f > 0:
                out["mfu_gap"] = min(max(1.0 - o / f, 0.0), 1.0)
        lag = r.collect("ps_standby_lag")
        if lag:
            # how many commit-log entries the slowest PS standby is
            # behind the primary: bounds the failover data-loss window
            # in async replication mode (sync mode pins it near 0)
            out["ps_standby_lag"] = float(
                max(m.value for _, m in lag))
        return out

    # -- evaluation ---------------------------------------------------

    def evaluate(self, now_s: float | None = None) -> dict:
        """One evaluation pass.  ``now_s`` (a ``now()``-clock stamp)
        is injectable so hysteresis is unit-testable without real
        sleeps; production callers omit it."""
        sig = self.signals()
        rank = {"ok": 0, "degraded": 1, "critical": 2}
        raw, breaches = "ok", {}
        for k, v in sig.items():
            degraded_at, critical_at = self.thresholds[k]
            if k in LOWER_IS_WORSE_SLO_SIGNALS:
                level = ("critical" if v <= critical_at else
                         "degraded" if v <= degraded_at else "ok")
            else:
                level = ("critical" if v >= critical_at else
                         "degraded" if v >= degraded_at else "ok")
            if level != "ok":
                breaches[k] = {"value": v, "level": level,
                               "degraded_at": degraded_at,
                               "critical_at": critical_at}
            if rank[level] > rank[raw]:
                raw = level
        t = now() if now_s is None else float(now_s)
        with self._lock:
            prev = self._last["state"]
            # violation-minutes accrual (ISSUE 18): the time since the
            # previous evaluation was spent in the previously COMMITTED
            # state — integrate it before this pass can transition.
            # Closed out on every evaluate(), which includes registry
            # ``health()`` reads and the background loop, so
            # ``slo_violation_seconds_total{state}`` is current
            # whenever it is scraped.
            if (prev != "ok" and self._accrual_t is not None
                    and t > self._accrual_t):
                self.registry.counter(
                    "slo_violation_seconds_total",
                    state=prev).inc(t - self._accrual_t)
            self._accrual_t = t
            if raw == prev or not self.sustain_secs:
                # agreement (or edge-trigger mode): commit instantly
                # and disarm any pending transition
                state = raw
                self._pending_state = None
            elif self._pending_state != raw:
                # a NEW candidate state: arm its sustain window (a
                # candidate that changes — degraded→critical while
                # waiting — restarts the clock; it is a different
                # transition)
                state = prev
                self._pending_state = raw
                self._pending_since = t
            elif t - self._pending_since >= self.sustain_secs:
                state = raw
                self._pending_state = None
            else:
                state = prev
            verdict = {"state": state, "raw_state": raw,
                       "signals": sig, "breaches": breaches}
            self._last = verdict
        if prev != state:
            instant("slo_state", state=state,
                    breaches=sorted(breaches))
            from distkeras_tpu import flight_recorder
            flight_recorder.record("slo_state", state=state,
                                   previous=prev,
                                   breaches=sorted(breaches))
        return verdict

    @property
    def state(self) -> str:
        with self._lock:
            return self._last["state"]

    def last(self) -> dict:
        """The most recent verdict (without re-evaluating)."""
        with self._lock:
            return dict(self._last)

    # -- background loop ----------------------------------------------

    def start(self) -> "SLOWatchdog":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.evaluate()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="dkt-slo-watchdog")
        self._thread.start()
        return self

    def stop(self) -> dict:
        """Stop the background loop; returns one final evaluation."""
        if self._thread is not None:
            self._stop.set()
            self._thread.join()
            self._thread = None
        return self.evaluate()


class Autoscaler:
    """Policy loop that turns ``SLOWatchdog`` verdicts into scaling
    actions (ISSUE 14): capacity follows load instead of being
    provisioned for peak.

    Two independent domains, each driven by its own signal set and
    wired to caller-supplied verbs (pass ``None`` to disable a
    domain):

    * ``"ps"`` — a breach on any of ``ps_scale_signals``
      (``ps_lock_wait`` / ``staleness_p99`` by default: workers
      convoying on too few shards) calls ``split_shard()``; a domain
      quiet for ``idle_sustain_s`` scales back down via
      ``merge_shards()``.  ``shard_count()`` reports the current K for
      the ``min_shards``/``max_shards`` bounds — with an
      ``elastic_ps.ElasticPSGroup`` these are ``group.split(...)`` /
      ``group.merge(...)`` wrappers and the reshard happens live under
      traffic;
    * ``"gateway"`` — a breach on ``gateway_scale_signals``
      (``queue_depth`` / ``ttft_p95_s``) calls ``spawn_replica()``
      (``gateway.add_replica``, which warms weights through
      ``rolling_update``'s drain-swap-readmit plumbing before
      admitting); sustained idle calls ``drain_replica()``
      (``gateway.remove_replica``), bounded by ``min_replicas``/
      ``max_replicas`` via ``replica_count()``.

    ``cooldown_s`` throttles actions per domain (a split needs time to
    show up in the signals before the next decision); pair with the
    watchdog's ``sustain_secs`` hysteresis so one noisy sample cannot
    trigger a reshard.  EVERY decision — executed, cooldown-suppressed,
    bounds-suppressed, or failed — lands as an ``autoscale_decision``
    flight event and in ``autoscale_decisions_total`` so
    ``postmortem.py`` can replay the scaling story.

    ``decide(verdict, now_s)`` is pure (reads policy state, mutates
    nothing) — the decision table is unit-testable without servers;
    ``step()`` executes and advances state; ``start()`` runs ``step``
    on a daemon thread every ``interval_s``.
    """

    def __init__(self, watchdog: SLOWatchdog, *,
                 split_shard=None, merge_shards=None,
                 spawn_replica=None, drain_replica=None,
                 shard_count=None, replica_count=None,
                 min_shards: int = 1, max_shards: int = 8,
                 min_replicas: int = 1, max_replicas: int = 8,
                 cooldown_s: float = 30.0,
                 idle_sustain_s: float = 60.0,
                 interval_s: float = 1.0,
                 ps_scale_signals=("ps_lock_wait", "staleness_p99"),
                 gateway_scale_signals=("queue_depth", "ttft_p95_s"),
                 busy=None):
        for name, sigs in (("ps_scale_signals", ps_scale_signals),
                           ("gateway_scale_signals",
                            gateway_scale_signals)):
            unknown = set(sigs) - set(DEFAULT_SLO_THRESHOLDS)
            if unknown:
                raise ValueError(
                    f"{name} names unknown SLO signal(s) "
                    f"{sorted(unknown)}; expected a subset of "
                    f"{sorted(DEFAULT_SLO_THRESHOLDS)}")
        if (split_shard is None) != (shard_count is None):
            raise ValueError(
                "split_shard and shard_count come as a pair (the "
                "bounds check needs the live K)")
        if (spawn_replica is None) != (replica_count is None):
            raise ValueError(
                "spawn_replica and replica_count come as a pair (the "
                "bounds check needs the live replica count)")
        self.watchdog = watchdog
        self.split_shard = split_shard
        self.merge_shards = merge_shards
        self.spawn_replica = spawn_replica
        self.drain_replica = drain_replica
        self.shard_count = shard_count
        self.replica_count = replica_count
        self.min_shards = int(min_shards)
        self.max_shards = int(max_shards)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.cooldown_s = float(cooldown_s)
        self.idle_sustain_s = float(idle_sustain_s)
        self.interval_s = float(interval_s)
        self.ps_scale_signals = tuple(ps_scale_signals)
        self.gateway_scale_signals = tuple(gateway_scale_signals)
        # busy-guard (ISSUE 18 fix): a zero-arg callable; truthy means
        # a rolling_update / live migration is mid-flight and verbs
        # must NOT interleave with it.  ``step`` defers every executed
        # decision (reason="deferred: busy", counted in
        # ``autoscale_deferred_total{domain}``) and retries next tick —
        # no cooldown is started, so the deferral costs one interval,
        # not a cooldown window.
        self.busy = busy
        # per-domain policy state: last time the domain's signals were
        # in breach (idle tracking) and last time an action executed
        # (cooldown).  Seeded "now" lazily on the first step so a
        # fresh autoscaler neither scales down instantly (idle clock
        # starts at construction) nor stalls the first scale-up.
        self._last_breach: dict[str, float] = {}
        self._last_action: dict[str, float] = {}
        self._started_at: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- the decision table (pure) ------------------------------------

    def _domain_decision(self, domain: str, breached: dict,
                         now_s: float, count, lo: int, hi: int,
                         up: str, down: str,
                         can_down: bool) -> dict | None:
        """One domain's verdict row: scale up on breach, down on
        sustained quiet, else nothing (None)."""
        last_action = self._last_action.get(domain)
        cooling = (last_action is not None
                   and now_s - last_action < self.cooldown_s)
        n = int(count())
        if breached:
            signal, info = next(iter(sorted(breached.items())))
            d = {"domain": domain, "action": up, "signal": signal,
                 "value": info["value"], "level": info["level"],
                 "count": n, "executed": False, "reason": None}
            if cooling:
                d["reason"] = "cooldown"
            elif n >= hi:
                d["reason"] = "bounds"
            else:
                d["executed"] = True
            return d
        quiet_since = self._last_breach.get(
            domain, self._started_at if self._started_at is not None
            else now_s)
        if (can_down and n > lo
                and now_s - quiet_since >= self.idle_sustain_s):
            d = {"domain": domain, "action": down, "signal": None,
                 "value": None, "level": "ok", "count": n,
                 "executed": False, "reason": None}
            if cooling:
                d["reason"] = "cooldown"
            else:
                d["executed"] = True
            return d
        return None

    def decide(self, verdict: dict,
               now_s: float | None = None) -> list[dict]:
        """The decisions ``step`` WOULD take on ``verdict`` — pure, so
        the breach→action / cooldown / bounds table is testable with
        hand-built verdicts and clocks."""
        t = now() if now_s is None else float(now_s)
        breaches = verdict.get("breaches", {})
        out = []
        if self.split_shard is not None:
            d = self._domain_decision(
                "ps",
                {k: v for k, v in breaches.items()
                 if k in self.ps_scale_signals},
                t, self.shard_count, self.min_shards,
                self.max_shards, "split", "merge",
                self.merge_shards is not None)
            if d is not None:
                out.append(d)
        if self.spawn_replica is not None:
            d = self._domain_decision(
                "gateway",
                {k: v for k, v in breaches.items()
                 if k in self.gateway_scale_signals},
                t, self.replica_count, self.min_replicas,
                self.max_replicas, "spawn", "drain",
                self.drain_replica is not None)
            if d is not None:
                out.append(d)
        return out

    # -- execution ----------------------------------------------------

    _VERBS = {"split": "split_shard", "merge": "merge_shards",
              "spawn": "spawn_replica", "drain": "drain_replica"}

    def step(self, verdict: dict | None = None,
             now_s: float | None = None) -> list[dict]:
        """One policy tick: evaluate (unless a verdict is injected),
        decide, execute, and record — every decision becomes an
        ``autoscale_decision`` flight event and an
        ``autoscale_decisions_total`` count, suppressed ones
        included."""
        from distkeras_tpu import flight_recorder

        t = now() if now_s is None else float(now_s)
        if self._started_at is None:
            self._started_at = t
        if verdict is None:
            verdict = self.watchdog.evaluate(now_s=now_s)
        decisions = self.decide(verdict, t)
        breaches = verdict.get("breaches", {})
        for domain, sigs in (("ps", self.ps_scale_signals),
                             ("gateway", self.gateway_scale_signals)):
            if any(k in breaches for k in sigs):
                self._last_breach[domain] = t
        m = metrics()
        busy_now = (bool(decisions) and self.busy is not None
                    and bool(self.busy()))
        for d in decisions:
            if d["executed"] and busy_now:
                # a reshard / rolling update is in flight: defer rather
                # than interleave verbs with it (retry next tick)
                d["executed"] = False
                d["reason"] = "deferred: busy"
                m.counter("autoscale_deferred_total",
                          domain=d["domain"]).inc()
            if d["executed"]:
                try:
                    getattr(self, self._VERBS[d["action"]])()
                    self._last_action[d["domain"]] = t
                except Exception as e:  # the verb failed — record,
                    d["executed"] = False  # don't kill the loop
                    d["reason"] = f"error: {e!r}"
            m.counter("autoscale_decisions_total",
                      domain=d["domain"], action=d["action"]).inc()
            flight_recorder.record(
                "autoscale_decision", domain=d["domain"],
                action=d["action"], signal=d["signal"],
                value=d["value"], count=d["count"],
                executed=d["executed"], reason=d["reason"])
        return decisions

    # -- background loop ----------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.step()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="dkt-autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join()
            self._thread = None


class HistoryView(collections.abc.Mapping):
    """Trainer ``history`` as a read view over a ``MetricsRegistry``'s
    series (SURVEY.md §5 / ISSUE 2 tentpole: one bookkeeping system,
    not two).  ``view[key]`` returns a list copy of the series values;
    the Mapping ABC supplies ``get``/``in``/``keys``/``items``.
    Writers go through the registry (``Trainer._record``); ``replace``
    repopulates from a checkpointed plain dict on resume."""

    __slots__ = ("_registry",)

    def __init__(self, registry: MetricsRegistry):
        self._registry = registry

    def _series(self) -> dict[str, Series]:
        with self._registry._lock:
            items = list(self._registry._metrics.values())
        return {name: m for kind, name, _, m in items
                if kind == "series" and len(m) > 0}

    def __getitem__(self, key: str) -> list:
        got = self._series().get(key)
        if got is None:
            raise KeyError(key)
        return got.values()

    def __iter__(self) -> Iterator[str]:
        return iter(self._series())

    def __len__(self) -> int:
        return len(self._series())

    def __repr__(self) -> str:
        return f"HistoryView({dict(self)!r})"

    def replace(self, mapping: Mapping[str, list]) -> None:
        """Reset the backing series to ``mapping`` (checkpoint
        resume).  Series absent from ``mapping`` are emptied, so the
        view equals the checkpointed history exactly."""
        for name, s in self._series().items():
            if name not in mapping:
                s.replace([])
        for k, v in mapping.items():
            self._registry.series(k).replace(list(v))

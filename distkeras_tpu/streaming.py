"""Streaming inference — the reference's Kafka demo, TPU-shaped.

The reference shipped a Kafka notebook that consumed an event stream and
ran ``model.predict`` per message batch (SURVEY.md §2.1 Examples:
"Kafka streaming demo").  The TPU-native concern is different from the
Spark one: a stream hands you ragged micro-batches, and every new batch
shape costs a fresh XLA compile.  ``StreamingPredictor`` therefore runs
ONE compiled forward at a fixed ``[batch_size, ...]`` shape: rows are
buffered to micro-batches, the tail is padded up to the compiled shape
and stripped after, so a long-running stream never recompiles.

Sources are plain Python iterables (a Kafka/PubSub consumer loop, a
socket reader, a generator), so there is no broker dependency; each
yielded item is one row dict (the reference's message-with-features).
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, Mapping

import jax.numpy as jnp
import numpy as np

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.predictors import ModelPredictor
from distkeras_tpu.utils import pad_to_multiple


class StreamingPredictor(ModelPredictor):
    """Micro-batching streaming front end over the sharded predictor.

    ``predict_stream(rows)`` consumes an iterable of row dicts and
    yields the same rows with the prediction column appended, in input
    order.  Rows are flushed to the device every ``batch_size`` rows
    (one compiled shape — padded tail included), or immediately at
    end-of-stream.  ``flush_every`` bounds latency for trickling
    sources: a buffer older than that many consumed rows is flushed
    even if not full.
    """

    def __init__(self, model, variables: Mapping, *,
                 batch_size: int = 64, flush_every: int | None = None,
                 **kwargs):
        if "num_shards" in kwargs:
            raise TypeError(
                "StreamingPredictor feeds one device call at a time "
                "(num_shards is fixed to 1); use ModelPredictor for "
                "sharded offline batches")
        # Streams feed one device call at a time; keep the compiled
        # shape the micro-batch (no cross-shard chunking).
        super().__init__(model, variables, batch_size=batch_size,
                         num_shards=1, **kwargs)
        self.flush_every = flush_every

    def _flush(self, rows: list[Mapping[str, Any]]
               ) -> Iterator[Mapping[str, Any]]:
        x = np.stack([np.asarray(r[self.features_col]) for r in rows])
        n = len(x)
        x = pad_to_multiple(x, self.batch_size, axis=0)
        out = self._forward(self.variables, jnp.asarray(x))
        if isinstance(out, tuple):
            # multi-output model: one key per head, mirroring
            # ModelPredictor's column-per-head contract
            heads = [np.asarray(o)[:n] for o in out]
            for i, row in enumerate(rows):
                yield {**row, **{f"{self.output_col}_{j}": h[i]
                                 for j, h in enumerate(heads)}}
            return
        pred = np.asarray(out)[:n]
        for row, p in zip(rows, pred):
            yield {**row, self.output_col: p}

    def predict_stream(self, rows: Iterable[Mapping[str, Any]]
                       ) -> Iterator[Mapping[str, Any]]:
        flush_at = (self.batch_size if self.flush_every is None
                    else min(self.batch_size, self.flush_every))
        buf: list[Mapping[str, Any]] = []
        for row in rows:
            buf.append(row)
            if len(buf) >= flush_at:
                yield from self._flush(buf)
                buf = []
        if buf:
            yield from self._flush(buf)

    def __call__(self, rows):
        """Dataset -> batch predict (the parent's pipeline contract);
        any other iterable -> predict_stream."""
        if isinstance(rows, Dataset):
            return self.predict(rows)
        return self.predict_stream(rows)

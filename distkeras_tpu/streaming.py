"""Streaming inference — the reference's Kafka demo, TPU-shaped.

The reference shipped a Kafka notebook that consumed an event stream and
ran ``model.predict`` per message batch (SURVEY.md §2.1 Examples:
"Kafka streaming demo").  The TPU-native concern is different from the
Spark one: a stream hands you ragged micro-batches, and every new batch
shape costs a fresh XLA compile.  ``StreamingPredictor`` therefore runs
ONE compiled forward at a fixed ``[batch_size, ...]`` shape: rows are
buffered to micro-batches, the tail is padded up to the compiled shape
and stripped after, so a long-running stream never recompiles.

Sources are plain Python iterables (a Kafka/PubSub consumer loop, a
socket reader, a generator), so there is no broker dependency; each
yielded item is one row dict (the reference's message-with-features).
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, Mapping

import jax.numpy as jnp
import numpy as np

from distkeras_tpu import telemetry
from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.predictors import ModelPredictor
from distkeras_tpu.utils import pad_to_multiple


class StreamingPredictor(ModelPredictor):
    """Micro-batching streaming front end over the sharded predictor.

    ``predict_stream(rows)`` consumes an iterable of row dicts and
    yields the same rows with the prediction column appended, in input
    order.  Rows are flushed to the device every ``batch_size`` rows
    (one compiled shape — padded tail included), or immediately at
    end-of-stream.  ``flush_every`` bounds latency for trickling
    sources: a buffer older than that many consumed rows is flushed
    even if not full.
    """

    def __init__(self, model, variables: Mapping, *,
                 batch_size: int = 64, flush_every: int | None = None,
                 **kwargs):
        if "num_shards" in kwargs:
            raise TypeError(
                "StreamingPredictor feeds one device call at a time "
                "(num_shards is fixed to 1); use ModelPredictor for "
                "sharded offline batches")
        # Streams feed one device call at a time; keep the compiled
        # shape the micro-batch (no cross-shard chunking).
        super().__init__(model, variables, batch_size=batch_size,
                         num_shards=1, **kwargs)
        self.flush_every = flush_every

    def _flush(self, rows: list[Mapping[str, Any]]
               ) -> Iterator[Mapping[str, Any]]:
        x = np.stack([np.asarray(r[self.features_col]) for r in rows])
        n = len(x)
        x = pad_to_multiple(x, self.batch_size, axis=0)
        telemetry.metrics().counter(
            "streaming_rows_total", kind="predict").inc(n)
        with telemetry.span("predict_flush", rows=n):
            out = self._forward(self.variables, jnp.asarray(x))
        if isinstance(out, tuple):
            # multi-output model: one key per head, mirroring
            # ModelPredictor's column-per-head contract
            heads = [np.asarray(o)[:n] for o in out]
            for i, row in enumerate(rows):
                yield {**row, **{f"{self.output_col}_{j}": h[i]
                                 for j, h in enumerate(heads)}}
            return
        pred = np.asarray(out)[:n]
        for row, p in zip(rows, pred):
            yield {**row, self.output_col: p}

    def predict_stream(self, rows: Iterable[Mapping[str, Any]]
                       ) -> Iterator[Mapping[str, Any]]:
        flush_at = (self.batch_size if self.flush_every is None
                    else min(self.batch_size, self.flush_every))
        buf: list[Mapping[str, Any]] = []
        for row in rows:
            buf.append(row)
            if len(buf) >= flush_at:
                yield from self._flush(buf)
                buf = []
        if buf:
            yield from self._flush(buf)

    def __call__(self, rows):
        """Dataset -> batch predict (the parent's pipeline contract);
        any other iterable -> predict_stream."""
        if isinstance(rows, Dataset):
            return self.predict(rows)
        return self.predict_stream(rows)


class StreamingGenerator:
    """Micro-batched autoregressive LM serving over ``models.generate``.

    ``generate_stream(rows)`` consumes an iterable of row dicts whose
    ``prompt_col`` holds token ids and yields the same rows with a
    ``output_col`` array of ``max_new_tokens`` generated ids appended,
    in input order.  The TPU serving concerns mirror
    ``StreamingPredictor`` — fixed compiled shapes — but a prompt
    stream is ragged on TWO axes, so rows buffer into per-prompt-length
    BUCKETS: a bucket flushes on its own when it fills to
    ``batch_size`` (full device batches, no padding waste from mixed
    lengths), and only end-of-stream/latency flushes pad — with whole
    dummy ROWS (repeats of the bucket's last row), never pad tokens
    inside a prompt, which would enter the KV cache and pollute real
    rows' attention.  One ``jax.jit`` wrapper serves every bucket;
    XLA's shape-keyed cache compiles each distinct prompt length once.
    Results are re-ordered to input order before yielding.

    ``flush_every`` bounds latency per ROW: once the oldest buffered
    row has waited through that many consumed rows, ALL partial
    buckets flush (padded) — a minority prompt length cannot be
    starved by a majority length that keeps filling its own bucket.
    Sampling (``temperature > 0``) keys each flush from ``seed`` and a
    per-stream flush counter, so replaying a stream reproduces its
    generations exactly — including on a reused instance (the counter
    resets per ``generate_stream`` call; the compile cache persists).

    A prompt that cannot fit (``len + max_new_tokens > max_len``)
    raises at CONSUME time, naming the row — not later inside a jitted
    flush where already-buffered neighbors would be lost with it.

    ``engine="continuous"`` swaps the run-to-completion bucket flushes
    for a ``serving.DecodeEngine``: a persistent slot-pool KV cache
    where an ``eos``/limit-finished row is evicted and replaced
    between steps instead of draining with its batch (PERF.md §23 —
    the measured mixed-traffic win).  Same row contract and in-order
    delivery; outputs are still fixed ``max_new_tokens`` arrays
    (``pad_id`` after ``eos_id``), and greedy results are identical to
    the bucketed mode.  ``engine_options`` passes through
    ``DecodeEngine`` knobs (``buckets``, ``steps_per_sync``,
    ``prefill_align``, ``slots``, ``queue_bound``, ``deadline``...);
    ``num_beams > 1`` stays bucketed-only.  ``flush_every`` is
    ignored: admission is per-request, so no bucket can starve a
    minority length.  Fault tolerance: a ``queue_bound`` engine's
    sheds become BACKPRESSURE inside the stream (the producer loop
    steps and resubmits), and an engine-side failure (deadline,
    poisoned request) surfaces as a ``"{output_col}_error"`` key on
    that row — tokens-so-far padded — instead of killing the stream.
    """

    def __init__(self, model, variables: Mapping, *,
                 max_new_tokens: int, batch_size: int = 8,
                 temperature: float = 0.0, top_k: int | None = None,
                 top_p: float | None = None,
                 num_beams: int = 1, length_penalty: float = 0.0,
                 seed: int = 0, prompt_col: str = "prompt",
                 output_col: str = "generated",
                 eos_id: int | None = None, pad_id: int = 0,
                 flush_every: int | None = None,
                 engine: str = "bucketed",
                 engine_options: Mapping | None = None):
        import jax

        from distkeras_tpu.models.generate import (_decode_model,
                                                   beam_search,
                                                   generate)

        # validate + normalize once (decode spelling is idempotent
        # through generate's own _decode_model)
        model = _decode_model(model)
        self.max_len = model.max_len
        # fail at construction, not inside the first jitted flush
        # (where already-buffered rows would be lost with the error)
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1; got {max_new_tokens}")
        if top_k is not None and not 1 <= top_k <= model.vocab_size:
            raise ValueError(
                f"top_k={top_k} out of range [1, {model.vocab_size}]")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p={top_p} out of range (0, 1]")
        if eos_id is not None and not (
                0 <= eos_id < model.vocab_size
                and 0 <= pad_id < model.vocab_size):
            raise ValueError(
                f"eos_id={eos_id}/pad_id={pad_id} outside vocab "
                f"[0, {model.vocab_size})")
        self.variables = dict(variables)
        self.max_new_tokens = int(max_new_tokens)
        self.batch_size = int(batch_size)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self.seed = int(seed)
        self.prompt_col = prompt_col
        self.output_col = output_col
        self.pad_id = int(pad_id)
        self.flush_every = flush_every
        if engine not in ("bucketed", "continuous"):
            raise ValueError(
                f"engine={engine!r} not one of ('bucketed', "
                "'continuous')")
        if engine == "continuous" and num_beams > 1:
            raise ValueError(
                "engine='continuous' serves single-sequence decoding; "
                "num_beams > 1 needs the bucketed run-to-completion "
                "path")
        self.engine = engine
        self.engine_options = dict(engine_options or {})
        self._model = model      # decode-mode clone; the engine's model
        self._eos_id = eos_id
        self._engine = None      # built lazily on first stream
        if num_beams < 1:
            raise ValueError(f"num_beams must be >= 1; got {num_beams}")
        if num_beams > model.vocab_size:
            raise ValueError(
                f"num_beams={num_beams} exceeds vocab_size="
                f"{model.vocab_size}")
        if length_penalty < 0:
            raise ValueError(
                f"length_penalty must be >= 0; got {length_penalty}")
        if num_beams > 1 and (temperature > 0.0 or top_k is not None
                              or top_p is not None):
            raise ValueError(
                "num_beams > 1 is deterministic beam decoding; it "
                "does not compose with temperature/top_k/top_p "
                "sampling")
        n_new, temp, top = self.max_new_tokens, self.temperature, top_k
        if num_beams > 1:
            # rng is accepted (and ignored) so both strategies share
            # one call signature; a "{output_col}_score" key is added
            self._generate = jax.jit(
                lambda v, p, rng: beam_search(
                    model, v, p, max_new_tokens=n_new,
                    num_beams=num_beams,
                    length_penalty=length_penalty,
                    eos_id=eos_id, pad_id=pad_id))
        else:
            self._generate = jax.jit(
                lambda v, p, rng: generate(model, v, p,
                                           max_new_tokens=n_new,
                                           temperature=temp,
                                           top_k=top, top_p=top_p,
                                           rng=rng, eos_id=eos_id,
                                           pad_id=pad_id))
        self.num_beams = int(num_beams)

    def _run_bucket(self, items: list, n_flush: int) -> dict:
        """Generate for one same-length bucket; -> {row_index: out}."""
        import jax

        prompts = np.stack([np.asarray(r[self.prompt_col], np.int32)
                            for _, r in items])
        t_p = prompts.shape[1]
        n = len(prompts)
        if n < self.batch_size:  # dummy-ROW padding (tail flush only)
            pad = np.repeat(prompts[-1:], self.batch_size - n, axis=0)
            prompts = np.concatenate([prompts, pad], axis=0)
        m = telemetry.metrics()
        m.counter("streaming_rows_total", kind="generate").inc(n)
        m.counter("streaming_pad_rows_total").inc(len(prompts) - n)
        rng = jax.random.fold_in(jax.random.key(self.seed), n_flush)
        with telemetry.span("bucket_flush", prompt_len=t_p, rows=n):
            out = self._generate(self.variables, jnp.asarray(prompts),
                                 rng)
        if self.num_beams > 1:
            seqs, scores = (np.asarray(out[0]), np.asarray(out[1]))
            return {i: {**row, self.output_col: seqs[j, t_p:],
                        f"{self.output_col}_score": float(scores[j])}
                    for j, (i, row) in enumerate(items)}
        full = np.asarray(out)
        return {i: {**row, self.output_col: full[j, t_p:]}
                for j, (i, row) in enumerate(items)}

    def _ensure_engine(self):
        if self._engine is None:
            from distkeras_tpu.serving import DecodeEngine

            opts = dict(self.engine_options)
            opts.setdefault("slots", self.batch_size)
            self._engine = DecodeEngine(
                self._model, self.variables,
                max_new_tokens=self.max_new_tokens,
                eos_id=self._eos_id, pad_id=self.pad_id,
                temperature=self.temperature, top_k=self.top_k,
                top_p=self.top_p, seed=self.seed, **opts)
        return self._engine

    def _continuous_stream(self, rows: Iterable[Mapping[str, Any]]
                           ) -> Iterator[Mapping[str, Any]]:
        eng = self._ensure_engine()
        eng.reset_rng()  # replaying a stream reproduces its draws
        done: dict[int, Mapping] = {}
        next_emit = 0
        rows_by_id: dict[int, Mapping] = {}

        def pad_out(res):
            row = rows_by_id.pop(res["request_id"])
            out = np.full((self.max_new_tokens,), self.pad_id,
                          np.int32)
            out[:len(res["tokens"])] = res["tokens"]
            rec = {**row, self.output_col: out}
            if "error" in res:
                # engine-side failure (deadline / poisoned request):
                # the row still flows — padded tokens-so-far plus the
                # reason — rather than one bad row killing the stream
                rec[f"{self.output_col}_error"] = res["error"]
            return rec

        from distkeras_tpu.serving import ShedError

        for i, row in enumerate(rows):
            prompt = np.asarray(row[self.prompt_col])
            if prompt.ndim != 1:
                raise ValueError(
                    f"stream row {i}: prompt must be a 1-D token-id "
                    f"array; got shape {prompt.shape}")
            while True:
                try:
                    eng.submit(prompt, request_id=i)
                    break
                except ShedError:
                    # a queue_bound engine sheds at the door; the
                    # stream is a bounded producer, so convert the
                    # shed into BACKPRESSURE — drain a step and retry
                    for res in eng.step():
                        done[res["request_id"]] = pad_out(res)
                except ValueError as e:
                    raise ValueError(f"stream row {i}: {e}") from e
            rows_by_id[i] = row
            # step while the slot pools are saturated (a queue is only
            # non-empty when every fitting slot is occupied)
            while any(p.queue for p in eng._pools):
                for res in eng.step():
                    done[res["request_id"]] = pad_out(res)
            while next_emit in done:       # restore input order
                yield done.pop(next_emit)
                next_emit += 1
        for res in eng.drain():            # graceful tail
            done[res["request_id"]] = pad_out(res)
        while next_emit in done:
            yield done.pop(next_emit)
            next_emit += 1

    def generate_stream(self, rows: Iterable[Mapping[str, Any]]
                        ) -> Iterator[Mapping[str, Any]]:
        if self.engine == "continuous":
            yield from self._continuous_stream(rows)
            return
        buckets: dict[int, list] = {}      # prompt_len -> [(i, row)]
        done: dict[int, Mapping] = {}      # row_index -> result
        next_emit = 0
        n_flush = 0   # per-stream: replay-reproducible sampling keys

        def flush(t_p):
            nonlocal n_flush
            n_flush += 1
            done.update(self._run_bucket(buckets.pop(t_p), n_flush))

        for i, row in enumerate(rows):
            prompt = np.asarray(row[self.prompt_col])
            if prompt.ndim != 1:
                raise ValueError(
                    f"stream row {i}: prompt must be a 1-D token-id "
                    f"array; got shape {prompt.shape}")
            t_p = len(prompt)
            if t_p < 1 or t_p + self.max_new_tokens > self.max_len:
                raise ValueError(
                    f"stream row {i}: prompt length {t_p} + "
                    f"max_new_tokens {self.max_new_tokens} does not "
                    f"fit max_len={self.max_len}")
            buckets.setdefault(t_p, []).append((i, row))
            if len(buckets[t_p]) >= self.batch_size:
                flush(t_p)
            # latency bound on the OLDEST buffered row (a full-bucket
            # flush of a majority length must not starve the rest)
            if (self.flush_every is not None and buckets
                    and i - min(b[0][0] for b in buckets.values()) + 1
                    >= self.flush_every):
                for t in sorted(buckets):
                    flush(t)
            while next_emit in done:       # restore input order
                yield done.pop(next_emit)
                next_emit += 1
        for t in sorted(buckets):
            flush(t)
        while next_emit in done:
            yield done.pop(next_emit)
            next_emit += 1

    __call__ = generate_stream

"""distkeras_tpu — a TPU-native distributed training framework with the
capability surface of dist-keras (see SURVEY.md): a uniform Trainer API over
data-parallel distributed optimizers (SingleTrainer, sync-DP, DOWNPOUR,
ADAG, AEASGD, EAMSGD, DynSGD), columnar ETL transformers, and distributed
batch inference — rebuilt on JAX/XLA (shard_map/pjit over a device mesh,
ICI collectives) instead of Spark executors + a TCP parameter server.
"""

from distkeras_tpu.version import __version__  # noqa: F401
from distkeras_tpu import (  # noqa: F401
    compat,
    data,
    mesh,
    models,
    ops,
    parallel,
    telemetry,
)
from distkeras_tpu.trainers import (  # noqa: F401
    ADAG,
    AEASGD,
    DOWNPOUR,
    AveragingTrainer,
    DistributedTrainer,
    DynSGD,
    EAMSGD,
    EnsembleTrainer,
    SingleTrainer,
    SyncTrainer,
    Trainer,
)
from distkeras_tpu.predictors import ModelPredictor  # noqa: F401
from distkeras_tpu.serving import DecodeEngine, ShedError  # noqa: F401
from distkeras_tpu.gateway import (  # noqa: F401
    EngineReplica,
    ReplicaServer,
    RemoteReplica,
    ServingGateway,
)
from distkeras_tpu.streaming import (  # noqa: F401
    StreamingGenerator,
    StreamingPredictor,
)
from distkeras_tpu.evaluators import (  # noqa: F401
    AccuracyEvaluator,
    BinaryClassificationEvaluator,
    ClassificationEvaluator,
    LossEvaluator,
    evaluate_model,
)

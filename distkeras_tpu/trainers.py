"""Trainer hierarchy — the framework's front door.

Mirrors the reference's ``distkeras/trainers.py`` surface (SURVEY.md §2.1):
``SingleTrainer``, ``EnsembleTrainer``/``AveragingTrainer``, and the async
parameter-server family ``DOWNPOUR`` / ``ADAG`` / ``AEASGD`` / ``EAMSGD`` /
``DynSGD`` — plus the TPU-native ``SyncTrainer`` (synchronous data
parallelism over ICI, the convergence control arm the reference lacked,
SURVEY.md §2.3).

Semantics map (reference -> rebuild):

* Spark DataFrame             -> ``distkeras_tpu.data.Dataset``
* ``num_workers`` partitions  -> slices of the device mesh's worker axis
  (``distkeras_tpu.mesh``), emulated per-device via ``vmap`` when the
  worker count exceeds the device count (Spark ``local[N]`` analogue)
* TCP pull/commit to the driver PS -> emulated commit rounds compiled
  on-mesh (``parallel.ps_emulator``) with deterministic staleness
* ``communication_window``    -> window of jitted local steps per round
* trained Keras model         -> flax variables dict (+ ``ModelSpec``)

Every trainer records ``training_time`` (as the reference's ``Trainer``
does) and a richer ``history`` (per-round losses, staleness telemetry —
SURVEY.md §5 "honest observability").
"""

from __future__ import annotations

import functools
import os
import pathlib
import queue
import threading
import time
from typing import Any, ClassVar, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu import mesh as mesh_lib
from distkeras_tpu import telemetry
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.models.core import ModelSpec
from distkeras_tpu.parallel import ps_dataplane, tensor_parallel
from distkeras_tpu.parallel.ps_emulator import make_round_fn
from distkeras_tpu.parallel.tiers import resolve_tier, tiers_with
from distkeras_tpu.parallel.update_rules import (
    AdagRule,
    DownpourRule,
    DynSGDRule,
    ElasticRule,
    UpdateRule,
)
from distkeras_tpu.workers import (
    TrainState,
    make_train_step,
    make_window_runner,
    resolve_optimizer,
)

Pytree = Any


def _resolve_spec(model) -> ModelSpec:
    if isinstance(model, ModelSpec):
        return model
    if isinstance(model, Mapping):
        return ModelSpec.from_config(model)
    raise TypeError(
        "model must be a ModelSpec or a model config dict "
        "(distkeras_tpu.models.model_config); got "
        f"{type(model).__name__}")


def _stack_batches(shard: Dataset, batch_size: int,
                   columns: Sequence[str]) -> dict[str, np.ndarray] | None:
    """Rows -> stacked batch arrays ``[num_batches, B, ...]``."""
    n = shard.num_batches(batch_size)
    if n == 0:
        return None
    out = {}
    for c in columns:
        col = shard[c][:n * batch_size]
        out[c] = col.reshape((n, batch_size) + col.shape[1:])
    return out


def _prefetch_depth() -> int:
    """Segments to load ahead of the consumer (0 disables).  Env-gated
    so the IO/compute-overlap A/B (PERF.md) and the bit-identity test
    can toggle it; prefetch never changes results, only timing."""
    return int(os.environ.get("DKT_SEGMENT_PREFETCH", "1"))


def _prefetch_iter(it, depth: int | None = None):
    """Iterate ``it`` on a daemon thread, keeping up to ``depth`` items
    built ahead of the consumer — overlaps segment IO (read / parse /
    shuffle) with the compute consuming the previous segment.  Order-
    preserving; iterator exceptions re-raise at the consumer's ``next``.
    """
    if depth is None:
        depth = _prefetch_depth()
    if depth <= 0:
        yield from it
        return
    done = object()
    q: queue.Queue = queue.Queue()
    # build tickets: the feeder may hold depth items beyond the one the
    # consumer is processing; released as the consumer moves on
    slots = threading.Semaphore(depth + 1)
    # set when the consumer abandons the generator mid-epoch (train
    # error, KeyboardInterrupt): the feeder must exit rather than block
    # in slots.acquire() forever pinning loaded segments
    cancelled = threading.Event()

    def feed():
        try:
            while True:
                slots.acquire()
                if cancelled.is_set():
                    return
                try:
                    item = next(it)
                except StopIteration:
                    q.put(done)
                    return
                q.put((item,))
        except BaseException as exc:  # surfaced on the consumer side
            q.put(exc)
            q.put(done)

    threading.Thread(target=feed, daemon=True,
                     name="dkt-segment-prefetch").start()
    try:
        while True:
            got = q.get()
            if got is done:
                return
            if isinstance(got, BaseException):
                raise got
            yield got[0]
            slots.release()
    finally:
        cancelled.set()
        slots.release()  # wake a feeder blocked on the ticket


def _epoch_segments(dataset, seed: int, stall: list | None = None):
    """One epoch as in-memory ``Dataset`` segments.

    In-memory datasets yield exactly one segment — the whole set,
    shuffled — so existing behavior is bit-identical.  A
    ``ShardedDataset`` (``data/sharded.py``) streams its shard files in
    seed-permuted order with rows shuffled per shard, so host peak
    memory is one segment being trained plus the prefetched next
    (Spark's partition streaming was the reference's equivalent,
    SURVEY.md §1 L0).

    ``stall`` (a one-element list) accumulates the seconds the CONSUMER
    spent blocked waiting for segments — the IO stall the prefetch
    thread exists to hide.  Unlike epoch wall-time it is exact, not
    noise-bound: with prefetch off it converges to the full load cost,
    with prefetch on to whatever the overlap could not hide."""
    from distkeras_tpu.data.sharded import ShardedDataset

    if isinstance(dataset, ShardedDataset):
        it = _prefetch_iter(dataset.epoch_segments(seed))
    else:
        it = iter([dataset.shuffle(seed=seed)])
    if stall is None:
        return it

    def timed():
        while True:
            t0 = telemetry.now()
            try:
                item = next(it)
            except StopIteration:
                return
            stall[0] += telemetry.now() - t0
            yield item
    return timed()


class _SegmentPrefetch:
    """One-deep background segment load for plan-driven loops (the
    emulated-PS arm, which must decide skips from metadata *before*
    touching the file).  ``queue(key, load)`` starts ``load()`` on a
    daemon thread; ``get(key, load)`` joins and returns it — or falls
    back to a synchronous ``load()`` on a key mismatch, so a wrong
    lookahead prediction costs only the overlap, never correctness.
    Load errors re-raise in ``get`` on the consumer thread."""

    def __init__(self):
        self._key = None
        self._thread: threading.Thread | None = None
        self._box: dict | None = None

    def queue(self, key, load):
        box: dict = {}

        def run():
            try:
                box["value"] = load()
            except BaseException as exc:
                box["error"] = exc

        t = threading.Thread(target=run, daemon=True,
                             name="dkt-segment-prefetch")
        t.start()
        self._key, self._thread, self._box = key, t, box

    def get(self, key, load):
        if self._thread is not None and self._key == key:
            self._thread.join()
            box = self._box
            self._key = self._thread = self._box = None
            if "error" in box:
                raise box["error"]
            return box["value"]
        return load()


def _epoch_segment_loaders(dataset, seed: int):
    """``_epoch_segments`` with the data deferred: yields ``(rows,
    load)`` so a resuming PS trainer can skip whole already-consumed
    shard files from header metadata alone."""
    from distkeras_tpu.data.sharded import ShardedDataset

    if isinstance(dataset, ShardedDataset):
        return dataset.epoch_segment_loaders(seed)
    return iter([(len(dataset),
                  lambda: dataset.shuffle(seed=seed))])


class Trainer:
    """Base trainer: owns the model spec, loss, worker optimizer, batch
    size and epoch count (the reference ``Trainer``'s fields), plus the
    trained result and timing."""

    def __init__(self, model, loss: str = "categorical_crossentropy",
                 worker_optimizer="sgd", learning_rate=None,
                 features_col: str = "features", label_col: str = "label",
                 batch_size: int = 32, num_epoch: int = 1, seed: int = 0,
                 checkpoint_dir: str | None = None,
                 profile_dir: str | None = None):
        """``learning_rate``: float, optax schedule, or a JSON-friendly
        ``{"schedule": name, **kwargs}`` dict (see
        ``workers.resolve_schedule``).  ``profile_dir`` wraps the whole
        ``train()`` in a ``jax.profiler`` trace written there (view
        with TensorBoard / xprof)."""
        self.spec = _resolve_spec(model)
        n_heads = len(self.spec.kwargs.get("outputs", ()))
        if n_heads > 1:
            # multi-output models train with one loss + label column
            # PER HEAD — validate here, not deep inside a jit trace
            if not (isinstance(loss, (list, tuple))
                    and isinstance(label_col, (list, tuple))
                    and len(loss) == n_heads
                    and len(label_col) == n_heads):
                raise ValueError(
                    f"this model has {n_heads} output heads: pass "
                    f"loss= and label_col= as sequences of {n_heads} "
                    f"entries (one loss and one label column per "
                    f"head); got loss={loss!r}, "
                    f"label_col={label_col!r}")
        elif isinstance(loss, (list, tuple)) \
                or isinstance(label_col, (list, tuple)):
            # single-head model: unwrap the length-1 sequence spelling
            # (mirrors the multi-head API), reject anything longer
            if not (isinstance(loss, (list, tuple))
                    and isinstance(label_col, (list, tuple))
                    and len(loss) == 1 and len(label_col) == 1):
                raise ValueError(
                    f"this model has one output head; loss= and "
                    f"label_col= sequences must both have exactly one "
                    f"entry (got loss={loss!r}, "
                    f"label_col={label_col!r})")
            loss, label_col = loss[0], label_col[0]
        self.model = self.spec.build()
        self.loss = loss
        self.worker_optimizer = worker_optimizer
        self.learning_rate = learning_rate
        self.features_col = features_col
        self.label_col = label_col
        self.batch_size = int(batch_size)
        self.num_epoch = int(num_epoch)
        self.seed = int(seed)
        self.checkpoint_dir = checkpoint_dir
        self.profile_dir = profile_dir
        self.training_time: float = 0.0
        # ``history`` is a read VIEW over this trainer's own metrics
        # registry (ISSUE 2: one bookkeeping system, not a second
        # hand-rolled dict): ``_record`` appends to thread-safe
        # registry series, the dict-like read surface — history[k],
        # .get, ``in`` — is unchanged.  The per-trainer registry is
        # always on (history must exist with global telemetry
        # disabled) and exportable like any other registry
        # (``trainer.metrics.write_jsonl(...)``).
        self.metrics = telemetry.MetricsRegistry()
        self.history = telemetry.HistoryView(self.metrics)
        self.trained_variables: dict | None = None

    # -- shared plumbing ---------------------------------------------------

    def _tx(self):
        return resolve_optimizer(self.worker_optimizer, self.learning_rate)

    def _init_variables(self, initial_variables=None) -> dict:
        if initial_variables is not None:
            return dict(initial_variables)
        sample = jnp.asarray(self.spec.example_input(self.batch_size))
        return self.model.init(jax.random.key(self.seed), sample)

    def _columns(self) -> list[str]:
        labels = (list(self.label_col)
                  if isinstance(self.label_col, (list, tuple))
                  else [self.label_col])
        return [self.features_col, *labels]

    def _record(self, **kwargs):
        for k, v in kwargs.items():
            self.metrics.series(k).append(v)

    def train(self, dataset: Dataset, initial_variables=None,
              resume_from: str | None = None,
              eval_dataset: Dataset | None = None) -> dict:
        """Train on ``dataset``.  ``resume_from`` continues from a
        checkpoint written by a previous run with ``checkpoint_dir``
        set (same trainer configuration + dataset ⇒ bitwise-identical
        continuation; see distkeras_tpu.checkpoint).  ``eval_dataset``
        records ``history['eval_accuracy']`` at every epoch boundary
        (the reference notebooks' accuracy-vs-trainer comparison,
        done in-framework)."""
        from distkeras_tpu.profiling import profiler_trace

        if eval_dataset is not None and isinstance(
                self.label_col, (list, tuple)):
            raise NotImplementedError(
                "per-epoch eval_dataset= supports single-head models "
                "(one prediction column against one label column); "
                "evaluate a multi-output model per head after "
                "training via ModelPredictor + ops.metrics")
        self._eval_dataset = eval_dataset
        start = time.time()
        try:
            with profiler_trace(self.profile_dir), \
                    telemetry.span("train",
                                   trainer=type(self).__name__):
                return self._train(dataset, initial_variables,
                                   resume_from)
        finally:
            self.training_time = time.time() - start

    def _eval_epoch(self, variables) -> None:
        """Epoch-boundary hook: accuracy on ``eval_dataset`` if set.
        The predictor (and its jitted forward) is built once and reused
        across epochs — only ``.variables`` is swapped."""
        if getattr(self, "_eval_dataset", None) is None:
            return
        from distkeras_tpu.evaluators import metrics_from_logits
        from distkeras_tpu.predictors import ModelPredictor

        host_vars = jax.tree_util.tree_map(mesh_lib.fetch, variables)
        predictor = getattr(self, "_eval_predictor", None)
        if predictor is None:
            predictor = ModelPredictor(
                self.model, host_vars, features_col=self.features_col,
                output="logits", batch_size=max(self.batch_size, 256))
            self._eval_predictor = predictor
        predictor.variables = host_vars
        scored = predictor.predict(self._eval_dataset)
        m = metrics_from_logits(scored["prediction"],
                                self._eval_dataset[self.label_col])
        self._record(eval_accuracy=m["accuracy"])

    def _train(self, dataset, initial_variables, resume_from=None):
        raise NotImplementedError

    # -- checkpoint plumbing ----------------------------------------------

    def _maybe_save(self, state, cursor: dict):
        # The full history rides in every checkpoint so a resumed run
        # reproduces the uninterrupted history exactly.  Cost grows with
        # rounds trained (O(rounds) per save); for very long runs with
        # frequent mid-epoch saves, an append-only side log would be
        # cheaper — revisit if save latency ever shows up in profiles.
        if self.checkpoint_dir is not None:
            from distkeras_tpu import checkpoint as ckpt

            # materialize the registry view: the cursor is JSON-encoded
            cursor = {**cursor, "history": dict(self.history)}
            if getattr(self, "_sharded_ckpt", False):
                # multi-host sharded state: every process writes only
                # its own shards (orbax)
                ckpt.save_sharded(self.checkpoint_dir, state, cursor)
                # one layout per dir (see the mirror-image cleanup in
                # the msgpack branch)
                if jax.process_index() == 0:
                    (pathlib.Path(self.checkpoint_dir) /
                     ckpt.LATEST).unlink(missing_ok=True)
            else:
                ckpt.save_checkpoint(self.checkpoint_dir, state,
                                     cursor)
                # one layout per dir: a stale sharded checkpoint left
                # from an earlier multi-host run would otherwise shadow
                # this (newer) msgpack save at the next resume
                if ckpt.has_sharded(self.checkpoint_dir) and \
                        jax.process_index() == 0:
                    import shutil

                    shutil.rmtree(
                        pathlib.Path(self.checkpoint_dir) /
                        ckpt.SHARDED, ignore_errors=True)

    def _restore_history(self, cursor: dict) -> dict:
        """Pop the checkpointed history into the registry-backed view
        (the view object stays; its backing series are reset)."""
        self.history.replace({
            k: list(v) for k, v in cursor.pop("history", {}).items()})
        return cursor

    def _maybe_resume(self, resume_from, state_template):
        """Returns (state, cursor) — (template, {}) when not resuming."""
        if resume_from is None:
            return state_template, {}
        from distkeras_tpu import checkpoint as ckpt

        state, cursor = ckpt.load_checkpoint(resume_from, state_template)
        return state, self._restore_history(cursor)


class SingleTrainer(Trainer):
    """Sequential baseline: one worker, whole dataset (reference
    ``SingleTrainer``: coalesce to one partition, SURVEY.md §3.1).  The
    epoch is scanned on-device in chunks, not stepped from Python."""

    SCAN_CHUNK = 64  # batches per device call (host loop granularity)

    def _train(self, dataset, initial_variables, resume_from=None):
        tx = self._tx()
        variables = self._init_variables(initial_variables)
        state = TrainState.create(variables, tx,
                                  jax.random.key(self.seed + 1))
        state, cursor = self._maybe_resume(resume_from, state)
        start_epoch = int(cursor.get("epoch", 0))
        step = make_train_step(self.model, self.loss, tx,
                               self.features_col, self.label_col)
        run_chunk = jax.jit(make_window_runner(step))

        for epoch in range(start_epoch, self.num_epoch):
            t_epoch = telemetry.now()
            losses = []
            stall = [0.0]
            for segment in _epoch_segments(dataset, self.seed + epoch,
                                           stall):
                stacked = _stack_batches(segment, self.batch_size,
                                         self._columns())
                if stacked is None:
                    # a shard file smaller than one batch: dropped like
                    # any other tail remainder (never silently for the
                    # whole epoch — see the check below)
                    continue
                n = len(next(iter(stacked.values())))
                for lo in range(0, n, self.SCAN_CHUNK):
                    chunk = {k: jnp.asarray(v[lo:lo + self.SCAN_CHUNK])
                             for k, v in stacked.items()}
                    state, metrics = run_chunk(state, chunk)
                    losses.append(np.asarray(metrics["loss"]))
            if not losses:
                raise ValueError("dataset smaller than one batch")
            epoch_loss = float(np.concatenate(losses).mean())
            self._record(epoch_loss=epoch_loss,
                         segment_stall_s=round(stall[0], 4))
            self._eval_epoch(state.variables())
            self._maybe_save(state, {"epoch": epoch + 1})
            telemetry.complete("epoch", t_epoch, epoch=epoch,
                               trainer=type(self).__name__)
        self.trained_variables = state.variables()
        return self.trained_variables


class SyncTrainer(Trainer):
    """Synchronous data parallelism over the mesh — one jitted step with
    the global batch sharded across the worker axis; XLA inserts the ICI
    all-reduce on the gradients (SURVEY.md §2.3 "sync DP via pjit is the
    natural TPU baseline").  Not in the reference; it is the convergence
    control arm for the async family."""

    SCAN_CHUNK = 32

    def __init__(self, model, num_workers: int | None = None,
                 model_parallel: int = 1, tp_rules=None,
                 pipeline_stages: int = 1,
                 pipeline_microbatches: int | None = None, **kwargs):
        """``model_parallel`` > 1 adds a tensor-parallel dimension: the
        mesh becomes ``(workers, model)`` and parameters are sharded
        over the ``model`` axis per ``parallel.tensor_parallel`` rules
        (Megatron-style for ``transformer_lm``/``mlp``; pass
        ``tp_rules`` for custom models).  Pure GSPMD — same numerics as
        ``model_parallel=1``, XLA inserts the collectives.

        ``pipeline_stages`` > 1 instead runs dp x pp over a
        ``(workers, stage)`` mesh: the model must be a
        ``transformer_lm`` whose ``num_layers`` divides into the stage
        count — its layer stack (``scan_blocks`` form) is sharded one
        slice per stage and driven through the GPipe microbatch
        schedule (``parallel.pipeline``).  ``pipeline_microbatches``
        defaults to 2 x stages (bubble fraction (S-1)/(M+S-1)).
        Mutually exclusive with ``model_parallel``."""
        super().__init__(model, **kwargs)
        self.num_workers = num_workers
        self.model_parallel = int(model_parallel)
        if self.model_parallel < 1:
            raise ValueError(
                f"model_parallel must be >= 1, got {model_parallel}")
        self.tp_rules = tp_rules
        self.pipeline_stages = int(pipeline_stages)
        if self.pipeline_stages < 1:
            raise ValueError(
                f"pipeline_stages must be >= 1, got {pipeline_stages}")
        if self.pipeline_stages > 1 and self.model_parallel > 1:
            raise ValueError(
                "pipeline_stages and model_parallel are mutually "
                "exclusive (pp x tp composition is not implemented)")
        self.pipeline_microbatches = (
            None if pipeline_microbatches is None
            else int(pipeline_microbatches))

    def _train(self, dataset, initial_variables, resume_from=None):
        if self.pipeline_stages > 1:
            return self._train_pipeline(dataset, initial_variables,
                                        resume_from)
        return self._train_dp(dataset, initial_variables, resume_from)

    def _train_pipeline(self, dataset, initial_variables, resume_from):
        """dp x pp: see ``parallel.pipeline.make_pp_train_step``."""
        from distkeras_tpu.models.core import ModelSpec
        from distkeras_tpu.parallel import pipeline as pp
        from distkeras_tpu.ops.losses import resolve_loss

        if jax.process_count() > 1:
            raise NotImplementedError(
                "pipeline_stages > 1 is single-process for now (the "
                "stage axis must not cross hosts anyway; use more "
                "workers per host)")
        stages = self.pipeline_stages
        if self.spec.family != "transformer_lm":
            raise ValueError(
                f"pipeline_stages > 1 supports the transformer_lm "
                f"family (homogeneous blocks), got "
                f"{self.spec.family!r}")
        kwargs = dict(self.spec.kwargs)
        if kwargs.get("num_experts"):
            raise ValueError(
                "pipeline_stages > 1 supports the dense-FFN "
                "transformer (MoE blocks are not homogeneous across "
                "the stack's expert dispatch)")
        n_layers = kwargs.get("num_layers", 4)
        if n_layers % stages:
            raise ValueError(
                f"num_layers={n_layers} does not divide into "
                f"{stages} stages")
        kwargs["scan_blocks"] = True
        spec = ModelSpec(family="transformer_lm", kwargs=kwargs,
                         input_shape=self.spec.input_shape,
                         input_dtype=self.spec.input_dtype)
        model = spec.build()

        devices = jax.devices()
        num_workers = self.num_workers or max(
            1, len(devices) // stages)
        if num_workers * stages > len(devices):
            raise ValueError(
                f"pipeline_stages={stages} with {num_workers} workers "
                f"needs {num_workers * stages} devices, have "
                f"{len(devices)}")
        mesh = Mesh(
            np.asarray(devices[:num_workers * stages]).reshape(
                num_workers, stages),
            (mesh_lib.WORKER_AXIS, pp.STAGE_AXIS))
        microbatches = self.pipeline_microbatches or 2 * stages
        if self.batch_size % microbatches:
            raise ValueError(
                f"per-worker batch {self.batch_size} not divisible "
                f"into {microbatches} microbatches")

        tx = self._tx()
        if initial_variables is not None:
            variables = dict(initial_variables)
        else:
            sample = jnp.asarray(spec.example_input(self.batch_size))
            variables = model.init(jax.random.key(self.seed), sample)
        state = TrainState.create(variables, tx,
                                  jax.random.key(self.seed + 1))
        state, cursor = self._maybe_resume(resume_from, state)
        specs = pp.lm_state_specs(state)
        state_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))
        state = jax.device_put(state, state_shardings)
        step = pp.make_pp_train_step(
            model, resolve_loss(self.loss), tx, mesh,
            num_microbatches=microbatches,
            workers_axis=mesh_lib.WORKER_AXIS,
            features_col=self.features_col, label_col=self.label_col)
        run_chunk = jax.jit(make_window_runner(step))

        global_batch = self.batch_size * num_workers
        batch_sharded = NamedSharding(
            mesh, P(None, mesh_lib.WORKER_AXIS))
        start_epoch = int(cursor.get("epoch", 0))
        self.num_workers = num_workers
        for epoch in range(start_epoch, self.num_epoch):
            t_epoch = telemetry.now()
            pending = []
            stall = [0.0]
            for segment in _epoch_segments(dataset, self.seed + epoch,
                                           stall):
                stacked = _stack_batches(segment, global_batch,
                                         self._columns())
                if stacked is None:
                    continue
                n = len(next(iter(stacked.values())))
                for lo in range(0, n, self.SCAN_CHUNK):
                    local = {k: v[lo:lo + self.SCAN_CHUNK]
                             for k, v in stacked.items()}
                    chunk = jax.device_put(local, batch_sharded)
                    state, metrics = run_chunk(state, chunk)
                    pending.append(metrics["loss"])
            if not pending:
                raise ValueError(
                    f"dataset smaller than one global batch "
                    f"({global_batch})")
            losses = [mesh_lib.fetch(x) for x in pending]
            self._record(
                epoch_loss=float(np.concatenate(losses).mean()),
                segment_stall_s=round(stall[0], 4))
            self._eval_epoch(state.variables())
            self._maybe_save(state, {"epoch": epoch + 1})
            telemetry.complete("epoch", t_epoch, epoch=epoch,
                               trainer=type(self).__name__)
        self.trained_variables = state.variables()
        return self.trained_variables

    def _train_dp(self, dataset, initial_variables, resume_from=None):
        devices = jax.devices()
        mp = self.model_parallel
        num_workers = self.num_workers or max(1, len(devices) // mp)
        use_mesh = len(devices) >= num_workers * mp > 1
        if mp > 1 and not use_mesh:
            raise ValueError(
                f"model_parallel={mp} with {num_workers} workers needs "
                f"{num_workers * mp} devices, have {len(devices)}")
        # Multi-host TP state is not fully addressable: switch
        # _maybe_save to the per-shard orbax layout (checkpoint.py
        # save_sharded) instead of the single-file msgpack fetch.
        self._sharded_ckpt = mp > 1 and jax.process_count() > 1
        global_batch = self.batch_size * num_workers
        # Multi-host: every process runs this same program; each holds
        # only its rows of the (identically generated) global dataset and
        # contributes them to the globally-sharded batch.
        pc = jax.process_count()
        if pc > 1:
            if not use_mesh or global_batch % pc:
                raise ValueError(
                    f"multi-host SyncTrainer needs a mesh and a global "
                    f"batch divisible by process count ({pc})")
        local_batch = global_batch // pc

        tx = self._tx()
        variables = self._init_variables(initial_variables)
        state = TrainState.create(variables, tx,
                                  jax.random.key(self.seed + 1))
        from distkeras_tpu import checkpoint as ckpt_mod

        resume_sharded = (resume_from is not None
                          and ckpt_mod.has_sharded(resume_from))
        cursor: dict = {}
        if not resume_sharded:
            state, cursor = self._maybe_resume(resume_from, state)
        step = make_train_step(self.model, self.loss, tx,
                               self.features_col, self.label_col)
        run_chunk = make_window_runner(step)

        if use_mesh:
            m = mesh_lib.create_mesh(num_workers, model_parallel=mp,
                                     devices=devices)
            rep = NamedSharding(m, P())
            # [chunk, B_global, ...]: global batch axis sharded across
            # workers — both the jit contract and the host-side chunk
            # assembly below use this one sharding.
            batch_sharded = NamedSharding(
                m, P(None, mesh_lib.WORKER_AXIS))
            if mp > 1:
                rules = (self.tp_rules if self.tp_rules is not None
                         else tensor_parallel.rules_for(self.spec.family))
                state_sharding = tensor_parallel.tree_shardings(
                    m, state, rules)
            else:
                state_sharding = rep
            state = mesh_lib.global_batch_from_local(state_sharding,
                                                     state)
            if resume_sharded:
                # sharded (orbax) checkpoints restore INTO the mesh
                # shardings — each process reads only its own shards
                state, cursor = ckpt_mod.load_sharded(resume_from,
                                                      state)
                cursor = self._restore_history(cursor)
            run_chunk = jax.jit(
                run_chunk,
                in_shardings=(state_sharding, batch_sharded),
                out_shardings=(state_sharding, rep))
        elif resume_sharded:
            raise ValueError(
                f"{resume_from!r} holds a sharded checkpoint but this "
                f"run has no mesh to restore it onto")
        else:
            run_chunk = jax.jit(run_chunk)

        start_epoch = int(cursor.get("epoch", 0))
        self.num_workers = num_workers
        for epoch in range(start_epoch, self.num_epoch):
            t_epoch = telemetry.now()
            pending = []
            stall = [0.0]
            for segment in _epoch_segments(dataset, self.seed + epoch,
                                           stall):
                shard = mesh_lib.process_shard(segment)
                stacked = _stack_batches(shard, local_batch,
                                         self._columns())
                if stacked is None:
                    # shard file smaller than one global batch: tail
                    # remainder; the epoch-level emptiness check below
                    # keeps it from passing silently
                    continue
                n = len(next(iter(stacked.values())))
                for lo in range(0, n, self.SCAN_CHUNK):
                    local = {k: v[lo:lo + self.SCAN_CHUNK]
                             for k, v in stacked.items()}
                    if use_mesh:
                        chunk = mesh_lib.global_batch_from_local(
                            batch_sharded, local)
                    else:
                        chunk = {k: jnp.asarray(v)
                                 for k, v in local.items()}
                    state, metrics = run_chunk(state, chunk)
                    # keep the device handle; fetching here would block
                    # next chunk's host assembly behind device compute
                    pending.append(metrics["loss"])
            if not pending:
                raise ValueError(
                    f"dataset smaller than one global batch "
                    f"({global_batch})")
            losses = [mesh_lib.fetch(x) for x in pending]
            self._record(
                epoch_loss=float(np.concatenate(losses).mean()),
                segment_stall_s=round(stall[0], 4))
            self._eval_epoch(state.variables())
            self._maybe_save(state, {"epoch": epoch + 1})
            telemetry.complete("epoch", t_epoch, epoch=epoch,
                               trainer=type(self).__name__)
        self.trained_variables = state.variables()
        return self.trained_variables


class DistributedTrainer(Trainer):
    """Base for the async PS family (reference ``DistributedTrainer`` /
    ``AsynchronousDistributedTrainer``): ``num_workers`` +
    ``communication_window``, worker placement on the mesh, emulated
    commit rounds."""

    #: effective per-round lr (configured lr x family amplification)
    #: above which the staleness families measurably degrade on the
    #: PARITY.md calibration task (MNIST MLP, sgd workers): the
    #: collapsing configs sit at 0.2-0.8, every law-scaled PARITY row
    #: at <= 0.1.  A heuristic guardrail, not a convergence proof.
    _LR_LAW_EFFECTIVE_MAX = 0.1

    def __init__(self, model, num_workers: int = 2,
                 communication_window: int = 5,
                 fidelity: str = "faithful",
                 transport: str = "inprocess",
                 checkpoint_every_rounds: int | None = None,
                 max_worker_failures: int = 0,
                 worker_retries: int = 0,
                 worker_timeout: float | None = None,
                 fault_injector=None, compression=None,
                 model_parallel: int = 1, tp_rules=None,
                 lr_law: str = "warn",
                 commit_overlap: bool = False,
                 ps_address: tuple[str, int] | None = None,
                 ps_replicas: list | None = None,
                 ps_shards: int = 1,
                 ps_elastic: bool = False,
                 ps_groups: list | None = None,
                 ps_snapshot_path: str | None = None,
                 ps_snapshot_every: int = 0,
                 comm_dtype: str = "float32",
                 comm_codec=None,
                 metrics_every: int = 1,
                 attrib_every: int = 0, **kwargs):
        """Elastic recovery (``fidelity='host'`` — the arm with real
        concurrency, hence real failures; the emulated arms recover via
        checkpoint/resume instead): a failing worker round is retried
        up to ``worker_retries`` times — the worker re-pulls the center
        and re-runs the window, which is exactly-once-per-commit by
        construction (the failed window's delta never reached the
        server; durable state lives only in the PS).  This is the
        correct form of the retry the reference inherited from Spark,
        which replayed a partition *against the live PS* (SURVEY.md §5
        "semantic hazard").  A worker that exhausts its retries dies;
        training continues if at most ``max_worker_failures`` workers
        have died (default 0: fail fast, the round-1 behavior).
        ``fault_injector(worker, epoch, round)`` is the chaos hook —
        called before every round; raise from it to inject a failure
        (SURVEY.md §5 "fault injection").  ``worker_timeout`` (seconds)
        arms a watchdog that records workers silent on the PS heartbeat
        beyond the timeout into ``history['detected_idle_workers']`` —
        the detection signal; the retry/elastic machinery is the
        action.  ``compression`` (``'int8'`` / ``'bfloat16'`` /
        ``'topk[:frac]'`` / a ``parallel.compression`` codec, host arm
        only) compresses each delta-family commit on the wire with
        client-side error feedback; wire/raw byte totals land in
        ``history['commit_wire_bytes']`` / ``['commit_raw_bytes']``
        (process-local under multi-host).  ``model_parallel=k`` runs
        every emulated worker tensor-parallel over a ``(workers,
        model)`` mesh — worker states shard ``P(workers, *tp_spec)``
        (``tp_rules`` defaulting to the family's Megatron-style rules),
        the PS center shards by the TP specs alone, and GSPMD derives
        both the TP collectives inside each worker and the commit
        reduction across workers; for PS-family models too big for one
        chip (beyond the reference, which was DP-only).

        Fault tolerance (host arm; docs/API.md "Fault tolerance"):
        network-level failures — connects, pulls, commits — are
        retried INSIDE ``parallel.host_ps.ResilientPSClient`` with
        exponential backoff + jitter and at-most-once commit seqs (a
        commit whose ack was lost is deduped server-side, never
        applied twice); compute-level failures (``fault_injector``, a
        poisoned window) re-pull and re-run the window here.  Both
        budgets are ``worker_retries`` and both record
        ``history['worker_round_retries']``.
        ``ps_snapshot_path`` + ``ps_snapshot_every=N`` (socket/
        in-process host arm) write a warm-restart PS snapshot every N
        commits — ``PSServer.restart_from`` brings a killed server
        back and reconnecting workers resume without double-applying
        (``history['ps_snapshots']`` counts the writes).
        ``ps_address=(host, port)`` attaches to an EXTERNALLY managed
        ``PSServer`` instead of creating one: the PS outlives this
        driver (the reference's driver-death=job-death hole,
        SURVEY.md §5), and an operator can kill/warm-restart it
        mid-run; requires ``transport='socket'`` (the server's rule
        must match this trainer's; staleness history stays
        server-side).

        ``ps_replicas=[(host, port), ...]`` attaches to a REPLICATED
        external PS (``parallel.replicated_ps``): the ORDERED worker
        address list of the replica group (the same order every
        replica holds — it is also the promotion tie-break).  Each
        worker's client walks the list with probe-before-declare-dead
        (``ResilientPSClient.for_replicas``), so a primary kill
        mid-training fails over to the promoted standby with the
        retried commit deduped by the replicated commit log — no
        operator action, byte-identical final center.
        ``history['ps_failovers']`` counts client-observed failovers;
        ``history['ps_epoch']`` records the serving replica's fencing
        epoch at the end of the run (``-1`` when that replica died
        after the final pull).  Mutually exclusive with
        ``ps_address`` (a one-element list is the unreplicated
        equivalent); same contract otherwise — socket transport, the
        group outlives the driver, snapshotting configured on the
        replicas.

        ``ps_shards=K`` (host arm, delta family) runs the PS sharded
        (``parallel.sharded_ps``): the parameter tree's leaves are
        partitioned into K byte-balanced shards, each with its own
        lock/clock/dedupe, so commits from different workers proceed
        per shard instead of convoying on one mutex; over
        ``transport='socket'`` the exchange additionally rides the
        zero-copy scatter-gather wire with version-delta pulls
        (``history['pull_shards_skipped'/'pull_bytes_saved']``).
        With an external ``ps_address`` the server must have been
        created with the same K.  Both rule families shard: the delta
        family's additive updates and the elastic family's per-leaf
        lerp are each exact per shard (the elastic local tree rides
        the wire as a second frame per shard).

        ``ps_elastic=True`` (host arm, socket) attaches to an
        ``parallel.elastic_ps.ElasticPSGroup`` member instead of a
        classic ``PSServer``: ``ps_address`` seeds the versioned
        shard-map bootstrap, and the group may split/merge/migrate
        shards (or be driven by ``telemetry.Autoscaler``) WHILE this
        trainer runs — workers re-route on fence/stale rejections via
        ``ResilientPSClient`` with zero training downtime.  Shard
        topology is owned server-side, so ``ps_shards`` stays 1 here;
        compression does not compose (the elastic wire ships raw
        leaf bytes so resharding stays byte-exact).

        ``ps_groups=[(leader_addr, [worker_ids...]), ...]`` (host arm,
        socket, delta family) runs the two-level hierarchical topology
        (``parallel.hier_ps``): each listed group's workers commit to
        a ``GroupLeader`` that folds their deltas over an
        ``aggregate_window`` (the group size) and forwards ONE
        pre-reduced upstream commit per window, cutting root fan-in
        from O(workers) to O(groups).  ``leader_addr`` is the
        ``(host, port)`` the leader binds, or ``None`` for a
        loopback-ephemeral bind; workers not listed in any group stay
        direct-to-root.  A dead leader degrades its workers to
        direct-to-root mode via a two-hop failover route (the
        ``leader_down`` / ``leader_rejoin`` flight kinds and the
        ``leader_failover_rate`` SLO); history grows
        ``ps_upstream_commits`` / ``ps_fanin_reduction`` /
        ``ps_leader_failovers``.  Composes with ``ps_shards`` (the
        root runs sharded; upstream windows ship the full tree),
        ``compression`` (the worker->leader hop), chaos and
        snapshots; the trainer must own the root server
        (mutually exclusive with ``ps_address`` / ``ps_replicas`` /
        ``ps_elastic`` and multi-host).

        ``commit_overlap=True`` on the host
        arm double-buffers each worker's loop: the commit/pull
        exchange for window *n* runs on a background thread while the
        device computes window *n+1* (the worker trains one exchange
        behind — +1 round of staleness, same trade as the emulated
        pipelined round).

        ``comm_dtype='bfloat16'`` / ``comm_codec='int8'`` (mesh tier
        only) lower communication compression INSIDE the compiled
        round: bf16 deltas through the reduce-scatter, an int8
        per-leaf-quantized center re-broadcast replacing the f32
        all-gather (``parallel.ps_dataplane``; the host arm's
        ``compression=`` codecs are the parity oracle).
        ``metrics_every=N`` (mesh tier) accumulates per-round metrics
        in a device-resident ring fetched every N rounds, and the
        driver loop dispatches round k+1 before blocking on round k —
        history contents are identical to the per-round fetch.
        ``attrib_every=N`` (mesh tier) samples every Nth round into the
        step-time decomposition (dispatch / device-compute / ring-fetch
        / host-gap segments, ``ps_round_attrib_seconds_total``) and the
        ``mfu_observed``/``mfu_roofline`` gauge pair from the XLA cost
        ledger; 0 (default) disables sampling and trained state is
        byte-identical either way."""
        super().__init__(model, **kwargs)
        self.num_workers = int(num_workers)
        self.communication_window = int(communication_window)
        # one registry validates every fidelity and names its
        # capabilities — feature gates below read flags, not strings
        self.tier = resolve_tier(fidelity)
        self.fidelity = fidelity
        self.transport = transport
        self.checkpoint_every_rounds = checkpoint_every_rounds
        self.max_worker_failures = int(max_worker_failures)
        self.worker_retries = int(worker_retries)
        self.fault_injector = fault_injector
        self.worker_timeout = (None if worker_timeout is None
                               else float(worker_timeout))
        self.model_parallel = int(model_parallel)
        self.tp_rules = tp_rules
        if self.model_parallel < 1:
            raise ValueError(
                f"model_parallel must be >= 1, got {model_parallel}")
        if self.model_parallel > 1 and not self.tier.model_parallel:
            raise ValueError(
                f"model_parallel > 1 is unsupported on the "
                f"{fidelity!r} tier (host workers are per-thread "
                f"device programs and the mesh tier maps one worker "
                f"per device — both DP-only); tensor-parallel tiers: "
                f"{tiers_with('model_parallel')}")
        self.compression = compression
        if compression is not None:
            from distkeras_tpu.parallel.compression import resolve_codec

            resolve_codec(compression)  # fail fast on a bad spec
        if self.worker_timeout is not None and self.worker_timeout <= 0:
            raise ValueError(
                f"worker_timeout must be positive, got {worker_timeout}")
        self.ps_address = (None if ps_address is None
                           else (str(ps_address[0]),
                                 int(ps_address[1])))
        self.ps_replicas = (None if ps_replicas is None
                            else [(str(h), int(p))
                                  for h, p in ps_replicas])
        if self.ps_replicas is not None and not self.ps_replicas:
            raise ValueError(
                "ps_replicas needs at least one (host, port) address")
        if ps_address is not None and ps_replicas is not None:
            raise ValueError(
                "ps_address and ps_replicas are mutually exclusive — "
                "a one-element ps_replicas list is the unreplicated "
                "equivalent")
        self.ps_shards = int(ps_shards)
        if self.ps_shards < 1:
            raise ValueError(
                f"ps_shards must be >= 1, got {ps_shards}")
        self.ps_elastic = bool(ps_elastic)
        if self.ps_elastic:
            if self.ps_address is None:
                raise ValueError(
                    "ps_elastic attaches to an externally managed "
                    "ElasticPSGroup member; pass ps_address=(host, "
                    "port) of any group server (it seeds the shard-"
                    "map bootstrap)")
            if self.ps_shards > 1:
                raise ValueError(
                    "ps_elastic owns its shard topology server-side "
                    "(the versioned shard map); leave ps_shards=1")
            if compression is not None:
                raise ValueError(
                    "compression does not compose with ps_elastic "
                    "(the elastic wire ships raw leaf bytes so "
                    "resharding stays byte-exact)")
        self.ps_groups = None
        if ps_groups is not None:
            groups, seen_ids = [], set()
            for entry in ps_groups:
                leader_addr, members = entry
                members = [int(m) for m in members]
                if not members:
                    raise ValueError(
                        "every ps_groups entry needs at least one "
                        "worker id")
                for m in members:
                    if not 0 <= m < self.num_workers:
                        raise ValueError(
                            f"ps_groups worker id {m} out of range "
                            f"[0, {self.num_workers})")
                    if m in seen_ids:
                        raise ValueError(
                            f"worker {m} appears in two ps_groups "
                            f"entries")
                    seen_ids.add(m)
                addr = (None if leader_addr is None
                        else (str(leader_addr[0]), int(leader_addr[1])))
                groups.append((addr, members))
            if not groups:
                raise ValueError(
                    "ps_groups needs at least one (leader_addr, "
                    "[worker_ids...]) entry")
            self.ps_groups = groups
            if transport != "socket":
                raise ValueError(
                    "ps_groups runs group leaders as TCP servers "
                    "fronting their workers; it requires "
                    f"transport='socket', got {transport!r}")
            if (ps_address is not None or ps_replicas is not None
                    or self.ps_elastic):
                raise ValueError(
                    "ps_groups needs the trainer-owned root server "
                    "(its HierPSServer speaks the upstream op); it "
                    "is mutually exclusive with ps_address / "
                    "ps_replicas / ps_elastic")
        self.ps_snapshot_path = ps_snapshot_path
        self.ps_snapshot_every = int(ps_snapshot_every)
        # on-chip comm knobs (mesh tier): lowered INSIDE the compiled
        # round, unlike the host arm's `compression=` wire codecs
        self.comm_dtype = str(comm_dtype)
        self.comm_codec = comm_codec
        self.metrics_every = int(metrics_every)
        if self.metrics_every < 1:
            raise ValueError(
                f"metrics_every must be >= 1, got {metrics_every}")
        if ((self.comm_dtype != "float32"
             or self.comm_codec is not None
             or self.metrics_every != 1)
                and not self.tier.comm_compression):
            raise ValueError(
                "comm_dtype / comm_codec / metrics_every lower "
                "communication compression and the metrics ring "
                "INSIDE the compiled round; they apply only to tiers "
                "with an on-chip data plane, got "
                f"fidelity={fidelity!r}; on-chip tiers: "
                f"{tiers_with('comm_compression')} (the host arm "
                "compresses the wire via compression= instead)")
        self.attrib_every = int(attrib_every)
        if self.attrib_every < 0:
            raise ValueError(
                f"attrib_every must be >= 0 (0 disables round "
                f"attribution sampling), got {attrib_every}")
        if self.attrib_every and not self.tier.round_attrib:
            raise ValueError(
                "attrib_every samples the compiled round's step-time "
                "decomposition off the mesh driver's AOT cost ledger; "
                f"it applies only to tiers with round attribution, got "
                f"fidelity={fidelity!r}; attribution tiers: "
                f"{tiers_with('round_attrib')}")
        if not self.tier.concurrent and (self.max_worker_failures
                                         or self.worker_retries
                                         or self.worker_timeout is not None
                                         or fault_injector is not None
                                         or compression is not None
                                         or ps_address is not None
                                         or ps_replicas is not None
                                         or self.ps_shards > 1
                                         or self.ps_elastic
                                         or ps_groups is not None
                                         or ps_snapshot_path is not None
                                         or self.ps_snapshot_every):
            raise ValueError(
                "max_worker_failures / worker_retries / worker_timeout "
                "/ fault_injector / compression / ps_address / "
                "ps_replicas / ps_shards / ps_groups / ps_snapshot_* "
                "apply only to "
                "fidelity='host' (the compiled tiers are "
                "deterministic; recover via checkpoint/resume), got "
                f"fidelity={fidelity!r}; concurrent tiers: "
                f"{tiers_with('concurrent')}")
        if ps_address is not None and transport != "socket":
            raise ValueError(
                "ps_address attaches to an external PSServer over TCP; "
                f"it requires transport='socket', got {transport!r}")
        if ps_replicas is not None and transport != "socket":
            raise ValueError(
                "ps_replicas attaches to an external replica group "
                "over TCP; it requires transport='socket', got "
                f"{transport!r}")
        if self.ps_snapshot_every and ps_snapshot_path is None:
            raise ValueError(
                "ps_snapshot_every needs ps_snapshot_path to write to")
        if ps_address is not None and (ps_snapshot_path is not None
                                       or self.ps_snapshot_every):
            raise ValueError(
                "with an external ps_address, configure snapshotting "
                "on the externally created HostParameterServer, not "
                "on the trainer (the driver does not own the server)")
        if ps_replicas is not None and (ps_snapshot_path is not None
                                        or self.ps_snapshot_every):
            raise ValueError(
                "with ps_replicas, configure snapshotting on the "
                "PSReplica nodes, not on the trainer (the driver does "
                "not own the replica group)")
        self.commit_overlap = bool(commit_overlap)
        if self.commit_overlap and not self.tier.commit_overlap:
            raise ValueError(
                "commit_overlap pipelines the commit against the next "
                "window; it needs a tier with a separate commit phase "
                "(faithful's pipelined round scan, mesh's overlapped "
                "reduce-scatter, host's double-buffered worker loop) "
                "— the fast arm's closed form has none, got "
                f"fidelity={fidelity!r}; overlap-capable tiers: "
                f"{tiers_with('commit_overlap')}")
        if self.commit_overlap and (checkpoint_every_rounds
                                    or kwargs.get("checkpoint_dir")):
            raise ValueError(
                "commit_overlap runs one commit round behind — "
                "mid-training checkpoints would snapshot a center "
                "missing the pending round; train without "
                "checkpointing or without commit_overlap")
        if lr_law not in ("warn", "scale", "off"):
            raise ValueError(
                f"lr_law={lr_law!r} must be 'warn' (default: warn "
                "when the configured lr violates the measured "
                "per-family stability law), 'scale' (divide lr by "
                "the family's amplification factor), or 'off'")
        self.lr_law = lr_law
        self._apply_lr_law()

    def _lr_law(self):
        """``(amplification, scale_divisor, law)`` for this family, or
        ``None``.

        The staleness families amplify the configured lr per PS round
        (PARITY.md "per-family learning-rate scaling laws", measured
        on the calibration task): DOWNPOUR commits raw window-summed
        deltas from every worker (x workers*window), ADAG normalizes
        the window but still sums worker commits (x workers), DynSGD's
        1/(staleness+1) divides the commit depth but not the window
        sum (x window), EAMSGD's Nesterov workers amplify ~1/(1-m).
        ``amplification`` drives the warning threshold;
        ``scale_divisor`` is the MEASURED correction ``lr_law='scale'``
        applies — equal for most families, but EAMSGD's measured law
        row is lr/2, not lr(1-m) (momentum amplification is transient,
        not a steady-state divisor).  The elastic exchange itself is
        lr-neutral (AEASGD: the rho x lr sweep is flat), so the AEASGD
        base declares no law."""
        return None

    def _apply_lr_law(self) -> None:
        """The library-side guardrail for the measured footguns the
        round-3/4 parity campaign documented only in prose (PARITY.md:
        DOWNPOUR at window 4 collapses to 0.26 accuracy unless the lr
        follows the family law).  ``lr_law='warn'`` (default) warns
        when lr x amplification exceeds the measured stability scale;
        ``'scale'`` applies the measured law (divides lr), matching
        what examples/compare_trainers.py hand-codes; ``'off'``
        silences informed users."""
        law = self._lr_law()
        if law is None or self.lr_law == "off":
            return
        factor, divisor, suggestion = law
        try:
            lr = float(self.learning_rate)
        except (TypeError, ValueError):
            return  # schedules: the law is about constant-lr configs
        if self.lr_law == "scale":
            self.learning_rate = lr / divisor
            return
        effective = lr * factor
        if effective > self._LR_LAW_EFFECTIVE_MAX:
            import warnings

            warnings.warn(
                f"{type(self).__name__}: learning_rate={lr:g} is "
                f"amplified ~{factor:g}x per PS round by this "
                f"family's update law (effective {effective:g} > "
                f"{self._LR_LAW_EFFECTIVE_MAX} — the measured "
                "stability scale; PARITY.md 'per-family learning-"
                f"rate scaling laws').  Consider {suggestion}, pass "
                "lr_law='scale' to apply it automatically, or "
                "lr_law='off' if this lr is deliberate.",
                UserWarning, stacklevel=3)

    def allocate_rule(self) -> UpdateRule:
        raise NotImplementedError

    def _train(self, dataset, initial_variables, resume_from=None):
        tier = self.tier
        if not tier.checkpoint and (resume_from or self.checkpoint_dir):
            if tier.name == "host":
                raise NotImplementedError(
                    "fidelity='host' is the nondeterministic faithful "
                    "arm; checkpoint/resume of racing threads is not "
                    "supported — use the emulated fidelities")
            raise NotImplementedError(
                f"fidelity={tier.name!r} does not checkpoint its "
                f"sharded-center layout; checkpointing tiers: "
                f"{tiers_with('checkpoint')}")
        if tier.data_plane == "host-wire":
            return self._train_host(dataset, initial_variables)
        mesh_tier = tier.data_plane == "mesh"
        rule = self.allocate_rule()
        tx = self._tx()
        variables = self._init_variables(initial_variables)
        center = variables["params"]
        model_state = {k: v for k, v in variables.items()
                       if k != "params"}
        num_workers = self.num_workers
        window = self.communication_window

        pc, pid = jax.process_count(), jax.process_index()
        if pc > 1 and num_workers % pc:
            raise ValueError(
                f"multi-host needs num_workers ({num_workers}) "
                f"divisible by process count ({pc})")
        local_workers = range(pid * (num_workers // pc),
                              (pid + 1) * (num_workers // pc))

        # Per-worker states: identical start, distinct rng streams.
        # Multi-host, each process materializes only its own workers'
        # states (the key split stays global so streams are identical to
        # a single-process run).
        def make_worker(rng):
            return TrainState.create(
                {"params": center, **model_state}, tx, rng)

        worker_keys = jax.random.split(
            jax.random.key(self.seed + 1), num_workers)
        mp = self.model_parallel
        if pc > 1 and mp == 1:
            worker_keys = worker_keys[local_workers.start:
                                      local_workers.stop]
        if mp > 1:
            tp_rules_resolved = (
                self.tp_rules if self.tp_rules is not None
                else tensor_parallel.rules_for(self.spec.family))
            m_tp = mesh_lib.create_mesh(num_workers, model_parallel=mp)
            # Worker states are BORN sharded: without out_shardings the
            # [W, ...] stack (params + optimizer moments) would
            # materialize on one device before placement — an OOM for
            # exactly the models TP exists for.  (The single center
            # copy from model.init still lands on one device first —
            # the same init limitation SyncTrainer's TP path has.)
            ws_struct = jax.eval_shape(jax.vmap(make_worker),
                                       worker_keys)
            ws_sharding = tensor_parallel.stacked_tree_shardings(
                m_tp, ws_struct, tp_rules_resolved)
            worker_states = jax.jit(
                jax.vmap(make_worker),
                out_shardings=ws_sharding)(worker_keys)
        else:
            worker_states = jax.vmap(make_worker)(worker_keys)

        step = make_train_step(self.model, self.loss, tx,
                               self.features_col, self.label_col)
        overlap = self.commit_overlap
        if overlap:
            if resume_from is not None:
                raise ValueError(
                    "commit_overlap cannot resume from a checkpoint "
                    "(the pipelined round carries an uncheckpointed "
                    "pending commit)")
            if self.model_parallel > 1:
                raise ValueError(
                    "commit_overlap supports data-parallel workers "
                    "only (model_parallel=1)")
            if not mesh_tier:
                from distkeras_tpu.parallel.ps_emulator import (
                    flush_pending, make_pipelined_round_fn)

                round_fn = make_pipelined_round_fn(rule, step)
                flush_fn = functools.partial(flush_pending, rule,
                                             num_workers=num_workers)
        elif not mesh_tier:
            round_fn = make_round_fn(rule, step, self.fidelity)
        ps_state = rule.init_state(center)
        perm_key = jax.random.key(self.seed + 2)

        # Multi-host: worker states are sharded across processes, so
        # checkpoints use the per-shard orbax layout (each process
        # writes/reads only its own rows); single-process runs keep the
        # single-file msgpack path.  Sharded restore happens below,
        # after mesh placement, INTO the mesh shardings.
        from distkeras_tpu import checkpoint as ckpt_mod

        self._sharded_ckpt = pc > 1
        resume_sharded = (resume_from is not None
                          and ckpt_mod.has_sharded(resume_from))
        if pc > 1 and resume_from is not None and not resume_sharded:
            raise ValueError(
                f"multi-host resume needs a sharded checkpoint, but "
                f"{resume_from!r} holds none — single-file msgpack "
                f"checkpoints restore only in single-process runs")
        cursor: dict = {}
        if not resume_sharded:
            ckpt_state, cursor = self._maybe_resume(
                resume_from, {"ps": ps_state, "workers": worker_states,
                              "perm_key": perm_key})
            ps_state, worker_states, perm_key = (
                ckpt_state["ps"], ckpt_state["workers"],
                ckpt_state["perm_key"])

        if mp > 1:
            # tensor-parallel workers: the (workers, model) mesh built
            # at init time (no vmap fallback — TP is a layout over real
            # devices)
            placement = mesh_lib.WorkerPlacement(
                mesh=m_tp, mesh_workers=num_workers, vmap_workers=1)
        else:
            placement = mesh_lib.place_workers(num_workers)
        if pc > 1 and (placement.mesh is None
                       or placement.mesh_workers != num_workers):
            raise ValueError(
                "multi-host needs one mesh slot per worker "
                f"({num_workers} workers over "
                f"{len(jax.devices())} global devices)")
        if mesh_tier:
            if pc > 1:
                raise NotImplementedError(
                    "fidelity='mesh' is single-process for now (the "
                    "sharded-center programs assume one controller) — "
                    "use fidelity='faithful'/'fast' for multi-host")
            if placement.mesh is None or placement.vmap_workers != 1:
                raise ValueError(
                    f"fidelity='mesh' maps one worker per device over "
                    f"the {mesh_lib.WORKER_AXIS!r} mesh axis; "
                    f"num_workers={num_workers} does not fit "
                    f"{len(jax.devices())} devices — use "
                    f"fidelity='fast' for vmap-folded workers")
        if placement.mesh is not None:
            m = placement.mesh
            rep = NamedSharding(m, P())
            row = NamedSharding(m, P(mesh_lib.WORKER_AXIS))
            if mesh_tier:
                # On-chip compiled data plane: the whole round is one
                # SPMD shard_map program with the center sharded over
                # the worker axis; states move into its packed layout
                # here and stay on device (donated) between rounds.
                dp = ps_dataplane.MeshDataplane(
                    rule, step, m, center, pipelined=overlap,
                    comm_dtype=self.comm_dtype,
                    comm_codec=self.comm_codec,
                    metrics_every=self.metrics_every)
                ps_state, worker_states = dp.to_device(
                    ps_state, worker_states)
            elif mp > 1:
                # PS center sharded by the TP specs (worker states were
                # born sharded above; a msgpack resume replaced them
                # with host arrays, which round_jit's in_shardings
                # place)
                ps_sharding = tensor_parallel.tree_shardings(
                    m, ps_state, tp_rules_resolved)
                ps_state = mesh_lib.global_batch_from_local(
                    ps_sharding, ps_state)
            else:
                ps_sharding, ws_sharding = rep, row
                # Each process contributes its own workers' states (and
                # the full replica of the PS state) to the global
                # arrays.
                worker_states = mesh_lib.global_batch_from_local(
                    ws_sharding, worker_states)
                ps_state = mesh_lib.global_batch_from_local(
                    ps_sharding, ps_state)
            if resume_sharded:
                # the sharded layout carries the device state; the
                # (host-local, process-identical) permutation key rides
                # in the cursor as raw key data
                restored, cursor = ckpt_mod.load_sharded(
                    resume_from,
                    {"ps": ps_state, "workers": worker_states})
                ps_state, worker_states = (restored["ps"],
                                           restored["workers"])
                cursor = self._restore_history(cursor)
                perm_key = jax.random.wrap_key_data(jnp.asarray(
                    np.asarray(cursor.pop("perm_key_data"),
                               np.uint32)))
            if mesh_tier:
                # async host dispatch: the driver owns the dataplane
                # state (and the pipelined pending), enqueues round
                # k+1 before fetching round k's metrics, and drains
                # the device-resident ring every metrics_every rounds
                driver = ps_dataplane.MeshRoundDriver(
                    dp, ps_state, worker_states,
                    attrib_every=self.attrib_every)
            elif overlap:
                round_jit = jax.jit(
                    round_fn,
                    in_shardings=(ps_sharding, ws_sharding, row, rep,
                                  row, rep, rep),
                    out_shardings=(ps_sharding, ws_sharding, rep, row,
                                   rep, rep))
                flush_jit = jax.jit(
                    flush_fn,
                    in_shardings=(ps_sharding, row, rep),
                    out_shardings=ps_sharding)
            else:
                round_jit = jax.jit(
                    round_fn,
                    in_shardings=(ps_sharding, ws_sharding, row, rep),
                    out_shardings=(ps_sharding, ws_sharding, rep))
            # worker-0 row of the model state (batch stats etc.),
            # sliced on device; jitted ONCE so epoch-boundary eval and
            # the end-of-train extraction share the compiled program
            slice_row0 = jax.jit(
                lambda t: jax.tree_util.tree_map(lambda x: x[0], t),
                out_shardings=rep)
        else:
            if resume_sharded:
                raise ValueError(
                    f"{resume_from!r} holds a sharded checkpoint but "
                    f"this run has no mesh to restore it onto")
            round_jit = jax.jit(round_fn)
            if overlap:
                flush_jit = jax.jit(flush_fn)
            slice_row0 = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda x: x[0], t)

        start_epoch = int(cursor.get("epoch", 0))
        start_round = int(cursor.get("round", 0))
        rows_per_worker_batch = self.batch_size
        cols = self._columns()

        if overlap and not mesh_tier:
            # the pipelined round's carried pending commit: a zero
            # delta (inert for the delta family) until the first round
            # marks it valid; pend_live mirrors validity host-side so
            # the epoch-end flush doesn't fetch a device bool
            # (the mesh tier's pending lives inside MeshRoundDriver)
            pend_payloads = jax.tree_util.tree_map(
                jnp.zeros_like, worker_states.params)
            if placement.mesh is not None:
                pend_perm = mesh_lib.global_batch_from_local(
                    rep, np.arange(num_workers, dtype=np.int32))
                pend_valid = mesh_lib.global_batch_from_local(
                    rep, np.asarray(False))
                _false = pend_valid
            else:
                pend_perm = jnp.arange(num_workers, dtype=jnp.int32)
                pend_valid = jnp.asarray(False)
                _false = pend_valid
            pend_live = False

        def save_point(point: dict):
            # reads the loop's current ps/worker/key state at call time
            if self._sharded_ckpt:
                self._maybe_save(
                    {"ps": ps_state, "workers": worker_states},
                    {**point, "perm_key_data": np.asarray(
                        jax.random.key_data(perm_key)).tolist()})
            else:
                self._maybe_save(
                    {"ps": ps_state, "workers": worker_states,
                     "perm_key": perm_key}, point)

        for epoch in range(start_epoch, self.num_epoch):
            t_epoch = telemetry.now()
            resuming_mid_epoch = epoch == start_epoch and start_round > 0
            if resuming_mid_epoch:
                # this epoch's pre-kill rounds live in the restored
                # history: seed epoch_losses with them (so epoch_loss
                # matches the uninterrupted run) and don't re-record
                # dropped_tail_batches for the same epoch
                epoch_losses = list(
                    self.history.get("round_loss", [])[-start_round:])
            else:
                epoch_losses = []
            first_round = start_round if epoch == start_epoch else 0

            # Metrics are fetched one round LATE: round r's device
            # metrics are pulled to host while round r+1 is already
            # queued, so the host-side batch assembly for the next round
            # overlaps device compute instead of blocking on a sync
            # every round (round-1 Weak #9; values and record order are
            # identical to the eager fetch).
            pending = None  # (device metrics of the previous round)

            def drain(metrics_dev):
                round_loss = float(
                    np.mean(mesh_lib.fetch(metrics_dev["loss"])))
                epoch_losses.append(round_loss)
                self._record(
                    round_loss=round_loss,
                    staleness=mesh_lib.fetch(
                        metrics_dev["staleness"]).tolist())

            def sync_metrics():
                # record everything outstanding, in round order: the
                # mesh driver's ring (full + partial cycles) or the
                # emulated tiers' one-round-late pending fetch
                nonlocal pending
                if mesh_tier:
                    for fetched in driver.drain():
                        drain(fetched)
                elif pending is not None:
                    drain(pending)
                    pending = None

            # Rounds are numbered globally across segments (one segment
            # for in-memory datasets — identical behavior; one per
            # shard file for ShardedDataset) so the checkpoint cursor's
            # "round" stays meaningful out-of-core.
            round_base = 0
            # a mid-epoch save due exactly at a segment boundary is
            # deferred until the next segment proves the epoch goes on
            # (the epoch-end save supersedes it otherwise) — keeps the
            # in-memory path save-for-save identical while still
            # honoring checkpoint_every_rounds across segments
            due_save = None
            def predicted_rounds(rows: int) -> int:
                # mirrors repartition + _stack_batches + // window
                # exactly, from row counts alone
                if rows < num_workers:
                    return 0
                return ((rows // num_workers)
                        // rows_per_worker_batch) // window

            plan = list(_epoch_segment_loaders(
                dataset, self.seed + 17 * epoch))
            prefetch = _SegmentPrefetch()
            seg_stall = 0.0

            def next_loadable(j: int, rb: int) -> int | None:
                # metadata-only replay of this loop's own skip rules,
                # to find which segment after j will actually load —
                # a wrong answer only costs the overlap (get() falls
                # back to a synchronous load on key mismatch)
                rb += predicted_rounds(plan[j][0])
                for k in range(j + 1, len(plan)):
                    hint = predicted_rounds(plan[k][0])
                    if rb + hint <= first_round and hint > 0:
                        rb += hint
                        continue
                    if plan[k][0] < num_workers:
                        continue
                    return k
                return None

            for seg_j, (seg_rows, load_segment) in enumerate(plan):
                sr_hint = predicted_rounds(seg_rows)
                if round_base + sr_hint <= first_round and sr_hint > 0:
                    # resume fast-path: every round of this segment
                    # predates the resume point — skip the file read
                    # entirely (records suppressed below anyway)
                    round_base += sr_hint
                    continue
                # records are suppressed for segments already processed
                # before a mid-epoch kill (their records live in the
                # restored history): a segment was entered pre-kill iff
                # its first round predates the resume round
                record_this_segment = round_base >= first_round
                if seg_rows < num_workers:
                    # too few rows to give every worker one: the whole
                    # segment is dropped — never silently, and without
                    # reading the file (row count is header metadata)
                    if record_this_segment:
                        self._record(skipped_segment_rows=seg_rows)
                    continue
                t_get = telemetry.now()
                segment = prefetch.get(seg_j, load_segment)
                seg_stall += telemetry.now() - t_get
                if _prefetch_depth() > 0:
                    nxt = next_loadable(seg_j, round_base)
                    if nxt is not None:
                        prefetch.queue(nxt, plan[nxt][1])
                shards = segment.repartition(num_workers)
                # Multi-host: stack only this process's workers' shards
                # (segment order is seed-deterministic, so every process
                # sees the same global rows and takes a disjoint slice).
                per_worker = [
                    _stack_batches(shards[i], rows_per_worker_batch,
                                   cols)
                    for i in local_workers]
                if any(p is None for p in per_worker):
                    if record_this_segment:
                        self._record(skipped_segment_rows=seg_rows)
                    continue  # segment smaller than one batch/worker
                n_batches = min(len(next(iter(p.values())))
                                for p in per_worker)
                seg_rounds = n_batches // window
                if record_this_segment:
                    # Tail batches that don't fill a whole window are
                    # dropped (the reference's per-partition loop had
                    # the same remainder behavior); record the count so
                    # it is never silent.
                    self._record(
                        dropped_tail_batches=(n_batches
                                              - seg_rounds * window))
                if due_save is not None and seg_rounds > 0:
                    sync_metrics()
                    save_point({"epoch": epoch, "round": due_save})
                    due_save = None
                for r_local in range(seg_rounds):
                    r = round_base + r_local
                    if r < first_round:
                        continue  # resume: rounds already in the ckpt
                    t_round = telemetry.now()
                    perm_key, sub = jax.random.split(perm_key)
                    perm = jax.random.permutation(sub, num_workers)
                    # [W, window, B, ...] device batch for this round;
                    # note the whole segment is already stacked per
                    # worker on the host (per_worker above) — host peak
                    # is one segment, the device sees one round at a
                    # time.
                    batch = {
                        k: np.stack(
                            [p[k][r_local * window:
                                  (r_local + 1) * window]
                             for p in per_worker])
                        for k in cols}
                    if placement.mesh is not None:
                        batch = mesh_lib.global_batch_from_local(row,
                                                                 batch)
                        perm = mesh_lib.global_batch_from_local(
                            rep, np.asarray(perm))
                    else:
                        batch = {k: jnp.asarray(v)
                                 for k, v in batch.items()}
                    if mesh_tier:
                        # dispatch round k+1 before blocking on k:
                        # poll() only surfaces rings fetched AFTER a
                        # newer round was already in flight
                        driver.dispatch(batch, perm)
                        for fetched in driver.poll():
                            drain(fetched)
                    elif overlap:
                        (ps_state, worker_states, metrics,
                         pend_payloads, pend_perm, pend_valid) = \
                            round_jit(ps_state, worker_states, batch,
                                      perm, pend_payloads, pend_perm,
                                      pend_valid)
                        pend_live = True
                    else:
                        ps_state, worker_states, metrics = round_jit(
                            ps_state, worker_states, batch, perm)
                    if not mesh_tier:
                        if pending is not None:
                            drain(pending)
                        pending = metrics
                    # host-side round span (dispatch + previous-round
                    # drain; device time lives in profiler traces)
                    telemetry.complete("ps_round", t_round,
                                       epoch=epoch, round=r)
                    every = self.checkpoint_every_rounds
                    if every and (r + 1) % every == 0:
                        if r_local + 1 < seg_rounds:
                            sync_metrics()
                            save_point({"epoch": epoch,
                                        "round": r + 1})
                        else:
                            # due exactly at the segment boundary:
                            # defer — flushed when the next segment
                            # proves the epoch continues, superseded
                            # by the epoch-end save otherwise
                            due_save = r + 1
                round_base += seg_rounds
            if round_base == 0:
                raise ValueError(
                    f"not enough batches per worker for one "
                    f"communication window ({window}) in any segment")
            sync_metrics()
            if mesh_tier:
                if overlap:
                    # the pipeline always runs one commit behind: fold
                    # the final pending round in so epoch-boundary eval
                    # (and the returned model) see every commit
                    driver.flush_pipeline()
                ps_state, worker_states = driver.mps, driver.mws
            elif overlap and pend_live:
                # same flush for the emulated pipelined tiers
                ps_state = flush_jit(ps_state, pend_payloads,
                                     pend_perm)
                pend_valid = _false
                pend_live = False
            self._record(epoch_loss=float(np.mean(epoch_losses)),
                         segment_stall_s=round(seg_stall, 4))
            if getattr(self, "_eval_dataset", None) is not None:
                self._eval_epoch({
                    "params": (dp.center(ps_state) if mesh_tier
                               else ps_state.center),
                    **slice_row0(worker_states.model_state)})
            save_point({"epoch": epoch + 1, "round": 0})
            telemetry.complete("epoch", t_epoch, epoch=epoch,
                               trainer=type(self).__name__)

        # Keep worker 0's model state (batch stats etc.): slice on device
        # (replicated output) so only one row ever crosses to host.
        final_model_state = jax.tree_util.tree_map(
            mesh_lib.fetch, slice_row0(worker_states.model_state))
        # Mesh tier: unpack the sharded-center layout back into the
        # public PSState shape callers (and save()) expect.
        ps_export = (dp.export_ps_state(ps_state) if mesh_tier
                     else ps_state)
        self.trained_variables = {"params": ps_export.center,
                                  **final_model_state}
        self.parameter_server_state = jax.device_get(ps_export)
        return self.trained_variables


    def _train_host(self, dataset, initial_variables):
        """Design 5a (SURVEY.md §7): free-running worker threads against
        a concurrent host-side parameter server.  Real races, emergent
        staleness — the faithful arm the on-mesh emulator's deterministic
        staleness is validated against.  See ``parallel.host_ps``.

        Multi-host (``transport='socket'`` required): process 0 hosts
        the PS, every process runs its slice of the worker ids, and the
        reference's star topology spans hosts over real TCP — the DCN
        arm.  The PS address travels by collective broadcast; the final
        center, staleness log, and epoch telemetry are broadcast/
        reduced so every process returns identical results."""
        from distkeras_tpu.parallel.compression import (raw_nbytes,
                                                        resolve_codec)
        from distkeras_tpu.parallel.host_ps import (
            HostParameterServer, PSClient, PSRetryExhausted, PSServer,
            ResilientPSClient, fetch_epoch)
        from distkeras_tpu.utils import (tree_add, tree_sub,
                                         tree_zeros_like)

        rule = self.allocate_rule()
        codec = resolve_codec(self.compression)
        if codec is not None and rule.payload_kind != "delta":
            raise ValueError(
                "compression applies only to the delta-family rules "
                "(DOWNPOUR/ADAG/DynSGD): their additive payloads are "
                "error-feedback-correctable; the elastic family "
                "commits absolute parameters")
        if self.commit_overlap and rule.payload_kind != "delta":
            raise ValueError(
                "commit_overlap on the host arm supports the delta "
                "family only (the elastic exchange folds the pulled "
                "center back into the worker's CURRENT locals — "
                "nothing to overlap)")
        tx = self._tx()
        variables = self._init_variables(initial_variables)
        center = variables["params"]
        model_state = {k: v for k, v in variables.items()
                       if k != "params"}
        num_workers = self.num_workers
        window = self.communication_window

        if self.transport not in ("inprocess", "socket"):
            raise ValueError(
                f"unknown transport {self.transport!r}; "
                "expected 'inprocess' or 'socket'")
        pc = jax.process_count()
        rank = jax.process_index()
        multi = pc > 1
        if multi:
            from jax.experimental import multihost_utils
            if self.transport != "socket":
                raise ValueError(
                    "multi-host fidelity='host' needs "
                    "transport='socket' (the PS lives on process 0)")
            if num_workers % pc:
                raise ValueError(
                    f"multi-host needs num_workers ({num_workers}) "
                    f"divisible by the process count ({pc})")
            if self.ps_address is not None:
                raise ValueError(
                    "external ps_address does not compose with "
                    "multi-host runs (process 0 hosts the PS there)")
            if self.ps_replicas is not None:
                raise ValueError(
                    "ps_replicas does not compose with multi-host "
                    "runs (process 0 hosts the PS there)")
            if self.ps_groups is not None:
                raise ValueError(
                    "ps_groups does not compose with multi-host runs "
                    "(group leaders run as threads of the single "
                    "driver process)")

        shard_plan = None
        if self.ps_shards > 1:
            from distkeras_tpu.parallel.sharded_ps import plan_shards

            # the one plan every endpoint derives: byte-balanced leaf
            # partition, a pure function of (template, K)
            shard_plan = plan_shards(
                jax.tree_util.tree_map(np.asarray, center),
                self.ps_shards)

        ps = None
        server = None
        if (self.ps_address is None and self.ps_replicas is None
                and (not multi or rank == 0)):
            if self.ps_shards > 1:
                from distkeras_tpu.parallel.sharded_ps import (
                    ShardedParameterServer)

                ps = ShardedParameterServer(
                    rule, center, self.ps_shards,
                    snapshot_path=self.ps_snapshot_path,
                    snapshot_every=self.ps_snapshot_every)
            else:
                ps = HostParameterServer(
                    rule, center, snapshot_path=self.ps_snapshot_path,
                    snapshot_every=self.ps_snapshot_every)
            if self.transport == "socket":
                server_cls = PSServer
                if self.ps_groups is not None:
                    # root must understand the leaders' upstream op
                    from distkeras_tpu.parallel.hier_ps import (
                        HierPSServer)

                    server_cls = HierPSServer
                server = server_cls(
                    ps, center,
                    host="0.0.0.0" if multi else "127.0.0.1").start()
        if multi:
            # ship process 0's "host:port" to everyone (fixed-width
            # byte buffer: broadcast needs one shape on all processes)
            wire = np.zeros(64, np.uint8)
            if rank == 0:
                import os as _os

                from distkeras_tpu.parallel import transport as _tp

                ps_host = (_os.environ.get("DKT_PS_HOST")
                           or _tp.determine_host_address())
                if ps_host.startswith("127."):
                    # correct for single-machine multi-process (the
                    # local[N] analogue); a real pod must override
                    print("[distkeras_tpu] PS address resolved to "
                          f"loopback ({ps_host}) — fine for processes "
                          "on one machine; set DKT_PS_HOST to a "
                          "routable address for true multi-host",
                          flush=True)
                addr = f"{ps_host}:{server.address[1]}".encode()
                wire[:len(addr)] = np.frombuffer(addr, np.uint8)
            wire = np.asarray(
                multihost_utils.broadcast_one_to_all(wire))
            host_s, _, port_s = bytes(
                wire).rstrip(b"\0").decode().rpartition(":")
            ps_address = (host_s, int(port_s))
        elif self.ps_address is not None:
            ps_address = self.ps_address  # externally managed PSServer
        else:
            ps_address = server.address if server is not None else None

        # Hierarchical aggregation (parallel.hier_ps): one in-process
        # GroupLeader per ps_groups entry fronts its workers and folds
        # their windows into single upstream commits against the root.
        leaders: list = []
        group_of: dict[int, int] = {}
        if self.ps_groups is not None:
            from distkeras_tpu.parallel.hier_ps import (
                GroupLeader, resilient_hier_client)

            if rule.payload_kind != "delta":
                raise ValueError(
                    "ps_groups supports the delta-family rules only "
                    "(DOWNPOUR/ADAG/DynSGD): leaders fold additive "
                    "payloads; the elastic exchange has no "
                    "closed-form combination")
            for gi, (addr, members) in enumerate(self.ps_groups):
                leader = GroupLeader(
                    rule, center, ps_address, group_id=gi,
                    aggregate_window=len(members),
                    host=addr[0] if addr is not None else "127.0.0.1",
                    port=addr[1] if addr is not None else 0)
                leader.start()
                leaders.append(leader)
                for m in members:
                    group_of[m] = gi

        step = make_train_step(self.model, self.loss, tx,
                               self.features_col, self.label_col)
        run_window = jax.jit(make_window_runner(step))
        worker_keys = jax.random.split(
            jax.random.key(self.seed + 1), num_workers)
        cols = self._columns()
        # Thread-shared accumulators are telemetry primitives (ISSUE 2:
        # the hand-rolled history_lock is gone) — Series/Counter carry
        # their own locks, so worker threads append race-free and the
        # post-join code snapshots once.
        round_records = telemetry.Series()  # (worker, epoch, loss)
        retry_records = telemetry.Series()  # (worker, epoch, round)
        failures = telemetry.Series()       # (worker, exception)
        wire_total = telemetry.Counter()    # codec-arm commit bytes
        raw_total = telemetry.Counter()
        skip_total = telemetry.Counter()    # version-delta pull savings
        saved_total = telemetry.Counter()   # (sharded socket arm)
        failover_total = telemetry.Counter()  # ps_replicas client arm
        leader_failover_total = telemetry.Counter()  # ps_groups arm

        # Threads free-run through epochs, so the per-epoch shuffle +
        # repartition is memoized under a lock: the first worker to
        # reach epoch e builds the shards once (not one full-dataset
        # copy per thread); entries are dropped after the last worker
        # fetches them.
        # RLock: segment_shard -> epoch_plan nests the acquisition
        shard_lock = racecheck.rlock("trainers.shard")
        # keyed (epoch, segment slot): one segment for in-memory
        # datasets (the whole shuffled set), one per shard file for
        # ShardedDataset — the host arm streams out-of-core data the
        # same way the emulated arms do, with peak memory bounded by
        # the segments concurrently in flight across threads
        # entry: (shards | None | BaseException, fetched, event, ready)
        shard_cache: dict[tuple[int, int], tuple] = {}
        plan_cache: dict[int, list] = {}
        per_proc = num_workers // pc
        local_workers = (range(rank * per_proc, (rank + 1) * per_proc)
                         if multi else range(num_workers))
        # workers this process will never run (multi-host slices) count
        # as "never fetching" for the shard-cache sweep, or every
        # epoch's repartition would stay pinned in memory
        dead_workers: set[int] = (set(range(num_workers))
                                  - set(local_workers))
        dropped_per_epoch = [0] * self.num_epoch
        skipped_rows_per_epoch = [0] * self.num_epoch
        accum_lock = racecheck.lock("trainers.accum")  # the two index+= arrays above

        def _sweep_shard_cache():
            # caller holds shard_lock: drop READY entries every live
            # worker has fetched (dead workers never will — without
            # this, each dead worker would pin one segment per slot)
            for e in [e for e, (_, fetched, _, ready)
                      in shard_cache.items()
                      if ready and fetched | dead_workers
                      >= set(range(num_workers))]:
                del shard_cache[e]

        def epoch_plan(epoch: int) -> list:
            # (rows, load) pairs, deterministic in the epoch seed —
            # every worker walks the same segment order
            with shard_lock:
                if epoch not in plan_cache:
                    plan_cache[epoch] = list(_epoch_segment_loaders(
                        dataset, self.seed + 17 * epoch))
                return plan_cache[epoch]

        def build_segment(key: tuple[int, int],
                          event: threading.Event):
            """Load/shuffle/repartition segment ``key`` and publish it.
            Build failures poison the entry before the event fires:
            waiting workers re-raise instead of blocking forever on an
            event nobody will set."""
            epoch, slot = key
            shards: object = None
            try:
                rows, load = epoch_plan(epoch)[slot]
                shards = (load().repartition(num_workers)
                          if rows >= num_workers else None)
            except BaseException as exc:
                shards = exc
                raise
            finally:
                with shard_lock:
                    shard_cache[key] = (shards, set(), event, True)
                event.set()

        def prefetch_segment(epoch: int, slot: int):
            """Background one-ahead build: claim the entry if nobody
            has, then build it through the same publish/poison path a
            requesting worker would use."""
            key = (epoch, slot)
            with shard_lock:
                if key in shard_cache:
                    return
                event = threading.Event()
                shard_cache[key] = (None, set(), event, False)
            try:
                build_segment(key, event)
            except BaseException:
                pass  # poisoned entry re-raises in every requester

        def segment_shard(epoch: int, slot: int, w: int):
            """Worker ``w``'s slice of segment ``slot``; None when the
            segment cannot give every worker a row.  The segment is
            built (loaded / shuffled / repartitioned) OUTSIDE the lock
            by the first requester — other workers wait on its event,
            and requesters of cached or different segments never block
            behind the IO.  A successful build kicks a one-ahead
            background build of the next slot so segment IO overlaps
            the epoch's compute."""
            key = (epoch, slot)
            while True:
                build = False
                with shard_lock:
                    entry = shard_cache.get(key)
                    if entry is None:
                        event = threading.Event()
                        shard_cache[key] = (None, set(), event, False)
                        build = True
                    else:
                        shards, fetched, event, ready = entry
                        if ready:
                            fetched.add(w)
                            _sweep_shard_cache()
                            if isinstance(shards, BaseException):
                                raise RuntimeError(
                                    f"segment (epoch {epoch}, slot "
                                    f"{slot}) failed to build in "
                                    "another worker") from shards
                            return (None if shards is None
                                    else shards[w])
                if build:
                    build_segment(key, event)
                    nxt = slot + 1
                    if (_prefetch_depth() > 0
                            and nxt < len(epoch_plan(epoch))):
                        threading.Thread(
                            target=prefetch_segment, args=(epoch, nxt),
                            daemon=True,
                            name="dkt-segment-prefetch").start()
                else:
                    event.wait()

        def note_death(w: int):
            with shard_lock:
                dead_workers.add(w)
                _sweep_shard_cache()

        def worker_loop(w: int):
            # (epoch, round) the retry callback stamps; -1 = startup.
            # Network-level failures (connect/pull/commit) are retried
            # INSIDE ResilientPSClient — backoff + jitter + at-most-once
            # commit seqs; this loop keeps only the COMPUTE-level
            # budget (fault_injector, a poisoned window).
            round_ctx = [-1, -1]

            def on_retry(attempt, exc):
                retry_records.append((w, round_ctx[0], round_ctx[1]))
                telemetry.instant("worker_retry", worker=w,
                                  epoch=round_ctx[0],
                                  round=round_ctx[1])

            retry_kw = dict(retries=self.worker_retries,
                            seed=self.seed + 101 * w,
                            on_retry=on_retry)
            socket_arm = (ps_address is not None
                          or self.ps_replicas is not None)
            sharded_socket = socket_arm and (self.ps_shards > 1
                                             or self.ps_elastic)
            # per-worker, so client instances (rebuilt per reconnect)
            # accumulate race-free; folded into the shared counters
            # in the finally below
            shard_stats = ({"pull_shards_skipped": 0,
                            "pull_bytes_saved": 0}
                           if sharded_socket else None)
            gi = group_of.get(w)
            if gi is not None:
                # grouped worker: leader first, root on leader death
                client = resilient_hier_client(
                    leaders[gi].address, ps_address, worker_id=w,
                    template=center, codec=codec, **retry_kw)
            elif self.ps_elastic:
                client = ResilientPSClient.for_elastic(
                    [ps_address], worker_id=w, template=center,
                    stats=shard_stats, **retry_kw)
            elif self.ps_replicas is not None:
                client = ResilientPSClient.for_replicas(
                    self.ps_replicas, worker_id=w, template=center,
                    codec=codec, shards=self.ps_shards,
                    shard_stats=shard_stats, **retry_kw)
            elif socket_arm:
                client = ResilientPSClient.for_address(
                    *ps_address, worker_id=w, template=center,
                    codec=codec, shards=self.ps_shards,
                    shard_stats=shard_stats, **retry_kw)
            else:
                client = ResilientPSClient.for_server(ps, w,
                                                      **retry_kw)
            overlap = self.commit_overlap
            exchange = None
            pending: list = [None]
            if overlap:
                from concurrent.futures import ThreadPoolExecutor

                # one-deep double buffer: the exchange for window n
                # runs here while the device computes window n+1 (the
                # worker trains one exchange behind — +1 staleness)
                exchange = ThreadPoolExecutor(
                    max_workers=1,
                    thread_name_prefix=f"dkt-ps-exchange-{w}")

            def drain_exchange():
                """Join the in-flight exchange (if any) and adopt its
                pulled center; every synchronous client op must be
                preceded by this (one connection, one op at a time).
                Raises what the exchange raised (PSRetryExhausted
                included)."""
                fut, pending[0] = pending[0], None
                return fut.result() if fut is not None else None

            wire_bytes = raw_bytes = 0
            try:
                state = TrainState.create(
                    {"params": center, **model_state}, tx,
                    worker_keys[w])
                residual = (tree_zeros_like(center)
                            if codec is not None else None)
                # startup contact rides the same budget as any later
                # op (the client builds its connection lazily inside
                # the retry loop)
                pulled = client.pull()
                for epoch in range(self.num_epoch):
                    epoch_rounds = 0  # global round id across segments
                    for slot in range(len(epoch_plan(epoch))):
                        shard = segment_shard(epoch, slot, w)
                        stacked = (None if shard is None else
                                   _stack_batches(shard,
                                                  self.batch_size,
                                                  cols))
                        if stacked is None:
                            # segment too small for this worker's
                            # batch: its rows never train — recorded,
                            # never silent (this worker's nominal
                            # slice; summed over workers ~= the
                            # segment)
                            rows = epoch_plan(epoch)[slot][0]
                            with accum_lock:
                                skipped_rows_per_epoch[epoch] += (
                                    len(shard) if shard is not None
                                    else rows // num_workers)
                            continue
                        n_batches = len(next(iter(stacked.values())))
                        seg_rounds = n_batches // window
                        with accum_lock:
                            dropped_per_epoch[epoch] += (
                                n_batches - seg_rounds * window)
                        for r_local in range(seg_rounds):
                            r = epoch_rounds
                            epoch_rounds += 1
                            t_round = telemetry.now()
                            batches = {
                                k: jnp.asarray(
                                    v[r_local * window:
                                      (r_local + 1) * window])
                                for k, v in stacked.items()}
                            round_ctx[0], round_ctx[1] = epoch, r
                            attempts = 0  # compute-level retry budget
                            base_state = state  # pre-round snapshot: a
                            # retried window must not see optimizer
                            # moments / rng / step already advanced by the
                            # aborted attempt
                            while True:
                                try:
                                    if self.fault_injector is not None:
                                        self.fault_injector(w, epoch, r)
                                    start_params = (
                                        jax.tree_util.tree_map(
                                            jnp.asarray, pulled))
                                    state = base_state.replace(
                                        params=start_params)
                                    state, metrics = run_window(
                                        state, batches)
                                    if rule.payload_kind == "params":
                                        payload = local = state.params
                                    else:
                                        payload = rule.normalize_delta(
                                            tree_sub(state.params,
                                                     start_params),
                                            window)
                                        local = None
                                    if codec is not None:
                                        # Error feedback: fold the
                                        # residual under-transmitted so
                                        # far into this window's delta.
                                        # The client retries internally
                                        # with these IDENTICAL bytes
                                        # under ONE commit seq, so a
                                        # lost-ack retry dedupes
                                        # server-side and the residual
                                        # always matches what the
                                        # server absorbed.
                                        total = tree_add(payload,
                                                         residual)
                                        if sharded_socket:
                                            encoded, applied = (
                                                codec.round_trip_shards(
                                                    total, shard_plan))
                                            enc_len = sum(
                                                len(d) for d in encoded)
                                        else:
                                            encoded, applied = (
                                                codec.round_trip(total))
                                            enc_len = len(encoded)
                                        commit_args = (
                                            encoded if socket_arm
                                            else applied, None)
                                        residual = tree_sub(total,
                                                            applied)
                                        wire_bytes += enc_len
                                        raw_bytes += raw_nbytes(
                                            payload)
                                    else:
                                        commit_args = (
                                            payload,
                                            local
                                            if rule.pull_uses_local
                                            else None)
                                    if overlap:
                                        # adopt exchange n-1's center
                                        # (it ran under window n's
                                        # compute), hand exchange n to
                                        # the background thread
                                        got = drain_exchange()
                                        if got is not None:
                                            pulled = got
                                        pending[0] = exchange.submit(
                                            client.commit,
                                            *commit_args)
                                    else:
                                        pulled = client.commit(
                                            *commit_args)
                                    break
                                except PSRetryExhausted:
                                    # the network budget died inside
                                    # the client; recomputing the
                                    # window cannot revive the link
                                    raise
                                except Exception:
                                    # Compute-level failure (chaos
                                    # hook, poisoned window): re-pull
                                    # and re-run on this loop's own
                                    # budget.  At-most-once holds: an
                                    # uncommitted window's delta never
                                    # reached the PS.  (Exception, not
                                    # BaseException: KeyboardInterrupt
                                    # / MemoryError should not be
                                    # retried.)
                                    attempts += 1
                                    if attempts > self.worker_retries:
                                        raise
                                    retry_records.append((w, epoch, r))
                                    telemetry.instant("worker_retry",
                                                      worker=w,
                                                      epoch=epoch,
                                                      round=r)
                                    if overlap:
                                        # serialize with the in-flight
                                        # exchange before re-pulling
                                        # (its PSRetryExhausted, if
                                        # any, kills the worker here)
                                        drain_exchange()
                                    pulled = client.pull()
                            round_records.append(
                                (w, epoch,
                                 float(np.mean(
                                     np.asarray(metrics["loss"])))))
                            # one span per worker round on this
                            # worker thread's track — the acceptance
                            # timeline next to ps_commit spans
                            telemetry.complete("worker_round",
                                               t_round, worker=w,
                                               epoch=epoch, round=r)
                    if epoch_rounds == 0:
                        raise ValueError(
                            f"worker {w}: not enough batches per "
                            f"worker for one communication window "
                            f"({window}) in any segment")
                if overlap:
                    # the last window's exchange is still in flight;
                    # its center must land before the clean finish
                    drain_exchange()
                client.done()
                client.close()
            except BaseException as e:  # handled by the join below
                note_death(w)
                failures.append((w, e))
            finally:
                if exchange is not None:
                    exchange.shutdown(wait=False)
                # telemetry flush runs even for workers that die
                # mid-run — their applied commits' traffic was real
                if codec is not None:
                    wire_total.inc(wire_bytes)
                    raw_total.inc(raw_bytes)
                    m = telemetry.metrics()
                    m.counter("commit_wire_bytes_total").inc(wire_bytes)
                    m.counter("commit_raw_bytes_total").inc(raw_bytes)
                if shard_stats is not None:
                    skip_total.inc(shard_stats["pull_shards_skipped"])
                    saved_total.inc(shard_stats["pull_bytes_saved"])
                if self.ps_replicas is not None:
                    # the cycler survives reconnects, so its count is
                    # this worker's whole-run failover total
                    failover_total.inc(client.replicas.failovers)
                if group_of.get(w) is not None:
                    leader_failover_total.inc(
                        client.replicas.failovers)

        threads = [threading.Thread(target=worker_loop, args=(w,))
                   for w in local_workers]
        for t in threads:
            t.start()
        # Active failure detection (SURVEY.md §5): while workers run, a
        # watchdog samples the PS contact heartbeat and records any
        # worker silent beyond worker_timeout — the monitoring signal an
        # operator would page on; the join + elastic machinery below is
        # the corresponding action.
        detected: list[list[int]] = []
        watcher = None
        stop_watch = threading.Event()
        if self.worker_timeout is not None and ps is not None:
            for w in range(num_workers):
                # monitor from t=0: a worker hanging before its first
                # PS contact must be flagged, not invisible; grouped
                # workers heartbeat at their leader, not the root
                gi = group_of.get(w)
                (leaders[gi] if gi is not None else ps).register(w)

            def watchdog():
                while not stop_watch.wait(self.worker_timeout / 4):
                    seen = set(ps.idle_workers(self.worker_timeout))
                    for lead in leaders:
                        seen.update(
                            lead.idle_workers(self.worker_timeout))
                    # leader ids live in their own space above the
                    # worker range; only workers are paged on
                    idle = sorted(i for i in seen if i < num_workers)
                    if idle and (not detected or detected[-1] != idle):
                        detected.append(idle)
                        # timeline marker on the watchdog's own track
                        telemetry.instant("idle_workers", workers=idle)

            watcher = threading.Thread(target=watchdog, daemon=True)
            watcher.start()
        try:
            for t in threads:
                t.join()
            if multi:
                # the PS (and its watchdog — remote workers may still
                # be running and must stay monitored) must outlive
                # every process's workers
                multihost_utils.sync_global_devices(
                    "dkt-host-ps-drained")
        finally:
            # always reap the watchdog — a KeyboardInterrupt in join()
            # must not leak a thread polling the PS forever
            stop_watch.set()
            if watcher is not None:
                watcher.join()
        if detected:
            self._record(detected_idle_workers=detected)
        for lead in leaders:
            # drain flushes any partial window upstream so the root
            # center (the deliverable) holds every acked commit
            lead.drain()
            lead.stop()
        if server is not None:
            server.stop()
        # threads are joined: snapshot the shared accumulators once
        failures = failures.values()
        retry_records = retry_records.values()
        round_records = round_records.values()
        total_failures = len(failures)
        if multi:
            total_failures = int(multihost_utils.process_allgather(
                np.asarray([len(failures)])).sum())
        if total_failures and (total_failures > self.max_worker_failures
                               or total_failures == num_workers):
            if failures:
                raise failures[0][1]
            raise RuntimeError(
                f"{total_failures} worker(s) failed on other "
                f"processes (> max_worker_failures="
                f"{self.max_worker_failures})")
        if failures:
            # Elastic continuation: the dead workers' committed rounds
            # stay in the center (durable by construction); survivors
            # carried the rest of the budget.
            self._record(worker_failures=[(w, repr(e))
                                          for w, e in failures])
        if retry_records:
            self._record(worker_round_retries=retry_records)
        if ps is not None and ps.num_snapshots:
            self._record(ps_snapshots=ps.num_snapshots)
        if leaders:
            total_folded = sum(l.num_commits for l in leaders)
            ups = sum(l.num_upstream for l in leaders)
            self._record(
                ps_upstream_commits=ups,
                ps_fanin_reduction=total_folded / max(ups, 1),
                ps_leader_failovers=int(leader_failover_total.value))
        if codec is not None:
            self._record(commit_wire_bytes=int(wire_total.value),
                         commit_raw_bytes=int(raw_total.value))
        if ((self.ps_shards > 1 or self.ps_elastic)
                and self.transport == "socket"):
            # version-delta pull savings (process-local): shards the
            # server did NOT ship because this process's workers were
            # already current on them
            self._record(
                pull_shards_skipped=int(skip_total.value),
                pull_bytes_saved=int(saved_total.value))
        # end-of-run SLO verdict over whatever the run metered (with
        # telemetry disabled every signal is absent → "ok")
        self._record(slo_health=telemetry.metrics().health()["state"])

        # round_loss is per-process telemetry (this process's workers);
        # epoch_loss / dropped tails are reduced globally so every
        # process reports identical curves.
        for _, _, loss in round_records:
            self._record(round_loss=loss)
        sums = np.zeros((self.num_epoch, 4))
        for _, e, loss in round_records:
            sums[e] += (loss, 1.0, 0.0, 0.0)
        sums[:, 2] = dropped_per_epoch
        sums[:, 3] = skipped_rows_per_epoch
        if multi:
            sums = np.asarray(
                multihost_utils.process_allgather(sums)).sum(axis=0)
        for epoch in range(self.num_epoch):
            self._record(
                epoch_loss=float(sums[epoch, 0]
                                 / max(sums[epoch, 1], 1.0)),
                dropped_tail_batches=int(sums[epoch, 2]))
            if sums[epoch, 3]:
                self._record(
                    skipped_segment_rows=int(sums[epoch, 3]))

        if multi:
            # staleness log + final center live on process 0; broadcast
            # (two-phase: length first — shapes must match everywhere)
            n_stal = int(np.asarray(multihost_utils.broadcast_one_to_all(
                np.asarray([len(ps.staleness_log) if ps is not None
                            else 0])))[0])
            stal = np.zeros(n_stal, np.int64)
            if rank == 0:
                stal[:] = ps.staleness_log
            stal = np.asarray(
                multihost_utils.broadcast_one_to_all(stal))
            self._record(staleness=[int(s) for s in stal])
            final_center = multihost_utils.broadcast_one_to_all(
                jax.tree_util.tree_map(
                    np.asarray, ps.center if ps is not None else center),
                is_source=rank == 0)
        elif ps is not None:
            self._record(staleness=list(ps.staleness_log))
            final_center = ps.center
        elif self.ps_replicas is not None:
            # replicated external PS: the final center is pulled
            # through the SAME multi-address failover path the workers
            # used — the group may have promoted mid-run, so a pinned
            # address could point at a fenced ex-primary
            fin = ResilientPSClient.for_replicas(
                self.ps_replicas, worker_id=num_workers,
                template=center, retries=self.worker_retries,
                seed=self.seed, use_seq=False)
            try:
                final_center = fin.pull()
                fin.done()
                try:
                    served_epoch = fetch_epoch(
                        *fin.replicas.current())
                except OSError:
                    # the serving replica died between the final pull
                    # and this probe; the pull (the deliverable)
                    # already succeeded — record the sentinel, not a
                    # failed run
                    served_epoch = -1
                self._record(
                    ps_failovers=int(failover_total.value),
                    ps_epoch=served_epoch)
            finally:
                fin.close()
        elif self.ps_elastic:
            # elastic external PS: the group may have split / merged /
            # migrated mid-run, so the final pull walks the versioned
            # shard map exactly the way the workers did
            fin = ResilientPSClient.for_elastic(
                [self.ps_address], worker_id=num_workers,
                template=center, retries=self.worker_retries,
                seed=self.seed)
            try:
                final_center = fin.pull()
                fin.done()
            finally:
                fin.close()
        else:
            # external ps_address: the final center is pulled over the
            # wire; staleness history stays server-side (the PS
            # outlives this driver — the ps_address contract)
            fin = PSClient(*self.ps_address, worker_id=num_workers,
                           template=center)
            try:
                final_center = fin.pull()
                fin.done()
            finally:
                fin.close()
        self.parameter_server_state = ps  # None off process 0 and
        # for external ps_address (the server owns its state there)
        self.trained_variables = {
            "params": jax.tree_util.tree_map(jnp.asarray, final_center),
            **model_state}
        # Free-running threads have no global epoch boundary; evaluate
        # the final center once.
        self._eval_epoch(self.trained_variables)
        return self.trained_variables


class DOWNPOUR(DistributedTrainer):
    """Dean et al. async SGD (reference ``DOWNPOUR``)."""

    def allocate_rule(self):
        return DownpourRule()

    def _lr_law(self):
        f = self.num_workers * self.communication_window
        return (f, f, "learning_rate / (num_workers * "
                "communication_window)")


class ADAG(DistributedTrainer):
    """Asynchronous Distributed Adaptive Gradients — window-normalized
    deltas (reference's flagship, ``ADAG``)."""

    def allocate_rule(self):
        return AdagRule()

    def _lr_law(self):
        return (self.num_workers, self.num_workers,
                "learning_rate / num_workers")


class DynSGD(DistributedTrainer):
    """Staleness-scaled commits (reference ``DynSGD``)."""

    def allocate_rule(self):
        return DynSGDRule()

    def _lr_law(self):
        return (self.communication_window, self.communication_window,
                "learning_rate / communication_window")


class AEASGD(DistributedTrainer):
    """Asynchronous Elastic Averaging SGD (Zhang et al.; reference
    ``AEASGD``).  ``alpha = learning_rate * rho`` as in the paper's
    stability condition."""

    def __init__(self, model, rho: float = 5.0, **kwargs):
        kwargs.setdefault("learning_rate", 0.01)
        super().__init__(model, **kwargs)
        self.rho = float(rho)

    @property
    def alpha(self) -> float:
        try:
            lr = float(self.learning_rate)
        except (TypeError, ValueError):
            raise ValueError(
                "the elastic family derives alpha = learning_rate * "
                "rho (the paper's stability condition), which needs a "
                "scalar learning_rate — schedules are not supported "
                f"here, got {self.learning_rate!r}") from None
        return lr * self.rho

    def allocate_rule(self):
        return ElasticRule(alpha=self.alpha)


class EAMSGD(AEASGD):
    """AEASGD with Nesterov momentum in the worker loop (reference
    ``EAMSGD`` — same server law, momentum on the worker)."""

    def __init__(self, model, momentum: float = 0.9, **kwargs):
        kwargs.setdefault("worker_optimizer", "nesterov")
        # before super(): _apply_lr_law runs in the base __init__ and
        # EAMSGD's law reads the momentum
        self.momentum = momentum
        super().__init__(model, **kwargs)

    def _lr_law(self):
        if self.worker_optimizer != "nesterov" or self.momentum >= 1:
            return super()._lr_law()
        # Nesterov workers amplify the effective step ~1/(1-m)
        # transiently (10x at the default m=0.9) — that drives the
        # warning threshold — but the MEASURED correction is lr/2
        # (PARITY.md's "momentum law" row restores 0.99): momentum
        # amplification is transient, so dividing by the full 1/(1-m)
        # would under-train 5x below the measured parity lr.
        return (1.0 / (1.0 - self.momentum), 2.0,
                "learning_rate / 2 (the measured momentum-law row "
                "at the default momentum=0.9)")

    def _tx(self):
        if self.worker_optimizer == "nesterov":
            return resolve_optimizer("nesterov",
                                     self.learning_rate,
                                     m=self.momentum)
        return super()._tx()


class _MemberParallelTrainer(Trainer):
    """Shared engine for Ensemble/Averaging: every member trains
    *simultaneously* inside one vmapped, jitted program, members sharded
    across the mesh's worker axis (round-1 ran them as sequential
    Python loops — zero mesh utilization for an embarrassingly parallel
    job, VERDICT.md Weak #7)."""

    SCAN_CHUNK = 32

    #: False -> every member shares one init (the averaging setting);
    #: True -> per-member init seeds (independent ensemble members).
    distinct_inits: ClassVar[bool] = True

    def __init__(self, model, num_models: int = 2, **kwargs):
        super().__init__(model, **kwargs)
        self.num_models = int(num_models)

    def _member_states(self, initial_variables) -> "TrainState":
        tx = self._tx()
        n = self.num_models
        sample = jnp.asarray(self.spec.example_input(self.batch_size))
        if initial_variables is not None:
            variables = dict(initial_variables)
            stacked = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(
                    jnp.asarray(x), (n,) + jnp.shape(x)), variables)
        elif self.distinct_inits:
            init_keys = jnp.stack(
                [jax.random.key(self.seed + i) for i in range(n)])
            stacked = jax.vmap(
                lambda k: self.model.init(k, sample))(init_keys)
        else:
            variables = self.model.init(jax.random.key(self.seed),
                                        sample)
            stacked = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x, (n,) + x.shape),
                variables)
        member_rngs = jax.vmap(
            lambda i: jax.random.fold_in(
                jax.random.key(self.seed + 1), i))(jnp.arange(n))
        return jax.vmap(lambda v, r: TrainState.create(v, tx, r))(
            stacked, member_rngs)

    def _train_members(self, dataset, initial_variables):
        """Returns final member states (leaves stacked ``[M, ...]``)."""
        n = self.num_models
        tx = self._tx()
        states = self._member_states(initial_variables)
        step = make_train_step(self.model, self.loss, tx,
                               self.features_col, self.label_col)
        vrun = jax.vmap(make_window_runner(step))

        placement = mesh_lib.place_workers(n)
        self._member_placement = placement
        if placement.mesh is not None:
            m = placement.mesh
            # member axis sharded across the mesh for states and batches
            row = NamedSharding(m, P(mesh_lib.WORKER_AXIS))
            self._member_sharding = row
            states = mesh_lib.global_batch_from_local(row, states)
            vrun = jax.jit(vrun, in_shardings=(row, row),
                           out_shardings=(row, row))
        else:
            self._member_sharding = None
            vrun = jax.jit(vrun)

        cols = self._columns()
        # Partition ONCE (after one global shuffle so contiguous/sorted
        # datasets don't give members order-biased shards): member i
        # sees only its own 1/n of the data for the whole run — the
        # disjointness ensembling's variance reduction rests on.  Only
        # the within-shard batch order reshuffles per epoch.
        member_shards = dataset.shuffle(seed=self.seed).repartition(n)
        for epoch in range(self.num_epoch):
            t_epoch = telemetry.now()
            per_member = [
                _stack_batches(
                    s.shuffle(seed=self.seed + 13 * epoch + i),
                    self.batch_size, cols)
                for i, s in enumerate(member_shards)]
            if any(p is None for p in per_member):
                raise ValueError(
                    "a member shard is smaller than one batch")
            n_batches = min(len(next(iter(p.values())))
                            for p in per_member)
            losses = []
            for lo in range(0, n_batches, self.SCAN_CHUNK):
                # [M, chunk, B, ...]
                chunk = {
                    k: np.stack([p[k][lo:lo + self.SCAN_CHUNK]
                                 for p in per_member])
                    for k in cols}
                if placement.mesh is not None:
                    chunk = mesh_lib.global_batch_from_local(row, chunk)
                else:
                    chunk = {k: jnp.asarray(v)
                             for k, v in chunk.items()}
                states, metrics = vrun(states, chunk)
                losses.append(mesh_lib.fetch(metrics["loss"]))
            # per-member mean loss this epoch, [M]
            per_member_loss = np.concatenate(losses, axis=1).mean(
                axis=1)
            self._record(
                epoch_loss=float(per_member_loss.mean()),
                member_loss=[float(x) for x in per_member_loss])
            telemetry.complete("epoch", t_epoch, epoch=epoch,
                               trainer=type(self).__name__)
        return states

    def _guard_no_checkpoint(self, resume_from):
        if resume_from is not None or self.checkpoint_dir is not None:
            raise ValueError(
                f"{type(self).__name__} does not support checkpointing;"
                " checkpoint the member SingleTrainers instead")


class EnsembleTrainer(_MemberParallelTrainer):
    """Train ``num_models`` independent replicas (different init seeds,
    disjoint data shards) concurrently across the mesh; returns the list
    of member variable dicts (reference ``EnsembleTrainer``, SURVEY.md
    §2.3 [LOW])."""

    distinct_inits: ClassVar[bool] = True

    def _train(self, dataset, initial_variables, resume_from=None):
        self._guard_no_checkpoint(resume_from)
        states = self._train_members(dataset, initial_variables)
        # variables() first: drops the typed-rng leaf, which cannot
        # pass through numpy
        host = jax.tree_util.tree_map(mesh_lib.fetch,
                                      states.variables())
        results = [jax.tree_util.tree_map(lambda x: x[i], host)
                   for i in range(self.num_models)]
        self.trained_variables = results[0]
        self.ensemble_variables = results
        return results


class AveragingTrainer(_MemberParallelTrainer):
    """Train workers concurrently on disjoint shards from one shared
    init, then average their parameters — one-shot model averaging
    (reference ``AveragingTrainer``, SURVEY.md §2.3 [LOW])."""

    distinct_inits: ClassVar[bool] = False

    def __init__(self, model, num_workers: int = 2, **kwargs):
        super().__init__(model, num_models=num_workers, **kwargs)

    @property
    def num_workers(self) -> int:
        return self.num_models

    def _train(self, dataset, initial_variables, resume_from=None):
        self._guard_no_checkpoint(resume_from)
        states = self._train_members(dataset, initial_variables)

        # Mean over the member axis + member 0's model state, both on
        # device (one ICI reduce / slice when members are mesh-sharded)
        # so only the final values cross to host.
        def finalize(s):
            return (jax.tree_util.tree_map(lambda x: x.mean(axis=0),
                                           s.params),
                    jax.tree_util.tree_map(lambda x: x[0],
                                           s.model_state))

        row = self._member_sharding
        fin = (jax.jit(finalize, out_shardings=NamedSharding(
                   self._member_placement.mesh, P()))
               if row is not None else jax.jit(finalize))
        avg_params, member0_state = fin(states)
        self.trained_variables = {
            "params": jax.tree_util.tree_map(mesh_lib.fetch,
                                             avg_params),
            **jax.tree_util.tree_map(mesh_lib.fetch, member0_state)}
        return self.trained_variables

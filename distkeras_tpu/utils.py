"""Serialization and pytree helpers.

TPU-native re-design of the reference's ``distkeras/utils.py`` (see SURVEY.md
§2.1 "Utils": ``serialize_keras_model`` / ``deserialize_keras_model``,
``to_dense_vector``, row helpers).  Where the reference pickles a Keras
architecture-JSON + weight list, we serialize a flax module *config* + a
msgpack-encoded parameter pytree — no pickle on the wire, no Python-object
execution on deserialize.
"""

from __future__ import annotations

import json
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization as flax_serialization

Pytree = Any

# ---------------------------------------------------------------------------
# Pytree arithmetic helpers.
#
# The async parameter-server family (SURVEY.md §2.1, parameter_servers.py)
# operates on whole weight sets: delta = weights - last_pulled,
# center += delta, etc.  We express those as pure pytree ops so update rules
# stay jittable and unit-testable.
# ---------------------------------------------------------------------------


def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
    """``jax.shard_map`` across jax versions: the public alias only
    exists on newer jax; 0.4.x spells it
    ``jax.experimental.shard_map.shard_map``.  Every SPMD call site in
    the repo (ring attention, pipeline, MoE, their tests and examples)
    routes through this one name so a jax upgrade/downgrade never
    breaks the mesh paths again."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
        if "check_vma" in kwargs:  # the old spelling of the flag
            kwargs["check_rep"] = kwargs.pop("check_vma")
        # On new jax the call sites satisfy the vma checker with
        # explicit pcast(..., to="varying") bookkeeping; 0.4.x has no
        # vma types (utils.pcast is a no-op there), so its replication
        # checker sees the raw carries and rejects them.  Computation
        # is identical either way — disable the checker, which is the
        # old-jax equivalent of the casts.
        kwargs.setdefault("check_rep", False)
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **kwargs)


def axis_size(axis_name) -> int:
    """STATIC size of a named mesh axis from inside ``shard_map``,
    across jax versions: newer jax spells it ``lax.axis_size``; 0.4.x
    exposes it as ``jax.core.axis_frame`` (an int there).  Static
    matters — callers fold it into shape arithmetic (e.g. the
    sequence-parallel ``t_global`` bound check)."""
    import jax.lax as lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    import jax.core as core

    frame = core.axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size


def pcast(x, axis_names, *, to="varying"):
    """``lax.pcast`` across jax versions.  On newer jax it adjusts the
    varying-across-manual-axes type (the vma checker's bookkeeping);
    0.4.x has no vma type system, so the cast is a no-op there —
    semantically identical, since these casts only exist to satisfy
    the checker (the repo runs them under ``check_rep=False``)."""
    import jax.lax as lax
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_names, to=to)
    return x


def _host_leaf(x) -> bool:
    """True when ``x`` lives on the host as a plain numpy array (no
    tracer, no device array, no python scalar).  The host PS path runs
    these tree ops eagerly at ResNet scale, where per-leaf jax dispatch
    costs 100-300 ms/op on this runtime vs <1 ms in numpy (measured:
    62-leaf tree_add 5.7 s via jnp, 32 ms via np — PERF.md §12); numpy
    also keeps the server thread off the device entirely.  Everything
    else keeps the jnp path, so jitted update rules (and the legacy
    promotion semantics for scalars/int leaves) are untouched."""
    return isinstance(x, np.ndarray)


def _float_host(x) -> bool:
    """Host numpy leaf with a float dtype — the only leaves the
    scaled ops (axpy/lerp) take the numpy path for: a leaf-dtype
    scalar coefficient on an INT leaf would truncate (int32(0.5) == 0)
    where the jnp path promotes to float."""
    return isinstance(x, np.ndarray) and x.dtype.kind == "f"


def _binary(np_op, jnp_op):
    def op(x, y):
        if _host_leaf(x) and _host_leaf(y):
            return np_op(x, y)
        return jnp_op(x, y)
    return op


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree_util.tree_map(_binary(np.add, jnp.add), a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree_util.tree_map(_binary(np.subtract, jnp.subtract),
                                  a, b)


def tree_scale(a: Pytree, s) -> Pytree:
    return jax.tree_util.tree_map(lambda x: x * s, a)


def tree_zeros_like(a: Pytree) -> Pytree:
    return jax.tree_util.tree_map(
        lambda x: (np.zeros_like(x) if _host_leaf(x)
                   else jnp.zeros_like(x)), a)


def tree_axpy(alpha, x: Pytree, y: Pytree) -> Pytree:
    """alpha * x + y, elementwise over matching pytrees."""
    # numpy path (float leaves only): a leaf-dtype scalar keeps f32
    # leaves f32 (a bare np.asarray(alpha) would be f64 and promote
    # the whole tree)
    def op(xi, yi):
        if _float_host(xi) and _float_host(yi):
            return xi.dtype.type(alpha) * xi + yi
        return alpha * xi + yi
    return jax.tree_util.tree_map(op, x, y)


def tree_lerp(a: Pytree, b: Pytree, t) -> Pytree:
    """(1 - t) * a + t * b."""
    def op(ai, bi):
        if _float_host(ai) and _float_host(bi):
            return ai.dtype.type(1.0 - t) * ai + ai.dtype.type(t) * bi
        return (1.0 - t) * ai + t * bi
    return jax.tree_util.tree_map(op, a, b)


def tree_dot(a: Pytree, b: Pytree):
    """Sum of elementwise products across the whole pytree (a scalar)."""
    leaves = jax.tree_util.tree_map(lambda x, y: jnp.sum(x * y), a, b)
    return jax.tree_util.tree_reduce(jnp.add, leaves)


def tree_l2_norm(a: Pytree):
    return jnp.sqrt(tree_dot(a, a))


def tree_size(a: Pytree) -> int:
    """Total number of scalar parameters in the pytree."""
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(a))


def tree_cast(a: Pytree, dtype) -> Pytree:
    return jax.tree_util.tree_map(lambda x: x.astype(dtype), a)


# ---------------------------------------------------------------------------
# Model serialization.
# ---------------------------------------------------------------------------


def serialize_params(params: Pytree) -> bytes:
    """Parameter pytree -> msgpack bytes (flax canonical encoding)."""
    return flax_serialization.to_bytes(params)


def deserialize_params(template: Pytree, data: bytes) -> Pytree:
    """msgpack bytes -> parameter pytree shaped like ``template``."""
    return flax_serialization.from_bytes(template, data)


def serialize_model_config(config: Mapping[str, Any]) -> str:
    """Architecture config dict -> JSON (the analogue of Keras to_json())."""
    return json.dumps(config, sort_keys=True)


def deserialize_model_config(payload: str) -> dict:
    return json.loads(payload)


# ---------------------------------------------------------------------------
# Label / feature helpers (reference: utils.to_dense_vector, new_dataframe_row).
# ---------------------------------------------------------------------------


def to_dense_vector(label, num_classes: int) -> np.ndarray:
    """Integer label(s) -> one-hot float32 vector(s)."""
    label = np.asarray(label, dtype=np.int32)
    if label.size and (label.min() < 0 or label.max() >= num_classes):
        raise ValueError(
            f"labels must be in [0, {num_classes}), got range "
            f"[{label.min()}, {label.max()}]")
    return np.eye(num_classes, dtype=np.float32)[label]


def shuffle(arrays: Mapping[str, np.ndarray], seed: int = 0) -> dict:
    """Shuffle a column dict in unison (reference: utils.shuffle(df))."""
    n = len(next(iter(arrays.values())))
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    return {k: np.asarray(v)[perm] for k, v in arrays.items()}


def batch_iterator(arrays: Mapping[str, np.ndarray], batch_size: int,
                   drop_remainder: bool = True):
    """Yield dicts of aligned batches from a column dict."""
    n = len(next(iter(arrays.values())))
    stop = (n // batch_size) * batch_size if drop_remainder else n
    for start in range(0, stop, batch_size):
        yield {k: v[start:start + batch_size] for k, v in arrays.items()}


def pad_to_multiple(x: np.ndarray, multiple: int, axis: int = 0) -> np.ndarray:
    """Pad ``axis`` up to the next multiple (static shapes for XLA)."""
    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x
    pad_width = [(0, 0)] * x.ndim
    pad_width[axis] = (0, rem)
    return np.pad(x, pad_width)

"""Job deployment: launch a multi-process training job.

TPU-native analogue of the reference's experimental ``job_deployment.py``
(SURVEY.md §2.1 [MED]: SSH-based submission of a training job to a remote
Spark cluster).  Here a "job" is one command run as N cooperating
``jax.distributed`` processes:

* ``launch_local`` — N processes on this host (each seeing a slice of
  the local devices, or a forced CPU mesh): the substrate for multi-host
  integration tests and the direct analogue of the reference testing via
  Spark ``local[N]``.
* ``TPUPodJob`` — the command set a real TPU pod launch needs (one
  process per host via ``gcloud compute tpus tpu-vm ssh --worker=all``).
  With no network egress in this environment it only *builds* the
  commands (``dry_run=True``); running them requires a real pod.

Processes find each other through the ``JAX_COORDINATOR_ADDRESS`` /
``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` environment variables that
``distkeras_tpu.mesh.initialize_cluster`` reads.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import os
import shlex
import socket
import subprocess
import sys
from typing import Mapping, Sequence


@dataclasses.dataclass
class ProcessResult:
    process_id: int
    returncode: int
    stdout: str
    stderr: str


@dataclasses.dataclass
class JobSpec:
    """One multi-process job: ``argv`` is run once per process with the
    coordination env vars injected."""

    argv: Sequence[str]
    num_processes: int = 1
    env: Mapping[str, str] = dataclasses.field(default_factory=dict)
    cwd: str | None = None
    timeout_s: float = 900.0


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_local(spec: JobSpec, check: bool = True
                 ) -> list[ProcessResult]:
    """Run ``spec.argv`` as ``num_processes`` local cooperating processes.

    Returns per-process results (ordered by process id).  With ``check``,
    raises ``RuntimeError`` carrying every process's output if any exits
    nonzero — the whole job is one unit, like a Spark stage.
    """
    coord = f"127.0.0.1:{free_port()}"
    procs = []
    for i in range(spec.num_processes):
        env = {**os.environ, **spec.env,
               "JAX_COORDINATOR_ADDRESS": coord,
               "JAX_NUM_PROCESSES": str(spec.num_processes),
               "JAX_PROCESS_ID": str(i)}
        procs.append(subprocess.Popen(
            list(spec.argv), env=env, cwd=spec.cwd,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    # Drain every process concurrently: a sequential communicate() loop
    # deadlocks the job the moment a not-yet-reaped process fills its
    # ~64KiB pipe buffer while its peers block on a collective.
    results = []
    try:
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=spec.num_processes) as pool:
            futs = [pool.submit(p.communicate, timeout=spec.timeout_s)
                    for p in procs]
            for i, (p, f) in enumerate(zip(procs, futs)):
                out, err = f.result()
                results.append(ProcessResult(i, p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if check and any(r.returncode for r in results):
        detail = "\n".join(
            f"--- process {r.process_id} (rc={r.returncode}) ---\n"
            f"{r.stdout}\n{r.stderr}" for r in results)
        raise RuntimeError(f"local job failed:\n{detail}")
    return results


def run_multiprocess(script: str, num_processes: int,
                     args: Sequence[str] = (),
                     env: Mapping[str, str] | None = None,
                     timeout_s: float = 900.0) -> list[ProcessResult]:
    """Convenience wrapper: run a Python script as an N-process job with
    this interpreter."""
    spec = JobSpec(argv=[sys.executable, script, *args],
                   num_processes=num_processes, env=env or {},
                   timeout_s=timeout_s)
    return launch_local(spec)


@dataclasses.dataclass
class TPUPodJob:
    """Builds the gcloud command to run one process per pod host.

    ``jax.distributed.initialize`` auto-detects coordinator/process-id on
    TPU VMs, so the remote command needs no env injection.
    """

    tpu_name: str
    zone: str
    command: Sequence[str]
    project: str | None = None

    def build_command(self) -> list[str]:
        remote = " ".join(shlex.quote(c) for c in self.command)
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh",
               self.tpu_name, f"--zone={self.zone}", "--worker=all",
               f"--command={remote}"]
        if self.project:
            cmd.insert(1, f"--project={self.project}")
        return cmd

    def submit(self, dry_run: bool = True):
        cmd = self.build_command()
        if dry_run:
            return cmd
        import shutil

        if shutil.which("gcloud") is None:
            raise RuntimeError(
                "gcloud not available (no network egress in this "
                "environment); use submit(dry_run=True) to inspect the "
                "command and run it from a workstation with access")
        return subprocess.run(cmd, check=True)

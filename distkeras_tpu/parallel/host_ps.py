"""Host-side concurrent parameter server — the *faithful* async arm
(design 5a of SURVEY.md §7: "host-side PS process, per-host async client
threads, faithful staleness behavior").

Where ``ps_emulator`` compiles the whole commit round into one XLA
program with *deterministic* staleness, this module runs the reference's
actual concurrency model: worker threads free-running against a central
server whose commits are serialized by a mutex, staleness emerging from
real scheduling races (SURVEY.md §2.1 SocketParameterServer: accept
loop, handler per connection, lock around center updates).  It reuses
the very same ``UpdateRule`` objects as the emulator — the server law,
payload kind, window normalization and pull law are shared code — which
is what makes the two arms comparable: any convergence difference is
attributable to staleness semantics, not to reimplemented math
(VERDICT.md round-1 Missing #4).

Two transports:
* in-process — workers call the server object directly (the common case:
  one host, threads driving device steps);
* socket — a TCP server thread speaking the L1 framing
  (``parallel.transport``): single-byte commands ``b"p"`` (pull) /
  ``b"c"`` (commit payload) / ``b"s"`` (stop).  Raw parameter
  payloads ride ``pack_params``'s template-implied encoding
  (concatenated leaf bytes in canonical pytree order — both endpoints
  hold the same template, so the wire carries only data; ~10x faster
  than the earlier msgpack encoding at ResNet scale, PERF.md §12);
  compressed commits ride the negotiated codec's bytes.  The
  reference's wire protocol, minus pickle.
"""

from __future__ import annotations

import contextlib
import os
import socket
import threading
import time
from typing import Any, Callable, Optional

import jax
import numpy as np

from distkeras_tpu import flight_recorder, telemetry
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.parallel import transport
from distkeras_tpu.parallel.update_rules import PSState, UpdateRule
from distkeras_tpu.utils import tree_add

def _to_numpy(tree: Pytree) -> Pytree:
    return jax.tree_util.tree_map(np.asarray, tree)


def _readonly_view(x: np.ndarray) -> np.ndarray:
    """A no-copy read-only view: what ``pull``/``commit`` hand out so
    the in-process arm cannot alias-and-mutate server state (arrays
    built on read-only buffers — ``frombuffer`` views — already are)."""
    if not x.flags.writeable:
        return x
    v = x.view()
    v.flags.writeable = False
    return v


def _readonly_tree(tree: Pytree) -> Pytree:
    return jax.tree_util.tree_map(_readonly_view, tree)


def pack_params(tree, template=None) -> bytes:
    """Raw-buffer wire encoding: leaves in canonical pytree order,
    concatenated ``tobytes()``.  Shapes/dtypes ride the TEMPLATE both
    endpoints already hold (PSServer and PSClient are constructed with
    the same center tree), so the wire carries only data — measured
    ~10x faster than the msgpack path at ResNet-18 scale (45 MB:
    ~13 ms pack vs 132 ms serialize; unpack is zero-copy views vs
    47 ms), which matters because serialization IS the PS ceiling
    (PERF.md §12).  ``template`` casts each leaf to the wire dtype
    (the msgpack path did the cast on the receive side; e.g. a worker
    computing f64 deltas against an f32 center)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if template is not None:
        temps = jax.tree_util.tree_leaves(template)
        if len(temps) != len(leaves):
            raise ValueError(
                f"payload has {len(leaves)} leaves, template "
                f"{len(temps)}")
        leaves = [np.asarray(x, dtype=t.dtype)
                  for x, t in zip(leaves, temps)]
    return b"".join(
        np.ascontiguousarray(x).tobytes() for x in leaves)


def unpack_params(template, data: bytes):
    """Inverse of ``pack_params``: zero-copy ``frombuffer`` views
    sliced per the template's leaf shapes/dtypes (read-only arrays —
    every consumer treats pulled/committed trees as immutable)."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    buf = memoryview(data)
    out, off = [], 0
    for t in leaves:
        n = int(t.nbytes)
        arr = np.frombuffer(buf[off:off + n],
                            dtype=t.dtype).reshape(t.shape)
        out.append(arr)
        off += n
    if off != len(data):
        raise ValueError(
            f"wire payload is {len(data)} bytes but the template "
            f"expects {off} (mismatched model between worker and PS)")
    return jax.tree_util.tree_unflatten(treedef, out)

Pytree = Any

# Wire value for "no sequence number" (dedupe off) in commit frames.
_NO_SEQ = 2 ** 64 - 1


class PSFencedError(ConnectionError):
    """The server refused a commit because it has been deposed: a newer
    primary holds a higher replication epoch (``replicated_ps``).  A
    deposed primary must reject rather than apply — two servers
    applying commits for the same training run is a split brain.
    Subclasses ``ConnectionError`` so ``ResilientPSClient`` treats it
    like a dead server and fails over to the next replica address."""



class PSShardFencedError(PSFencedError):
    """One SHARD refused the op — its fencing epoch moved or the
    client's shard-map version is stale (``elastic_ps``: a split,
    merge or migration changed the routing table).  Unlike a node-epoch
    fence, the server is healthy and the fix is routing, not failover:
    ``ResilientPSClient`` refreshes the shard map (``map_obj`` rides
    the rejection when the server attached its current map) and
    retries against the new owner WITHOUT burning a retry attempt."""

    def __init__(self, message: str, *, shard: int | None = None,
                 map_obj: Any = None):
        super().__init__(message)
        self.shard = shard
        self.map_obj = map_obj


class HostParameterServer:
    """Threaded central state: ``pull``/``commit`` under a mutex.

    Staleness bookkeeping matches the reference DynSGD server: a global
    commit clock; a commit's staleness is the number of commits applied
    since the committing worker's last pull (SURVEY.md §2.1
    DynSGDParameterServer).

    ``staleness_log`` keeps only the last ``STALENESS_LOG_WINDOW``
    entries (a long run would otherwise grow one int per commit
    forever); the unbounded-horizon record is the
    ``ps_commit_staleness`` telemetry histogram, which aggregates
    without growing.
    """

    #: entries retained in ``staleness_log`` (the newest ones); the
    #: telemetry histogram is the full-horizon record.  Trimming is
    #: amortized: the list briefly overshoots by 25% before a cut.
    STALENESS_LOG_WINDOW = 100_000

    def __init__(self, rule: UpdateRule, center: Pytree, *,
                 snapshot_path: str | os.PathLike | None = None,
                 snapshot_every: int = 0):
        """``snapshot_path`` + ``snapshot_every=N``: every N-th commit
        atomically writes a warm-restart snapshot (center + clocks +
        commit-seq dedupe table) BEFORE the commit's reply is released
        — so with ``snapshot_every=1`` every acked commit is durable
        and a kill/restart cycle is exactly-once end to end (a commit
        applied-but-unacked is either in the snapshot, in which case
        the retry dedupes, or lost with the snapshot, in which case
        the retry re-applies it once).  Larger N amortizes the write:
        commits after the last snapshot are recovered only if the
        client retries them (unacked); acked ones are rolled back."""
        self.rule = rule
        self._lock = racecheck.lock("host_ps")
        self._center = _to_numpy(center)  # guarded-by: _lock
        self._clock = 0  # guarded-by: _lock
        self._pull_clock: dict[int, int] = {}
        self.staleness_log: list[int] = []
        self.num_commits = 0
        self.num_snapshots = 0
        self._snapshot_path = snapshot_path
        self._snapshot_every = int(snapshot_every)
        if self._snapshot_every and snapshot_path is None:
            raise ValueError(
                "snapshot_every needs a snapshot_path to write to")
        self._last_seen: dict[int, float] = {}
        # worker -> (seq, packed reply bytes).  Packed — not a live
        # tree — so the cache's footprint is explicit and measurable
        # (``ps_reply_cache_bytes`` gauge) instead of a hidden full
        # param copy per worker pinned by aliasing.
        self._last_reply: dict[int, tuple[int, bytes]] = {}
        self._reply_bytes = 0
        # replication (replicated_ps): fencing epoch stamped on the
        # wire, the deposed flag, and the primary-side log shipper.
        # Written rarely (attach/promotion/demotion) and read inside
        # the commit lock; plain attributes by design.
        self.epoch = 0
        self._fenced = False
        self._replicator = None

    # -- the two verbs -----------------------------------------------------

    def pull(self, worker_id: int) -> Pytree:
        """Returns READ-ONLY views of the center (no copy): the
        in-process arm must not be able to mutate server state through
        the pulled tree (every consumer treats pulls as immutable; the
        views enforce it)."""
        telemetry.metrics().counter("ps_pulls_total").inc()
        with self._lock:
            self._pull_clock[worker_id] = self._clock
            self._last_seen[worker_id] = telemetry.now()
            return _readonly_tree(self._center)

    def commit(self, worker_id: int, payload: Pytree,
               local: Pytree | None = None,
               seq: int | None = None) -> Pytree:
        """Apply one commit; returns the worker's new local params (the
        rule's pull law, evaluated against the same center the server
        used — commit-and-pull is one atomic exchange, as in the
        reference where the handler thread holds the connection).

        ``seq`` is the worker's commit sequence number (monotonic per
        worker), used to dedupe retries: when a commit was applied but
        its *reply* was lost (a socket dying between apply and ack),
        the retried commit carries the same seq and gets the cached
        reply back instead of applying the window's delta twice —
        at-most-once application.  Any ``seq <=`` the worker's last
        applied seq is a duplicate (a straggler handler can deliver an
        old retransmit arbitrarily late); stragglers older than the
        last commit get the cached latest reply, which lands on a dead
        connection anyway."""
        # normalize to host numpy up front: the in-process arm hands
        # jax arrays straight in, which would silently push the apply
        # back onto the eager per-leaf jnp path the numpy fast path
        # exists to avoid (PERF.md §12)
        payload = _to_numpy(payload)
        if local is not None:
            local = _to_numpy(local)
        m = telemetry.metrics()
        # the span encloses the mutex wait, so its duration shows both
        # apply cost and serialization contention on the timeline
        with telemetry.span("ps_commit", worker=worker_id), self._lock:
            if self._fenced:
                raise PSFencedError(
                    f"commit rejected: this server was deposed (a "
                    f"newer primary holds epoch > {self.epoch})")
            if seq is not None:
                last = self._last_reply.get(worker_id)
                if last is not None and seq <= last[0]:
                    self._last_seen[worker_id] = telemetry.now()
                    m.counter("ps_commit_dedup_total").inc()
                    # lint: allow(blocking-call-under-lock): the dedup
                    # decision must hit the flight log before the
                    # cached reply escapes (acked => recorded)
                    flight_recorder.record("commit_dedup",
                                           worker=worker_id, seq=seq)
                    return unpack_params(self._center, last[1])
            staleness = self._clock - self._pull_clock.get(worker_id, 0)
            state = PSState(center=self._center,
                            clock=np.int32(self._clock))
            new_state = self.rule.commit(
                state, payload, np.int32(staleness))
            pulled = self.rule.worker_pull(
                local, state.center, new_state.center)
            self._center = _to_numpy(new_state.center)
            self._clock += 1
            self._pull_clock[worker_id] = self._clock
            self.staleness_log.append(int(staleness))
            if len(self.staleness_log) > self.STALENESS_LOG_WINDOW * 5 // 4:
                del self.staleness_log[:-self.STALENESS_LOG_WINDOW]
            self.num_commits += 1
            self._last_seen[worker_id] = telemetry.now()
            m.counter("ps_commits_total").inc()
            m.histogram("ps_commit_staleness",
                        buckets=telemetry.STALENESS_BUCKETS
                        ).observe(int(staleness))
            # lint: allow(blocking-call-under-lock): acked => durable —
            # the commit event must be on disk before the reply leaves
            # the lock (the warm-restart story depends on it)
            flight_recorder.record("commit", worker=worker_id, seq=seq,
                                   clock=self._clock,
                                   staleness=int(staleness))
            pulled = _to_numpy(pulled)
            reply_packed = b""
            if seq is not None:
                reply_packed = pack_params(pulled)
                self._cache_reply_locked(worker_id, seq, reply_packed)
            if self._replicator is not None:
                # inside the lock, BEFORE the reply escapes: in sync
                # ack mode an acked commit is already on the standbys
                # (exactly-once across failover depends on it); a
                # fenced shipper raises here and the reply never leaves
                self._replicator.replicate(
                    kind="commit", worker=worker_id,
                    payload=pack_params(payload, self._center),
                    seq=_NO_SEQ if seq is None else int(seq),
                    staleness=int(staleness), reply=reply_packed)
            if (self._snapshot_every
                    and self.num_commits % self._snapshot_every == 0):
                # inside the lock, BEFORE the reply escapes: an acked
                # commit is durable (see __init__)
                self._write_snapshot_locked()
            return _readonly_tree(pulled)

    def _cache_reply_locked(self, worker_id: int, seq: int,
                            packed: bytes) -> None:
        old = self._last_reply.get(worker_id)
        if old is not None:
            self._reply_bytes -= len(old[1])
        self._last_reply[worker_id] = (seq, packed)
        self._reply_bytes += len(packed)
        telemetry.metrics().gauge("ps_reply_cache_bytes").set(
            self._reply_bytes)

    def commit_packed(self, worker_id: int, payload: Pytree,
                      local: Pytree | None = None,
                      seq: int | None = None) -> bytes:
        """``commit`` returning the WIRE encoding of the reply
        (``pack_params`` bytes): the socket handler's path, which packs
        exactly once — the same bytes land in the dedupe cache and on
        the socket (a dedupe hit returns the cached bytes with no
        repack at all)."""
        pulled = self.commit(worker_id, payload, local, seq=seq)
        if seq is not None:
            # commit() just cached this reply's pack — reuse it (one
            # pack per commit, shared between cache and wire)
            with self._lock:
                last = self._last_reply.get(worker_id)
                if last is not None and last[0] == seq:
                    return last[1]
        return pack_params(pulled)

    def commit_group(self, leader_id: int, fold: Pytree,
                     staleness, workers,
                     seq: int | None = None) -> Pytree:
        """Apply one pre-reduced group window from a ``hier_ps``
        leader: ``fold`` is the sum of ``len(workers)`` already-scaled
        delta commits (the leader ran the rule's server law per
        constituent), so the root applies it with a plain
        ``center += fold`` and advances its clock by the constituent
        count.  ``staleness`` is the per-worker staleness vector the
        leader measured — logged and histogrammed here so the root's
        staleness record stays faithful to what the rule actually
        scaled by.

        ``seq`` dedupes per LEADER (leader ids live in their own
        ``HIER_LEADER_BASE`` space): a lost-ack upstream retry gets
        the cached center back instead of double-applying the window —
        exactly-once end to end.  Returns the new center (the leader's
        next mirror)."""
        if self.rule.payload_kind != "delta":
            raise ValueError(
                f"hierarchical aggregation needs a delta-family "
                f"rule; {type(self.rule).__name__} commits "
                f"{self.rule.payload_kind!r} payloads")
        fold = _to_numpy(fold)
        n = len(workers)
        staleness = [int(s) for s in staleness]
        m = telemetry.metrics()
        with telemetry.span("ps_commit", worker=leader_id,
                            fanin=n), self._lock:
            if self._fenced:
                raise PSFencedError(
                    f"commit rejected: this server was deposed (a "
                    f"newer primary holds epoch > {self.epoch})")
            if self._replicator is not None:
                raise RuntimeError(
                    "hierarchical upstream commits do not compose "
                    "with primary/standby replication (the standby "
                    "replay re-runs the rule's single-commit law, "
                    "not the group fold)")
            if seq is not None:
                last = self._last_reply.get(leader_id)
                if last is not None and seq <= last[0]:
                    self._last_seen[leader_id] = telemetry.now()
                    m.counter("ps_commit_dedup_total").inc()
                    # lint: allow(blocking-call-under-lock): the dedup
                    # decision must hit the flight log before the
                    # cached reply escapes (acked => recorded)
                    flight_recorder.record("commit_dedup",
                                           worker=leader_id, seq=seq)
                    return unpack_params(self._center, last[1])
            self._center = _to_numpy(tree_add(self._center, fold))
            self._clock += n
            self._pull_clock[leader_id] = self._clock
            self.staleness_log.extend(staleness)
            if len(self.staleness_log) > \
                    self.STALENESS_LOG_WINDOW * 5 // 4:
                del self.staleness_log[:-self.STALENESS_LOG_WINDOW]
            before = self.num_commits
            self.num_commits += n
            self._last_seen[leader_id] = telemetry.now()
            m.counter("ps_commits_total").inc(n)
            m.counter("ps_upstream_commits_total").inc()
            m.gauge("ps_fanin_reduction").set(n)
            hist = m.histogram("ps_commit_staleness",
                               buckets=telemetry.STALENESS_BUCKETS)
            for s in staleness:
                hist.observe(s)
            # lint: allow(blocking-call-under-lock): acked => durable,
            # same contract as the single-commit path
            flight_recorder.record(
                "commit", worker=leader_id, seq=seq,
                clock=self._clock, fanin=n,
                staleness=max(staleness, default=0))
            if seq is not None:
                self._cache_reply_locked(leader_id, seq,
                                         pack_params(self._center))
            if (self._snapshot_every
                    and self.num_commits // self._snapshot_every
                    > before // self._snapshot_every):
                # the clock jumps by n; snapshot on every crossed
                # boundary, not just exact multiples
                self._write_snapshot_locked()
            return _readonly_tree(self._center)

    @property
    def center(self) -> Pytree:
        with self._lock:
            return _readonly_tree(self._center)

    def register(self, worker_id: int) -> None:
        """Start liveness monitoring before first contact, so a worker
        that hangs before ever reaching the server is still flagged by
        ``idle_workers`` instead of being invisible."""
        with self._lock:
            self._last_seen.setdefault(worker_id, telemetry.now())
            n = len(self._last_seen)
        telemetry.metrics().gauge("ps_registered_workers").set(n)

    def retire(self, worker_id: int) -> None:
        """A worker finished cleanly: stop monitoring it (so
        ``idle_workers`` never flags it) and drop its dedupe reply."""
        with self._lock:
            self._last_seen.pop(worker_id, None)
            dropped = self._last_reply.pop(worker_id, None)
            if dropped is not None:
                self._reply_bytes -= len(dropped[1])
                telemetry.metrics().gauge("ps_reply_cache_bytes").set(
                    self._reply_bytes)

    def clear_reply_cache(self) -> None:
        """Drop all cached dedupe replies (a full packed param copy
        per worker) — for when no client can retry anymore."""
        with self._lock:
            self._last_reply.clear()
            self._reply_bytes = 0
            telemetry.metrics().gauge("ps_reply_cache_bytes").set(0)

    def idle_workers(self, timeout: float) -> list[int]:
        """Failure *detection* (SURVEY.md §5 row the reference left
        empty): workers silent — no pull or commit — for more than
        ``timeout`` seconds.  PS traffic is the natural heartbeat: an
        alive PS-family worker contacts the server every communication
        window; one that is silent is stalled, partitioned, or dead.

        Heartbeats are stamped with ``telemetry.now()`` — the same
        monotonic clock as every other host timestamp in the repo —
        so idleness compares cleanly against serving/trainer spans."""
        now = telemetry.now()
        with self._lock:
            idle = sorted(w for w, seen in self._last_seen.items()
                          if now - seen > timeout)
            n = len(self._last_seen)
        telemetry.metrics().gauge("ps_idle_workers").set(len(idle))
        telemetry.metrics().gauge("ps_registered_workers").set(n)
        return idle

    def last_acked_seqs(self) -> dict[int, int]:
        """Per-worker last acked commit seq — the dedupe table's view,
        i.e. the at-most-once state a warm restart carries forward.
        ``scripts/postmortem.py`` cross-checks this against the flight
        recorder's pre-crash record."""
        with self._lock:
            return {int(w): int(seq)
                    for w, (seq, _) in self._last_reply.items()}

    # -- replication (replicated_ps) --------------------------------------

    def attach_replicator(self, replicator) -> None:
        """Install the primary-side log shipper: every applied commit
        is replayed to the standbys from inside the commit lock (sync
        ack mode blocks the reply on the standby acks)."""
        with self._lock:
            self._replicator = replicator

    def fence(self, epoch: int) -> None:
        """Depose this server: a newer primary (higher ``epoch``) owns
        the training run now.  Every later commit raises
        ``PSFencedError`` — the client's cue to fail over."""
        with self._lock:
            self._fenced = True
            self.epoch = max(self.epoch, int(epoch))
        telemetry.metrics().counter("ps_fenced_total").inc()

    def apply_replicated(self, worker_id: int, payload: bytes,
                         seq: int | None, staleness: int,
                         reply: bytes) -> None:
        """Standby-side replay of one primary commit: re-runs the
        rule's server law against the SHIPPED payload and staleness
        (not locally derived — the standby replays the primary's
        decisions, so its center is byte-identical) and installs the
        primary's cached reply bytes, keeping the dedupe table
        replicated — a client retrying across the failover boundary
        dedupes on the promoted standby exactly as it would have on
        the dead primary."""
        with self._lock:
            tree = unpack_params(self._center, payload)
            state = PSState(center=self._center,
                            clock=np.int32(self._clock))
            new_state = self.rule.commit(state, tree,
                                         np.int32(staleness))
            self._center = _to_numpy(new_state.center)
            self._clock += 1
            self._pull_clock[worker_id] = self._clock
            self.staleness_log.append(int(staleness))
            if len(self.staleness_log) > \
                    self.STALENESS_LOG_WINDOW * 5 // 4:
                del self.staleness_log[:-self.STALENESS_LOG_WINDOW]
            self.num_commits += 1
            if seq is not None:
                self._cache_reply_locked(worker_id, int(seq),
                                         bytes(reply))
            if (self._snapshot_every
                    and self.num_commits % self._snapshot_every == 0):
                self._write_snapshot_locked()

    def replication_snapshot(self, head_fn) -> tuple[int, dict]:
        """A ``(replication-log head seq, snapshot dict)`` pair that is
        CONSISTENT: both are read under the commit lock, where every
        log-seq assignment also happens, so the snapshot contains
        exactly the commits through ``head`` — the correctness
        condition for bootstrapping a standby (``head_fn`` is the
        replicator's ``head_seq``; lock order stays PS -> replicator,
        same as the in-commit ship path)."""
        with self._lock:
            return int(head_fn()), self._snapshot_locked()

    # -- snapshot / warm restart ------------------------------------------

    def _snapshot_locked(self) -> dict:
        # numpy leaves are replaced, never mutated, by commit — shallow
        # references are a consistent point-in-time copy under the lock
        return {
            "center": self._center,
            "epoch": self.epoch,
            "clock": self._clock,
            "num_commits": self.num_commits,
            "pull_clock": {str(w): c
                           for w, c in self._pull_clock.items()},
            "staleness_log": np.asarray(self.staleness_log, np.int64),
            "last_reply": {str(w): {"seq": np.uint64(seq),
                                    "packed": packed}
                           for w, (seq, packed)
                           in self._last_reply.items()},
        }

    def snapshot(self) -> dict:
        """Point-in-time warm-restart state: center, commit clock,
        per-worker pull clocks, staleness log, and the commit-seq
        dedupe table (``last_reply`` — WITHOUT it a restarted server
        would re-apply a retried commit whose ack was lost)."""
        with self._lock:
            return self._snapshot_locked()

    def _write_snapshot_locked(self) -> None:
        from distkeras_tpu import checkpoint as ckpt

        with telemetry.span("ps_snapshot", commits=self.num_commits):
            ckpt.save_ps_snapshot(self._snapshot_path,
                                  self._snapshot_locked())
        self.num_snapshots += 1
        telemetry.metrics().counter("ps_snapshots_total").inc()
        flight_recorder.record(
            "snapshot", path=os.fspath(self._snapshot_path),
            num_commits=self.num_commits,
            last_acked={str(w): int(seq)
                        for w, (seq, _) in self._last_reply.items()})

    def save_snapshot(self, path: str | os.PathLike) -> str:
        """Write ``snapshot()`` atomically (``checkpoint`` machinery:
        tmp + rename, msgpack encoding) — never observed half-written."""
        from distkeras_tpu import checkpoint as ckpt

        return ckpt.save_ps_snapshot(path, self.snapshot())

    @classmethod
    def from_snapshot(cls, rule: UpdateRule,
                      snapshot: dict | str | os.PathLike, *,
                      snapshot_path: str | os.PathLike | None = None,
                      snapshot_every: int = 0) -> "HostParameterServer":
        """Warm-restart a server from ``snapshot()`` output or a path
        written by ``save_snapshot``/periodic snapshotting.  The rule
        must match the one that produced the snapshot (the center IS
        the rule's durable state; the commit clock and dedupe table
        restore staleness bookkeeping and at-most-once semantics for
        reconnecting clients)."""
        if isinstance(snapshot, (str, os.PathLike)):
            from distkeras_tpu import checkpoint as ckpt

            snapshot = ckpt.load_ps_snapshot(snapshot)
        if "sharded" in snapshot:
            raise ValueError(
                "this snapshot came from a ShardedParameterServer "
                f"(K={int(snapshot['sharded'])}); restore it with "
                "sharded_ps.ShardedParameterServer.from_snapshot")
        ps = cls(rule, snapshot["center"], snapshot_path=snapshot_path,
                 snapshot_every=snapshot_every)
        ps.epoch = int(snapshot.get("epoch", 0))
        ps._clock = int(snapshot["clock"])
        ps.num_commits = int(snapshot["num_commits"])
        ps._pull_clock = {int(w): int(c) for w, c
                          in snapshot["pull_clock"].items()}
        ps.staleness_log = [int(s) for s
                            in np.asarray(snapshot["staleness_log"])]
        for w, e in snapshot["last_reply"].items():
            packed = (bytes(e["packed"]) if "packed" in e
                      else pack_params(e["pulled"]))  # pre-round-8 file
            ps._cache_reply_locked(int(w), int(e["seq"]), packed)
        return ps


class PSServer:
    """TCP front end for a ``HostParameterServer``.

    Protocol (all messages framed by ``transport``): first message on a
    connection is the worker id (4-byte big-endian int).  Then requests:
    ``b"p"`` -> center params; ``b"c" + 8-byte seq + params`` (+
    optional second frame with local params for pull-uses-local rules)
    -> new local params, where ``seq`` is the worker's monotonic commit
    counter (dedupes retried commits whose ack was lost; the all-ones
    value means "no seq" and disables dedupe for that commit).  ``b"s"``
    shuts the server down.
    """

    def __init__(self, ps, template: Pytree,
                 host: str = "127.0.0.1", port: int = 0,
                 sock: socket.socket | None = None):
        """``ps`` is a ``HostParameterServer`` or a
        ``sharded_ps.ShardedParameterServer`` — the latter additionally
        serves the shard-addressed ops ``b"P"`` (version-delta pull)
        and ``b"C"`` (per-shard commit) over the zero-copy
        scatter-gather wire (``transport.send_msg_gather`` /
        ``recv_msg_into``); the classic ``b"p"``/``b"c"`` verbs keep
        working against either server.

        The handshake frame is ``4-byte worker id`` optionally
        followed by a codec name (``parallel.compression``): commits on
        that connection then arrive codec-encoded instead of via the
        raw template-implied ``pack_params`` encoding — the wire-compression arm."""
        self.ps = ps
        self._template = _to_numpy(template)
        # duck-typed (no import cycle): the sharded server exposes the
        # per-shard verbs and its plan
        self._sharded = getattr(ps, "num_shards", 1) > 1 or \
            hasattr(ps, "pull_since")
        if self._sharded:
            tleaves = jax.tree_util.tree_leaves(self._template)
            self._shard_templates = [[tleaves[i] for i in idx]
                                     for idx in ps.plan]
        if sock is not None:
            # a pre-bound (not yet listening) socket: replicated_ps
            # reserves each replica's advertised worker port at
            # construction and hands it over at promotion time
            self._sock = sock
        else:
            self._sock = socket.socket()
            self._sock.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
            self._sock.bind((host, port))
        self._sock.listen()
        self.address = self._sock.getsockname()
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True)

    def start(self) -> "PSServer":
        self._accept_thread.start()
        return self

    def _accept_loop(self):
        try:
            # inside the try: kill() may close the socket before this
            # thread gets scheduled, and that race must not traceback
            try:
                self._sock.settimeout(0.2)
            except OSError:
                return
            while not self._stop.is_set():
                try:
                    conn, _ = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                1)
                self._conns.append(conn)
                t = threading.Thread(target=self._serve, args=(conn,),
                                     daemon=True)
                t.start()
                self._threads.append(t)
        finally:
            # however the loop exits (stop() or the b"s" command), stop
            # listening — a bound-but-dead port accepts TCP connects
            # from health checks/reconnects that then hang
            try:
                self._sock.close()
            except OSError:
                pass

    def _serve(self, conn: socket.socket):
        # per-direction wire totals (message bodies; the 4-byte frame
        # headers are omitted — negligible against parameter payloads)
        rx = telemetry.metrics().counter("ps_wire_bytes_total",
                                         direction="rx")
        tx = telemetry.metrics().counter("ps_wire_bytes_total",
                                         direction="tx")
        with conn:
            try:
                hello = transport.recv_msg(conn)
                rx.inc(len(hello))
                worker_id = int.from_bytes(hello[:4], "big")
                codec = None
                if len(hello) > 4:
                    from distkeras_tpu.parallel.compression import (
                        resolve_codec)

                    codec = resolve_codec(hello[4:].decode())
                while True:
                    msg = transport.recv_msg_into(conn)
                    rx.inc(len(msg))
                    # optional 17-byte trace-context header (zero bytes
                    # when client tracing is off): link the handler
                    # span back to the client span and complete the
                    # client→server flow arrow
                    link, msg = transport.split_trace_header(msg)
                    cmd, body = bytes(msg[:1]), msg[1:]
                    with contextlib.ExitStack() as rpc:
                        if link is not None:
                            rpc.enter_context(telemetry.span(
                                "ps_rpc", cmd=cmd.decode(),
                                worker=worker_id,
                                link_trace=format(link[0], "x"),
                                link_span=format(link[1], "x")))
                            telemetry.flow_end("wire", link[1],
                                               cmd=cmd.decode())
                        self._dispatch(conn, worker_id, codec, cmd,
                                       body, rx, tx)
                        if self._stop.is_set():
                            return
            except PSFencedError as e:
                # deposed primary: refuse the commit and drop the
                # connection — the client's ConnectionError sends it
                # to the next replica address.  Recorded (not printed):
                # fencing is the protocol working, not a handler bug.
                flight_recorder.record("ps_fenced", worker=worker_id,
                                       detail=str(e))
                return
            except (ConnectionError, OSError):
                return  # client gone; reference handlers did the same
            except Exception as e:
                # malformed frame / decode failure: drop the connection
                # with a diagnostic instead of dying silently (the
                # client sees a ConnectionError and retries/fails)
                import sys

                print(f"[distkeras_tpu] PS handler error (worker "
                      f"connection dropped): {e!r}", file=sys.stderr,
                      flush=True)
                return

    def _dispatch(self, conn: socket.socket, worker_id: int, codec,
                  cmd: bytes, body, rx, tx) -> None:
        """One request: dispatch ``cmd`` against the PS and reply.
        Split from ``_serve`` so the trace-linked rpc span can wrap
        exactly one request."""
        if cmd == b"p":
            wire = pack_params(
                self.ps.pull(worker_id), self._template)
            tx.inc(len(wire))
            transport.send_msg(conn, wire)
        elif cmd == b"c":
            seq = int.from_bytes(body[:8], "big")
            if seq == _NO_SEQ:
                seq = None
            if codec is not None:
                payload = codec.decode(body[8:], self._template)
            else:
                payload = unpack_params(self._template, body[8:])
            local = None
            if self.ps.rule.pull_uses_local:
                raw = transport.recv_msg(conn)
                rx.inc(len(raw))
                local = unpack_params(self._template, raw)
            if hasattr(self.ps, "commit_packed"):
                # single pack, shared with the dedupe cache
                wire = self.ps.commit_packed(
                    worker_id, payload, local, seq=seq)
            else:
                wire = pack_params(
                    self.ps.commit(worker_id, payload, local, seq=seq),
                    self._template)
            tx.inc(len(wire))
            transport.send_msg(conn, wire)
        elif cmd == b"P" and self._sharded:
            from distkeras_tpu.parallel.sharded_ps import leaf_buffers

            k = self.ps.num_shards
            since = [int.from_bytes(body[8 * i:8 * i + 8], "big")
                     for i in range(k)]
            included, _, _ = self.ps.pull_since(worker_id, since)
            head = len(included).to_bytes(2, "big") + \
                b"".join(s.to_bytes(2, "big") + c.to_bytes(8, "big")
                         for s, c, _ in included)
            parts = [head]
            for s, _, leaves in included:
                parts.extend(leaf_buffers(
                    leaves, self._shard_templates[s]))
            tx.inc(transport.send_msg_gather(conn, *parts))
        elif cmd == b"C" and self._sharded:
            from distkeras_tpu.parallel.sharded_ps import (
                leaf_buffers, unpack_leaves)

            k = int.from_bytes(body[:2], "big")
            seq = int.from_bytes(body[2:10], "big")
            if seq == _NO_SEQ:
                seq = None
            temps = self._shard_templates[k]
            if codec is not None:
                leaves = codec.decode_leaves(body[10:], temps)
            else:
                leaves = unpack_leaves(temps, body[10:])
            local = None
            if self.ps.rule.pull_uses_local:
                # elastic family: the worker's local slice for THIS
                # shard rides as a second frame (the b"c" convention,
                # shard-scoped)
                raw = transport.recv_msg(conn)
                rx.inc(len(raw))
                local = unpack_leaves(temps, raw)
            clock, pulled = self.ps.commit_shard(
                worker_id, k, leaves, local, seq=seq)
            tx.inc(transport.send_msg_gather(
                conn, clock.to_bytes(8, "big"),
                *leaf_buffers(pulled, temps)))
        elif cmd == b"E":
            # replication epoch probe: 8-byte big-endian epoch (0 for
            # an unreplicated server) — lets trainers record which
            # epoch served the run and clients spot a deposed primary
            wire = int(getattr(self.ps, "epoch", 0)).to_bytes(8, "big")
            tx.inc(len(wire))
            transport.send_msg(conn, wire)
        elif cmd == b"V":
            # template-free center fetch (msgpack): the gateway's
            # rolling_update(source=[(host, port), ...]) pulls promoted
            # weights without holding the training template
            wire = transport.pack_obj({
                "center": jax.tree_util.tree_map(
                    np.asarray, self.ps.center),
                "epoch": int(getattr(self.ps, "epoch", 0)),
                "num_commits": int(getattr(self.ps, "num_commits", 0)),
            })
            tx.inc(len(wire))
            transport.send_msg(conn, wire)
        elif cmd == b"d":
            # clean worker finish: retire from liveness monitoring and
            # drop its dedupe reply
            self.ps.retire(worker_id)
        elif cmd == b"s":
            self._stop.set()
        else:
            raise ValueError(f"unknown command {cmd!r}")

    def stop(self):
        self._stop.set()
        # No more clients: the dedupe replies have nothing to answer.
        self.ps.clear_reply_cache()
        try:
            self._sock.close()
        except OSError:
            pass

    def kill(self):
        """Crash simulation: drop the listening socket AND every live
        connection mid-exchange, keeping NO graceful-shutdown courtesy
        (the dedupe cache is not cleared — a real crash would not
        either; durable state is whatever the snapshots hold).  Clients
        see ``ConnectionError`` and retry against ``restart_from``."""
        # kill-path flight record, fsynced BEFORE the sockets die: the
        # postmortem's crash marker must survive whatever follows
        flight_recorder.record(
            "ps_kill", port=self.address[1],
            num_commits=int(getattr(self.ps, "num_commits", 0)))
        flight_recorder.flush(fsync=True)
        self._stop.set()
        for s in (self._sock, *self._conns):
            try:
                s.close()
            except OSError:
                pass

    @classmethod
    def restart_from(cls, snapshot: dict | str | os.PathLike,
                     rule: UpdateRule, template: Pytree, *,
                     host: str = "127.0.0.1", port: int = 0,
                     snapshot_path: str | os.PathLike | None = None,
                     snapshot_every: int = 0) -> "PSServer":
        """Warm restart: bring a killed PS back (typically on its old
        port so reconnecting ``ResilientPSClient``s find it) from a
        snapshot dict or file.  Commit-seq dedupe survives the restart,
        so a client retrying a commit the dead server already applied
        (and snapshotted) gets its cached reply instead of
        double-applying the delta.  Dispatches on the snapshot's
        ``"sharded"`` key, so a ``ShardedParameterServer`` snapshot
        restarts sharded (same K, plan re-derived from the saved
        center).  Returns a STARTED server."""
        if isinstance(snapshot, (str, os.PathLike)):
            from distkeras_tpu import checkpoint as ckpt

            snapshot = ckpt.load_ps_snapshot(snapshot)
        if "sharded" in snapshot:
            from distkeras_tpu.parallel.sharded_ps import (
                ShardedParameterServer)

            ps = ShardedParameterServer.from_snapshot(
                rule, snapshot, snapshot_path=snapshot_path,
                snapshot_every=snapshot_every)
        else:
            ps = HostParameterServer.from_snapshot(
                rule, snapshot, snapshot_path=snapshot_path,
                snapshot_every=snapshot_every)
        telemetry.metrics().counter("ps_restarts_total").inc()
        telemetry.instant("ps_restart", commits=ps.num_commits)
        flight_recorder.record(
            "ps_restart", num_commits=int(ps.num_commits),
            last_acked={str(w): s
                        for w, s in ps.last_acked_seqs().items()})
        return cls(ps, template, host=host, port=port).start()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class PSClient:
    """Worker-side connection to a ``PSServer`` (one per worker thread,
    as the reference opened one socket per Spark task)."""

    def __init__(self, host: str, port: int, worker_id: int,
                 template: Pytree, codec=None):
        """``codec`` (a ``parallel.compression`` codec or name): commits
        are sent codec-encoded — pass pre-encoded ``bytes`` to
        ``commit`` (the worker loop encodes once and keeps the residual
        for error feedback)."""
        from distkeras_tpu.parallel.compression import resolve_codec

        self._sock = transport.connect(host, port, timeout=30.0)
        self._template = _to_numpy(template)
        self.codec = resolve_codec(codec)
        self.worker_id = int(worker_id)
        hello = int(worker_id).to_bytes(4, "big")
        if self.codec is not None:
            # The wire carries only the codec NAME; the server decodes
            # with its own name-resolved instance.  A custom codec class
            # (or a subclass shadowing a built-in name) would be decoded
            # by the stock codec — corrupting every update silently —
            # so require name-resolution to reproduce this exact class.
            try:
                server_side = resolve_codec(self.codec.name)
            except KeyError:
                server_side = None
            if server_side is None or \
                    type(server_side) is not type(self.codec):
                raise ValueError(
                    f"codec {type(self.codec).__name__}(name="
                    f"{self.codec.name!r}) cannot be reconstructed "
                    f"server-side from its name; custom codecs work "
                    f"only over the in-process transport")
            hello += self.codec.name.encode()
        transport.send_msg(self._sock, hello)

    def pull(self) -> Pytree:
        # the span pushes trace context; trace_header() reads it back,
        # so the wire carries (trace_id, span_id) only while tracing —
        # hdr is b"" (zero wire bytes) when telemetry is off
        with telemetry.span("ps_client_pull",
                            worker=self.worker_id) as sp:
            hdr = transport.trace_header()
            transport.send_msg(self._sock, hdr + b"p")
            if hdr:
                # arrow tail AFTER a successful send: an arrow exists
                # only for requests that actually left this process
                telemetry.flow_start("wire", sp.span_id, op="pull")
            return unpack_params(self._template,
                                 transport.recv_msg(self._sock))

    def commit(self, payload: Pytree, local: Pytree | None = None,
               seq: int | None = None) -> Pytree:
        """``seq``: monotonic per-worker commit counter enabling
        server-side retry dedupe; ``None`` (default) disables dedupe
        for this commit.  Pass explicit seqs if you retry commits."""
        wire_seq = _NO_SEQ if seq is None else int(seq)
        if seq is not None and not 0 <= wire_seq < _NO_SEQ:
            raise ValueError(
                f"seq out of range [0, 2**64-1): {seq}")
        if isinstance(payload, bytes):
            if self.codec is None:
                raise ValueError(
                    "pre-encoded commit bytes need a codec declared at "
                    "connect time (PSClient(codec=...))")
            body = payload
        elif self.codec is not None:
            # codec connection, tree payload: encode here (the server
            # decodes everything on this connection with the codec) —
            # callers wanting error feedback encode themselves and pass
            # bytes, keeping the residual
            body = self.codec.encode(payload)
        else:
            body = pack_params(_to_numpy(payload), self._template)
        with telemetry.span("ps_client_commit", worker=self.worker_id,
                            seq=seq) as sp:
            hdr = transport.trace_header()
            transport.send_msg(
                self._sock,
                hdr + b"c" + wire_seq.to_bytes(8, "big"), body)
            if local is not None:
                transport.send_msg(self._sock,
                                   pack_params(_to_numpy(local),
                                               self._template))
            if hdr:
                telemetry.flow_start("wire", sp.span_id, op="commit",
                                     seq=seq)
            return unpack_params(self._template,
                                 transport.recv_msg(self._sock))

    def done(self):
        """Announce a clean finish (retires this worker from the
        server's liveness monitoring) — call before ``close``."""
        transport.send_msg(self._sock, b"d")

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class PSRetryExhausted(ConnectionError):
    """An operation kept failing past its retry budget; the last
    underlying error is ``__cause__``.  Distinct from a transient
    failure so callers (the trainer's round loop) can tell "the budget
    is spent, die" from "recompute and try again"."""


class _InProcessClient:
    """The in-process arm behind the same client face as ``PSClient``:
    direct method calls on a ``HostParameterServer``."""

    def __init__(self, ps: HostParameterServer, worker_id: int):
        self._ps = ps
        self._w = worker_id

    def pull(self) -> Pytree:
        return self._ps.pull(self._w)

    def commit(self, payload, local=None, seq=None) -> Pytree:
        return self._ps.commit(self._w, payload, local, seq=seq)

    def done(self):
        self._ps.retire(self._w)

    def close(self):
        pass


class _ReplicaCycler:
    """Ordered replica address list with probe-before-declare-dead
    (mirroring ``gateway.RemoteReplica.probe``): the client sticks to
    its current address until a connect fails AND a cheap probe agrees
    the address is dead, then advances to the next replica — so a
    transient fault (a chaos-injected reset on a healthy primary)
    retries in place instead of stampeding the standby, while a killed
    primary fails over within one retry.  Wraps around: an unpromoted
    standby refuses connects (its worker port is reserved but not yet
    listening), so the cycle keeps walking until promotion finishes."""

    def __init__(self, addresses, *, probe_timeout: float = 0.25,
                 worker: int | None = None):
        addrs = [(str(h), int(p)) for h, p in addresses]
        if not addrs:
            raise ValueError("ps_replicas needs at least one address")
        self.addresses = addrs
        self.probe_timeout = float(probe_timeout)
        self.worker = worker
        self.failovers = 0  # guarded-by: _lock
        self._i = 0  # guarded-by: _lock
        self._lock = racecheck.lock("ps_replica_cycler")

    def current(self) -> tuple[str, int]:
        with self._lock:
            return self.addresses[self._i]

    def _probe(self, host: str, port: int) -> bool:
        """Is anything still accepting on (host, port)?  A bare TCP
        connect is the PS wire's health check — the server speaks only
        after the client's hello, so an accepted connect IS liveness."""
        try:
            transport.connect(host, port,
                              timeout=self.probe_timeout).close()
            return True
        except OSError:
            return False

    def connect(self, build: Callable[[str, int], Any]):
        """Build a client against the current address; on failure,
        probe before declaring the replica dead and advancing."""
        host, port = self.current()
        try:
            return build(host, port)
        except Exception:
            if not self._probe(host, port):
                with self._lock:
                    # another thread may have advanced first; only
                    # count a failover if we still point at the dead
                    # address (workers share one cycle position per
                    # client, not a global one)
                    if self.addresses[self._i] == (host, port):
                        self._i = (self._i + 1) % len(self.addresses)
                        self.failovers += 1
                telemetry.metrics().counter(
                    "ps_client_failovers_total").inc()
            raise


class ResilientPSClient:
    """Self-healing PS client: reconnect + exponential backoff with
    deterministic jitter + an explicit retry budget + at-most-once
    commit seqs — the recovery logic that used to live inline in
    ``trainers._train_host``'s worker loop, shared by trainers and
    scripts.

    The underlying connection is built lazily by ``factory`` (so the
    FIRST contact consumes the same budget as any later one) and
    rebuilt after every failure.  ``commit`` stamps a monotonic
    per-client sequence number and retries with the IDENTICAL payload
    bytes/tree, so a commit whose *ack* was lost is deduped server-side
    instead of applied twice (``HostParameterServer.commit``); the seq
    advances only after a confirmed reply.  Budget exhaustion raises
    ``PSRetryExhausted`` (from the last error) rather than the raw
    transport exception.

    ``on_retry(attempt, exc)`` fires before each backoff sleep — the
    trainer uses it to record ``worker_round_retries`` history and
    ``worker_retry`` trace instants.  Jitter draws from a seeded rng,
    so a chaos run's sleep schedule is reproducible.
    """

    def __init__(self, factory: Callable[[], Any], *, retries: int = 0,
                 backoff_base: float = 0.05, backoff_max: float = 2.0,
                 jitter: float = 0.5, seed: int = 0,
                 use_seq: bool = True,
                 retry_deadline: float | None = None,
                 on_retry: Optional[Callable[[int, Exception], None]]
                 = None, worker: int | None = None,
                 fence_refresh_limit: int = 2000,
                 fence_refresh_delay: float = 0.005):
        """``retry_deadline`` (seconds, wall clock) bounds each
        operation's WHOLE retry ladder alongside the attempt-count
        budget: a generous ``retries`` with exponential backoff can
        otherwise stall a worker for the full ladder even after
        failover has already produced a live server elsewhere.  Either
        budget tripping raises ``PSRetryExhausted`` (the message says
        which)."""
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter={jitter} outside [0, 1]")
        if retry_deadline is not None and retry_deadline <= 0:
            raise ValueError(
                f"retry_deadline must be > 0 seconds, got "
                f"{retry_deadline}")
        self.worker = worker  # identity for traces / flight records
        self._factory = factory
        self.retries = int(retries)
        self.retry_deadline = (None if retry_deadline is None
                               else float(retry_deadline))
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.jitter = float(jitter)
        self.use_seq = bool(use_seq)
        self.on_retry = on_retry
        # shard-fence (elastic reshard) routing refreshes: free of the
        # attempt budget but bounded against livelock — the limit ×
        # delay product (~10s default) rides out any sane cutover
        self.fence_refresh_limit = int(fence_refresh_limit)
        self.fence_refresh_delay = float(fence_refresh_delay)
        self._rng = np.random.default_rng(seed)
        self._raw = None
        self._seq = 0
        self.retry_count = 0

    @classmethod
    def for_address(cls, host: str, port: int, *, worker_id: int,
                    template: Pytree, codec=None, shards: int = 1,
                    shard_stats: dict | None = None, **kwargs
                    ) -> "ResilientPSClient":
        """Socket arm: (re)connects a ``PSClient`` — or, with
        ``shards > 1``, a ``sharded_ps.ShardedPSClient`` speaking the
        shard-addressed zero-copy wire (``shard_stats`` accumulates its
        version-delta pull savings across reconnects) — to a
        ``PSServer``.  Retries are shard-aware for free: the one seq
        stamped per logical commit rides every shard, so a retry after
        a partial application re-applies exactly the missed shards."""
        kwargs.setdefault("worker", worker_id)
        if shards > 1:
            from distkeras_tpu.parallel.sharded_ps import (
                ShardedPSClient)

            return cls(lambda: ShardedPSClient(
                host, port, worker_id=worker_id, template=template,
                num_shards=shards, codec=codec, stats=shard_stats),
                **kwargs)
        return cls(lambda: PSClient(host, port, worker_id=worker_id,
                                    template=template, codec=codec),
                   **kwargs)

    @classmethod
    def for_replicas(cls, addresses, *, worker_id: int,
                     template: Pytree, codec=None, shards: int = 1,
                     shard_stats: dict | None = None,
                     probe_timeout: float = 0.25, **kwargs
                     ) -> "ResilientPSClient":
        """Multi-address socket arm for a replicated PS
        (``replicated_ps``): ``addresses`` is the ORDERED replica list
        — the same order every replica holds, which is also the
        promotion tie-break order.  The client walks it through a
        ``_ReplicaCycler`` (probe-before-declare-dead), so a primary
        kill mid-training fails over transparently: the commit retry
        lands on the promoted standby, whose replicated dedupe table
        makes the retry exactly-once.  The cycler is exposed as
        ``.replicas`` (``.failovers`` feeds trainer history)."""
        kwargs.setdefault("worker", worker_id)
        cycler = _ReplicaCycler(addresses, probe_timeout=probe_timeout,
                                worker=worker_id)
        if shards > 1:
            from distkeras_tpu.parallel.sharded_ps import (
                ShardedPSClient)

            def build(host, port):
                return ShardedPSClient(
                    host, port, worker_id=worker_id,
                    template=template, num_shards=shards, codec=codec,
                    stats=shard_stats)
        else:
            def build(host, port):
                return PSClient(host, port, worker_id=worker_id,
                                template=template, codec=codec)
        client = cls(lambda: cycler.connect(build), **kwargs)
        client.replicas = cycler
        return client

    @classmethod
    def for_server(cls, ps: HostParameterServer, worker_id: int,
                   **kwargs) -> "ResilientPSClient":
        """In-process arm.  Commits there are atomic (apply-and-reply
        under the server mutex — no lost-ack window), so dedupe seqs
        default off and no reply cache is kept per worker."""
        kwargs.setdefault("use_seq", False)
        kwargs.setdefault("worker", worker_id)
        return cls(lambda: _InProcessClient(ps, worker_id), **kwargs)

    @classmethod
    def for_elastic(cls, seeds, *, worker_id: int, template: Pytree,
                    stats: dict | None = None, **kwargs
                    ) -> "ResilientPSClient":
        """Elastic socket arm (``elastic_ps``): ``seeds`` is any list
        of group member addresses — the client bootstraps the current
        versioned shard map from whichever answers and re-routes
        itself on every fence/stale rejection thereafter.  The one
        logical seq per commit rides every shard via the per-leaf
        dedupe table, so retries across a split/merge/migration are
        exactly-once regardless of where each leaf now lives."""
        kwargs.setdefault("worker", worker_id)
        from distkeras_tpu.parallel.elastic_ps import ElasticPSClient

        return cls(lambda: ElasticPSClient(
            seeds, worker_id=worker_id, template=template,
            stats=stats), **kwargs)

    # -- retry machinery ---------------------------------------------------

    def _backoff_delay(self, attempt: int) -> float:
        delay = min(self.backoff_max,
                    self.backoff_base * (2.0 ** (attempt - 1)))
        if self.jitter:
            # full-jitter downward: desynchronizes a worker herd
            # reconnecting to a restarted PS, deterministic per seed
            delay *= 1.0 - self.jitter * float(self._rng.random())
        return delay

    def _close_raw(self) -> None:
        if self._raw is not None:
            try:
                self._raw.close()
            except Exception:
                pass
            self._raw = None

    def _op(self, op: Callable[[Any], Pytree],
            kind: str = "op") -> Pytree:
        attempt = 0
        m = telemetry.metrics()
        deadline = (None if self.retry_deadline is None
                    else telemetry.now() + self.retry_deadline)
        # one span over the WHOLE retry loop: every attempt's
        # ps_client_commit/pull span nests under it and inherits its
        # trace id, so a retry storm reads as one causal chain in the
        # merged trace
        fence_refreshes = 0
        with telemetry.span("ps_op", op=kind, worker=self.worker):
            while True:
                try:
                    if self._raw is None:
                        self._raw = self._factory()
                    return op(self._raw)
                except PSShardFencedError as e:
                    # a shard fence is a ROUTING signal, not a dead
                    # server: refresh the shard map and go again
                    # without burning an attempt or the connection —
                    # the rejection usually carries the new map, so
                    # the retry lands on the new owner immediately.
                    # During a cutover's fence window the map has not
                    # flipped yet; the bounded spin below rides it
                    # out (the wall-clock deadline still applies).
                    fence_refreshes += 1
                    m.counter("ps_shard_fence_refresh_total").inc()
                    if fence_refreshes > self.fence_refresh_limit:
                        raise PSRetryExhausted(
                            f"PS shard stayed fenced/stale through "
                            f"{fence_refreshes} routing refreshes "
                            f"(last: {e!r})") from e
                    if (deadline is not None
                            and telemetry.now() >= deadline):
                        raise PSRetryExhausted(
                            f"PS operation fence-refreshed "
                            f"{fence_refreshes} time(s); retry "
                            f"budget retry_deadline="
                            f"{self.retry_deadline}s (wall clock) "
                            f"exhausted (last: {e!r})") from e
                    try:
                        raw = self._raw
                        if raw is None:
                            pass
                        elif e.map_obj is not None:
                            raw.apply_shard_map(e.map_obj)
                        else:
                            raw.refresh_map()
                    except Exception:
                        # the map fetch itself failed — that IS a
                        # connectivity problem; let the generic
                        # ladder handle the rebuild
                        self._close_raw()
                    time.sleep(self.fence_refresh_delay)
                except Exception as e:
                    # Exception, not BaseException: KeyboardInterrupt /
                    # MemoryError must not be retried
                    self._close_raw()
                    attempt += 1
                    self.retry_count += 1
                    m.counter("ps_client_retries_total").inc()
                    flight_recorder.record("retry", op=kind,
                                           worker=self.worker,
                                           attempt=attempt,
                                           error=repr(e))
                    if attempt > self.retries:
                        raise PSRetryExhausted(
                            f"PS operation failed {attempt} time(s); "
                            f"retry budget retries={self.retries} "
                            f"(attempt count) exhausted "
                            f"(last: {e!r})") from e
                    remaining = (None if deadline is None
                                 else deadline - telemetry.now())
                    if remaining is not None and remaining <= 0:
                        raise PSRetryExhausted(
                            f"PS operation failed {attempt} time(s); "
                            f"retry budget retry_deadline="
                            f"{self.retry_deadline}s (wall clock) "
                            f"exhausted (last: {e!r})") from e
                    if self.on_retry is not None:
                        self.on_retry(attempt, e)
                    delay = self._backoff_delay(attempt)
                    if remaining is not None:
                        # never sleep past the wall-clock budget: the
                        # last attempt before the deadline still runs
                        delay = min(delay, remaining)
                    m.histogram(
                        "ps_client_backoff_seconds").observe(delay)
                    time.sleep(delay)

    # -- the client face ---------------------------------------------------

    def pull(self) -> Pytree:
        return self._op(lambda c: c.pull(), kind="pull")

    def commit(self, payload, local: Pytree | None = None) -> Pytree:
        """At-most-once commit: the seq is stamped once and reused
        across every internal retry (identical payload → the server
        either applies it or returns the cached reply), advancing only
        on success."""
        seq = self._seq if self.use_seq else None
        pulled = self._op(lambda c: c.commit(payload, local, seq=seq),
                          kind="commit")
        self._seq += 1
        return pulled

    def done(self) -> None:
        """Courtesy clean-finish announcement (retires this worker from
        server liveness monitoring); best-effort — a PS that is already
        gone must not fail a worker that finished its work."""
        if self._raw is not None:
            try:
                self._raw.done()
            except Exception:
                pass

    def close(self) -> None:
        self._close_raw()

    def __enter__(self) -> "ResilientPSClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def stop_server(host: str, port: int):
    """Ask a ``PSServer`` to shut down (the reference's stop command)."""
    sock = transport.connect(host, port, timeout=10.0)
    try:
        transport.send_msg(sock, (0).to_bytes(4, "big"))
        transport.send_msg(sock, b"s")
    finally:
        sock.close()


#: hello worker id used by management probes (epoch / center fetch) —
#: outside any trainer's worker-id range, never registered for liveness
_PROBE_WORKER = 2 ** 32 - 1


def fetch_epoch(host: str, port: int, timeout: float = 10.0) -> int:
    """The server's replication epoch (0 when unreplicated) via the
    ``b"E"`` wire verb — how trainers record ``ps_epoch`` history and
    tools identify which replica currently answers an address."""
    sock = transport.connect(host, port, timeout=timeout)
    try:
        transport.send_msg(sock, _PROBE_WORKER.to_bytes(4, "big"))
        transport.send_msg(sock, b"E")
        return int.from_bytes(transport.recv_msg(sock), "big")
    finally:
        sock.close()


def fetch_center_obj(host: str, port: int,
                     timeout: float = 30.0) -> dict:
    """Template-free center fetch via the ``b"V"`` wire verb: returns
    ``{"center": pytree, "epoch": int, "num_commits": int}``.  The
    serving gateway's ``rolling_update(source=[(host, port), ...])``
    uses this to pull promoted weights from whichever replica of a
    training PS is alive."""
    sock = transport.connect(host, port, timeout=timeout)
    try:
        transport.send_msg(sock, _PROBE_WORKER.to_bytes(4, "big"))
        transport.send_msg(sock, b"V")
        obj = transport.unpack_obj(transport.recv_msg(sock))
    finally:
        sock.close()
    return {"center": obj["center"], "epoch": int(obj["epoch"]),
            "num_commits": int(obj["num_commits"])}

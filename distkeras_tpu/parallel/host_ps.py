"""Host-side concurrent parameter server — the *faithful* async arm
(design 5a of SURVEY.md §7: "host-side PS process, per-host async client
threads, faithful staleness behavior").

Where ``ps_emulator`` compiles the whole commit round into one XLA
program with *deterministic* staleness, this module runs the reference's
actual concurrency model: worker threads free-running against a central
server whose commits are serialized by a mutex, staleness emerging from
real scheduling races (SURVEY.md §2.1 SocketParameterServer: accept
loop, handler per connection, lock around center updates).  It reuses
the very same ``UpdateRule`` objects as the emulator — the server law,
payload kind, window normalization and pull law are shared code — which
is what makes the two arms comparable: any convergence difference is
attributable to staleness semantics, not to reimplemented math
(VERDICT.md round-1 Missing #4).

Two transports:
* in-process — workers call the server object directly (the common case:
  one host, threads driving device steps);
* socket — a TCP server thread speaking the L1 framing
  (``parallel.transport``): single-byte commands ``b"p"`` (pull) /
  ``b"c"`` (commit payload) / ``b"s"`` (stop).  Raw parameter
  payloads ride ``pack_params``'s template-implied encoding
  (concatenated leaf bytes in canonical pytree order — both endpoints
  hold the same template, so the wire carries only data; ~10x faster
  than the earlier msgpack encoding at ResNet scale, PERF.md §12);
  compressed commits ride the negotiated codec's bytes.  The
  reference's wire protocol, minus pickle.
"""

from __future__ import annotations

import socket
import threading
from typing import Any

import jax
import numpy as np

from distkeras_tpu import telemetry
from distkeras_tpu.parallel import transport
from distkeras_tpu.parallel.update_rules import PSState, UpdateRule

def _to_numpy(tree: Pytree) -> Pytree:
    return jax.tree_util.tree_map(np.asarray, tree)


def pack_params(tree, template=None) -> bytes:
    """Raw-buffer wire encoding: leaves in canonical pytree order,
    concatenated ``tobytes()``.  Shapes/dtypes ride the TEMPLATE both
    endpoints already hold (PSServer and PSClient are constructed with
    the same center tree), so the wire carries only data — measured
    ~10x faster than the msgpack path at ResNet-18 scale (45 MB:
    ~13 ms pack vs 132 ms serialize; unpack is zero-copy views vs
    47 ms), which matters because serialization IS the PS ceiling
    (PERF.md §12).  ``template`` casts each leaf to the wire dtype
    (the msgpack path did the cast on the receive side; e.g. a worker
    computing f64 deltas against an f32 center)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if template is not None:
        temps = jax.tree_util.tree_leaves(template)
        if len(temps) != len(leaves):
            raise ValueError(
                f"payload has {len(leaves)} leaves, template "
                f"{len(temps)}")
        leaves = [np.asarray(x, dtype=t.dtype)
                  for x, t in zip(leaves, temps)]
    return b"".join(
        np.ascontiguousarray(x).tobytes() for x in leaves)


def unpack_params(template, data: bytes):
    """Inverse of ``pack_params``: zero-copy ``frombuffer`` views
    sliced per the template's leaf shapes/dtypes (read-only arrays —
    every consumer treats pulled/committed trees as immutable)."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    buf = memoryview(data)
    out, off = [], 0
    for t in leaves:
        n = int(t.nbytes)
        arr = np.frombuffer(buf[off:off + n],
                            dtype=t.dtype).reshape(t.shape)
        out.append(arr)
        off += n
    if off != len(data):
        raise ValueError(
            f"wire payload is {len(data)} bytes but the template "
            f"expects {off} (mismatched model between worker and PS)")
    return jax.tree_util.tree_unflatten(treedef, out)

Pytree = Any

# Wire value for "no sequence number" (dedupe off) in commit frames.
_NO_SEQ = 2 ** 64 - 1



class HostParameterServer:
    """Threaded central state: ``pull``/``commit`` under a mutex.

    Staleness bookkeeping matches the reference DynSGD server: a global
    commit clock; a commit's staleness is the number of commits applied
    since the committing worker's last pull (SURVEY.md §2.1
    DynSGDParameterServer).
    """

    def __init__(self, rule: UpdateRule, center: Pytree):
        self.rule = rule
        self._lock = threading.Lock()
        self._center = _to_numpy(center)
        self._clock = 0
        self._pull_clock: dict[int, int] = {}
        self.staleness_log: list[int] = []
        self.num_commits = 0
        self._last_seen: dict[int, float] = {}
        self._last_reply: dict[int, tuple[int, Pytree]] = {}

    # -- the two verbs -----------------------------------------------------

    def pull(self, worker_id: int) -> Pytree:
        telemetry.metrics().counter("ps_pulls_total").inc()
        with self._lock:
            self._pull_clock[worker_id] = self._clock
            self._last_seen[worker_id] = telemetry.now()
            return self._center

    def commit(self, worker_id: int, payload: Pytree,
               local: Pytree | None = None,
               seq: int | None = None) -> Pytree:
        """Apply one commit; returns the worker's new local params (the
        rule's pull law, evaluated against the same center the server
        used — commit-and-pull is one atomic exchange, as in the
        reference where the handler thread holds the connection).

        ``seq`` is the worker's commit sequence number (monotonic per
        worker), used to dedupe retries: when a commit was applied but
        its *reply* was lost (a socket dying between apply and ack),
        the retried commit carries the same seq and gets the cached
        reply back instead of applying the window's delta twice —
        at-most-once application.  Any ``seq <=`` the worker's last
        applied seq is a duplicate (a straggler handler can deliver an
        old retransmit arbitrarily late); stragglers older than the
        last commit get the cached latest reply, which lands on a dead
        connection anyway."""
        # normalize to host numpy up front: the in-process arm hands
        # jax arrays straight in, which would silently push the apply
        # back onto the eager per-leaf jnp path the numpy fast path
        # exists to avoid (PERF.md §12)
        payload = _to_numpy(payload)
        if local is not None:
            local = _to_numpy(local)
        m = telemetry.metrics()
        # the span encloses the mutex wait, so its duration shows both
        # apply cost and serialization contention on the timeline
        with telemetry.span("ps_commit", worker=worker_id), self._lock:
            if seq is not None:
                last = self._last_reply.get(worker_id)
                if last is not None and seq <= last[0]:
                    self._last_seen[worker_id] = telemetry.now()
                    m.counter("ps_commit_dedup_total").inc()
                    return last[1]
            staleness = self._clock - self._pull_clock.get(worker_id, 0)
            state = PSState(center=self._center,
                            clock=np.int32(self._clock))
            new_state = self.rule.commit(
                state, payload, np.int32(staleness))
            pulled = self.rule.worker_pull(
                local, state.center, new_state.center)
            self._center = _to_numpy(new_state.center)
            self._clock += 1
            self._pull_clock[worker_id] = self._clock
            self.staleness_log.append(int(staleness))
            self.num_commits += 1
            self._last_seen[worker_id] = telemetry.now()
            m.counter("ps_commits_total").inc()
            m.histogram("ps_commit_staleness",
                        buckets=telemetry.STALENESS_BUCKETS
                        ).observe(int(staleness))
            pulled = _to_numpy(pulled)
            if seq is not None:
                self._last_reply[worker_id] = (seq, pulled)
            return pulled

    @property
    def center(self) -> Pytree:
        with self._lock:
            return self._center

    def register(self, worker_id: int) -> None:
        """Start liveness monitoring before first contact, so a worker
        that hangs before ever reaching the server is still flagged by
        ``idle_workers`` instead of being invisible."""
        with self._lock:
            self._last_seen.setdefault(worker_id, telemetry.now())

    def retire(self, worker_id: int) -> None:
        """A worker finished cleanly: stop monitoring it (so
        ``idle_workers`` never flags it) and drop its dedupe reply."""
        with self._lock:
            self._last_seen.pop(worker_id, None)
            self._last_reply.pop(worker_id, None)

    def clear_reply_cache(self) -> None:
        """Drop all cached dedupe replies (a full param copy per
        worker) — for when no client can retry anymore."""
        with self._lock:
            self._last_reply.clear()

    def idle_workers(self, timeout: float) -> list[int]:
        """Failure *detection* (SURVEY.md §5 row the reference left
        empty): workers silent — no pull or commit — for more than
        ``timeout`` seconds.  PS traffic is the natural heartbeat: an
        alive PS-family worker contacts the server every communication
        window; one that is silent is stalled, partitioned, or dead.

        Heartbeats are stamped with ``telemetry.now()`` — the same
        monotonic clock as every other host timestamp in the repo —
        so idleness compares cleanly against serving/trainer spans."""
        now = telemetry.now()
        with self._lock:
            idle = sorted(w for w, seen in self._last_seen.items()
                          if now - seen > timeout)
        telemetry.metrics().gauge("ps_idle_workers").set(len(idle))
        return idle


class PSServer:
    """TCP front end for a ``HostParameterServer``.

    Protocol (all messages framed by ``transport``): first message on a
    connection is the worker id (4-byte big-endian int).  Then requests:
    ``b"p"`` -> center params; ``b"c" + 8-byte seq + params`` (+
    optional second frame with local params for pull-uses-local rules)
    -> new local params, where ``seq`` is the worker's monotonic commit
    counter (dedupes retried commits whose ack was lost; the all-ones
    value means "no seq" and disables dedupe for that commit).  ``b"s"``
    shuts the server down.
    """

    def __init__(self, ps: HostParameterServer, template: Pytree,
                 host: str = "127.0.0.1", port: int = 0):
        """The handshake frame is ``4-byte worker id`` optionally
        followed by a codec name (``parallel.compression``): commits on
        that connection then arrive codec-encoded instead of via the
        raw template-implied ``pack_params`` encoding — the wire-compression arm."""
        self.ps = ps
        self._template = _to_numpy(template)
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen()
        self.address = self._sock.getsockname()
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True)

    def start(self) -> "PSServer":
        self._accept_thread.start()
        return self

    def _accept_loop(self):
        self._sock.settimeout(0.2)
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                1)
                t = threading.Thread(target=self._serve, args=(conn,),
                                     daemon=True)
                t.start()
                self._threads.append(t)
        finally:
            # however the loop exits (stop() or the b"s" command), stop
            # listening — a bound-but-dead port accepts TCP connects
            # from health checks/reconnects that then hang
            try:
                self._sock.close()
            except OSError:
                pass

    def _serve(self, conn: socket.socket):
        # per-direction wire totals (message bodies; the 4-byte frame
        # headers are omitted — negligible against parameter payloads)
        rx = telemetry.metrics().counter("ps_wire_bytes_total",
                                         direction="rx")
        tx = telemetry.metrics().counter("ps_wire_bytes_total",
                                         direction="tx")
        with conn:
            try:
                hello = transport.recv_msg(conn)
                rx.inc(len(hello))
                worker_id = int.from_bytes(hello[:4], "big")
                codec = None
                if len(hello) > 4:
                    from distkeras_tpu.parallel.compression import (
                        resolve_codec)

                    codec = resolve_codec(hello[4:].decode())
                while True:
                    msg = transport.recv_msg(conn)
                    rx.inc(len(msg))
                    cmd, body = msg[:1], msg[1:]
                    if cmd == b"p":
                        wire = pack_params(
                            self.ps.pull(worker_id), self._template)
                        tx.inc(len(wire))
                        transport.send_msg(conn, wire)
                    elif cmd == b"c":
                        seq = int.from_bytes(body[:8], "big")
                        if seq == _NO_SEQ:
                            seq = None
                        if codec is not None:
                            payload = codec.decode(body[8:],
                                                   self._template)
                        else:
                            payload = unpack_params(
                                self._template, body[8:])
                        local = None
                        if self.ps.rule.pull_uses_local:
                            raw = transport.recv_msg(conn)
                            rx.inc(len(raw))
                            local = unpack_params(self._template, raw)
                        pulled = self.ps.commit(worker_id, payload,
                                                local, seq=seq)
                        wire = pack_params(pulled, self._template)
                        tx.inc(len(wire))
                        transport.send_msg(conn, wire)
                    elif cmd == b"d":
                        # clean worker finish: retire from liveness
                        # monitoring and drop its dedupe reply
                        self.ps.retire(worker_id)
                    elif cmd == b"s":
                        self._stop.set()
                        return
                    else:
                        raise ValueError(f"unknown command {cmd!r}")
            except (ConnectionError, OSError):
                return  # client gone; reference handlers did the same
            except Exception as e:
                # malformed frame / decode failure: drop the connection
                # with a diagnostic instead of dying silently (the
                # client sees a ConnectionError and retries/fails)
                import sys

                print(f"[distkeras_tpu] PS handler error (worker "
                      f"connection dropped): {e!r}", file=sys.stderr,
                      flush=True)
                return

    def stop(self):
        self._stop.set()
        # No more clients: the dedupe replies have nothing to answer.
        self.ps.clear_reply_cache()
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class PSClient:
    """Worker-side connection to a ``PSServer`` (one per worker thread,
    as the reference opened one socket per Spark task)."""

    def __init__(self, host: str, port: int, worker_id: int,
                 template: Pytree, codec=None):
        """``codec`` (a ``parallel.compression`` codec or name): commits
        are sent codec-encoded — pass pre-encoded ``bytes`` to
        ``commit`` (the worker loop encodes once and keeps the residual
        for error feedback)."""
        from distkeras_tpu.parallel.compression import resolve_codec

        self._sock = transport.connect(host, port, timeout=30.0)
        self._template = _to_numpy(template)
        self.codec = resolve_codec(codec)
        hello = int(worker_id).to_bytes(4, "big")
        if self.codec is not None:
            # The wire carries only the codec NAME; the server decodes
            # with its own name-resolved instance.  A custom codec class
            # (or a subclass shadowing a built-in name) would be decoded
            # by the stock codec — corrupting every update silently —
            # so require name-resolution to reproduce this exact class.
            try:
                server_side = resolve_codec(self.codec.name)
            except KeyError:
                server_side = None
            if server_side is None or \
                    type(server_side) is not type(self.codec):
                raise ValueError(
                    f"codec {type(self.codec).__name__}(name="
                    f"{self.codec.name!r}) cannot be reconstructed "
                    f"server-side from its name; custom codecs work "
                    f"only over the in-process transport")
            hello += self.codec.name.encode()
        transport.send_msg(self._sock, hello)

    def pull(self) -> Pytree:
        transport.send_msg(self._sock, b"p")
        return unpack_params(self._template,
                             transport.recv_msg(self._sock))

    def commit(self, payload: Pytree, local: Pytree | None = None,
               seq: int | None = None) -> Pytree:
        """``seq``: monotonic per-worker commit counter enabling
        server-side retry dedupe; ``None`` (default) disables dedupe
        for this commit.  Pass explicit seqs if you retry commits."""
        wire_seq = _NO_SEQ if seq is None else int(seq)
        if seq is not None and not 0 <= wire_seq < _NO_SEQ:
            raise ValueError(
                f"seq out of range [0, 2**64-1): {seq}")
        if isinstance(payload, bytes):
            if self.codec is None:
                raise ValueError(
                    "pre-encoded commit bytes need a codec declared at "
                    "connect time (PSClient(codec=...))")
            body = payload
        elif self.codec is not None:
            # codec connection, tree payload: encode here (the server
            # decodes everything on this connection with the codec) —
            # callers wanting error feedback encode themselves and pass
            # bytes, keeping the residual
            body = self.codec.encode(payload)
        else:
            body = pack_params(_to_numpy(payload), self._template)
        transport.send_msg(self._sock,
                           b"c" + wire_seq.to_bytes(8, "big"), body)
        if local is not None:
            transport.send_msg(self._sock,
                               pack_params(_to_numpy(local),
                                           self._template))
        return unpack_params(self._template,
                             transport.recv_msg(self._sock))

    def done(self):
        """Announce a clean finish (retires this worker from the
        server's liveness monitoring) — call before ``close``."""
        transport.send_msg(self._sock, b"d")

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def stop_server(host: str, port: int):
    """Ask a ``PSServer`` to shut down (the reference's stop command)."""
    sock = transport.connect(host, port, timeout=10.0)
    try:
        transport.send_msg(sock, (0).to_bytes(4, "big"))
        transport.send_msg(sock, b"s")
    finally:
        sock.close()

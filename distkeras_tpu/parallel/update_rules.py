"""Parameter-server update rules as pure, jittable pytree functions.

This is the portable essence of the reference's ``distkeras/
parameter_servers.py`` + the server-relevant half of ``workers.py``
(SURVEY.md §2.1): DOWNPOUR, ADAG, AEASGD, EAMSGD and DynSGD are each a
*(commit payload, server update, worker pull)* triple, parameterized by a
communication window and (for DynSGD) commit staleness.  The reference
implements these as mutating methods on a threaded TCP server; here they are
pure functions over parameter pytrees so they can be

  * unit-tested directly against the published update equations,
  * ``lax.scan``-ed over an in-round commit order (the on-mesh async
    emulator in ``ps_emulator.py``), and
  * closed into weighted ``psum``s where the rule is linear in the payload
    (the fast path — see ``ps_emulator.py``).

Staleness model: within an emulated round every worker pulls the center,
runs ``communication_window`` local steps, and the parameter server applies
the resulting commits in a (per-round permuted) order.  The i-th commit in
that order has observed ``i`` intervening commits since its pull, so its
staleness is exactly ``i`` — the same quantity the reference's DynSGD server
tracks with its global update counter, but deterministic and replayable
instead of a race outcome.
"""

from __future__ import annotations

import dataclasses
from typing import Any, ClassVar, NamedTuple

import jax
import jax.numpy as jnp

from distkeras_tpu.utils import tree_add, tree_axpy, tree_lerp

Pytree = Any


class PSState(NamedTuple):
    """Server-side state: the center variable plus a commit clock.

    ``clock`` mirrors the reference DynSGD server's global update counter
    (SURVEY.md §2.1 DynSGDParameterServer).
    """

    center: Pytree
    clock: jnp.ndarray  # scalar int32, total commits applied


@dataclasses.dataclass(frozen=True)
class UpdateRule:
    """Base class. Subclasses define the server commit + worker pull laws."""

    #: 'delta' — worker commits (local - last_pulled); 'params' — worker
    #: commits its full local parameters (elastic family).  Per-class
    #: constant, not a constructor argument.
    payload_kind: ClassVar[str] = "delta"

    #: Whether ``worker_pull`` reads its ``local`` argument.  False lets
    #: the faithful round scan skip carrying an extra [W, params] operand
    #: (``apply_commit_round_pulls``).
    pull_uses_local: ClassVar[bool] = False

    def init_state(self, center: Pytree) -> PSState:
        return PSState(center=center, clock=jnp.zeros((), jnp.int32))

    def commit(self, state: PSState, payload: Pytree,
               staleness: jnp.ndarray) -> PSState:
        raise NotImplementedError

    def worker_pull(self, local: Pytree, center_pre: Pytree,
                    center_post: Pytree) -> Pytree:
        """New local params after this worker's own commit.

        ``center_pre``/``center_post`` are the center immediately before /
        after the worker's commit was applied.  Default (DOWNPOUR-family)
        behavior is the reference's commit-then-pull: adopt the center as of
        just after our commit (later commits in the round will be seen at
        the next pull — i.e. next round).
        """
        del local, center_pre
        return center_post

    def normalize_delta(self, delta: Pytree, window: int) -> Pytree:
        """Worker-side transform of the accumulated delta before commit."""
        del window
        return delta


@dataclasses.dataclass(frozen=True)
class DownpourRule(UpdateRule):
    """DOWNPOUR (Dean et al., 2012): ``center += delta``.

    The worker accumulates ``communication_window`` optimizer steps locally;
    the commit payload is the raw parameter delta.  Reference:
    DeltaParameterServer.commit (SURVEY.md §2.1).
    """

    def commit(self, state, payload, staleness):
        del staleness
        return PSState(center=tree_add(state.center, payload),
                       clock=state.clock + 1)


@dataclasses.dataclass(frozen=True)
class AdagRule(UpdateRule):
    """ADAG / accumulated-gradient-normalization (Hermans).

    The worker normalizes its accumulated delta by the communication window
    before committing; the server applies it additively.  This keeps the
    effective per-commit step size independent of the window, which is what
    lets ADAG tolerate large windows (the reference repo's flagship claim).

    NOTE(provenance): the reference mount was empty (SURVEY.md header), so
    the exact ADAG normalization could not be re-verified against
    ``parameter_servers.py``; this implements the documented
    delta/window normalization with additive server apply.
    """

    def commit(self, state, payload, staleness):
        del staleness
        return PSState(center=tree_add(state.center, payload),
                       clock=state.clock + 1)

    def normalize_delta(self, delta, window):
        return jax.tree_util.tree_map(lambda d: d / float(window), delta)


@dataclasses.dataclass(frozen=True)
class DynSGDRule(UpdateRule):
    """DynSGD: staleness-aware dynamic learning rate.

    ``center += delta / (staleness + 1)`` — the reference's
    DynSGDParameterServer scales each commit by the inverse of its staleness
    (number of commits applied since the committing worker's pull), tracked
    via the global update counter (SURVEY.md §2.1).
    """

    def commit(self, state, payload, staleness):
        scale = 1.0 / (staleness.astype(jnp.float32) + 1.0)
        return PSState(center=tree_axpy(scale, payload, state.center),
                       clock=state.clock + 1)


@dataclasses.dataclass(frozen=True)
class ElasticRule(UpdateRule):
    """AEASGD / EAMSGD server law (Zhang, Choromanska & LeCun, 2015).

    Every window the worker fetches the center and exchanges the elastic
    force ``e = alpha * (x_i - center)``:

        server:  center <- center + e        (= (1-alpha)*center + alpha*x_i)
        worker:  x_i    <- x_i    - e

    ``alpha = learning_rate * rho`` (the reference trainers take ``rho`` and
    ``learning_rate`` kwargs — SURVEY.md §2.1 AEASGD/EAMSGD).  EAMSGD differs
    from AEASGD only on the worker side (Nesterov momentum in the local
    loop), so both share this rule.
    """

    alpha: float = 0.5
    payload_kind: ClassVar[str] = "params"
    pull_uses_local: ClassVar[bool] = True

    def commit(self, state, payload, staleness):
        del staleness
        # center <- (1 - alpha) * center + alpha * x_i
        return PSState(center=tree_lerp(state.center, payload, self.alpha),
                       clock=state.clock + 1)

    def worker_pull(self, local, center_pre, center_post):
        del center_post
        # x_i <- x_i - alpha * (x_i - center_pre): symmetric elastic move
        # against the same center value the server used for this commit.
        return tree_lerp(local, center_pre, self.alpha)


def apply_commit_round(rule: UpdateRule, state: PSState,
                       payloads: Pytree) -> tuple[PSState, Pytree, Pytree]:
    """Apply one round of N commits sequentially (the emulated PS loop).

    ``payloads`` is a pytree whose leaves are stacked ``[N, ...]`` in commit
    order.  Returns ``(new_state, centers_pre, centers_post)`` where
    ``centers_pre``/``centers_post`` hold, for each commit i, the center
    immediately before/after that commit (stacked ``[N, ...]``) — the values
    each worker's pull law needs.

    NOTE: materializes two ``[N, params]`` stacks; kept for unit tests and
    small models.  The production faithful path is
    ``apply_commit_round_pulls`` (O(params) carry, no center stacks) —
    used by ``ps_emulator.make_round_fn``; the fast path for linear rules
    is ``ps_emulator._fast_round``.
    """

    base_clock = state.clock

    def step(st, payload_i):
        staleness = st.clock - base_clock
        new_st = rule.commit(st, payload_i, staleness)
        return new_st, (st.center, new_st.center)

    final_state, (pre, post) = jax.lax.scan(step, state, payloads)
    return final_state, pre, post


def apply_commit_round_pulls(rule: UpdateRule, state: PSState,
                             payloads: Pytree, locals_: Pytree | None,
                             staleness_offset: int = 0
                             ) -> tuple[PSState, Pytree]:
    """Sequential commit round with the pulls computed in-scan.

    Same serialization semantics as ``apply_commit_round``, but instead of
    returning the full pre/post center stacks (O(N·params) memory — the
    round-1 faithful path could not fit the flagship model, VERDICT.md
    Weak #3), each scan step computes the committing worker's pulled
    parameters directly from the center it just observed.  Memory: one
    center carried through the scan + the ``[N, params]`` pulled output,
    which is the same size as the worker parameters the round must produce
    anyway.

    ``locals_`` are the workers' post-window local params stacked in commit
    order; pass ``None`` for rules whose ``worker_pull`` ignores the local
    value (``pull_uses_local = False`` — the delta family), which keeps the
    scan free of an unused ``[N, params]`` operand.

    ``staleness_offset`` adds a constant to every commit's staleness —
    the pipelined round (``ps_emulator.make_pipelined_round_fn``) uses
    it to account for the extra round of commits its windows run
    behind, so staleness-aware rules (DynSGD) see the TRUE commit
    depth.

    Returns ``(new_state, pulled)`` with ``pulled`` stacked in commit order.
    """
    base_clock = state.clock
    with_locals = locals_ is not None

    def step(st, inp):
        payload_i, local_i = inp if with_locals else (inp, None)
        staleness = st.clock - base_clock + staleness_offset
        new_st = rule.commit(st, payload_i, staleness)
        pulled_i = rule.worker_pull(local_i, st.center, new_st.center)
        return new_st, pulled_i

    xs = (payloads, locals_) if with_locals else payloads
    final_state, pulled = jax.lax.scan(step, state, xs)
    return final_state, pulled


RULES = {
    "downpour": DownpourRule,
    "adag": AdagRule,
    "dynsgd": DynSGDRule,
    "aeasgd": ElasticRule,
    "eamsgd": ElasticRule,
}

from distkeras_tpu.parallel.host_ps import (  # noqa: F401
    HostParameterServer,
    PSClient,
    PSServer,
    ResilientPSClient,
)
from distkeras_tpu.parallel.sharded_ps import (  # noqa: F401
    ShardedParameterServer,
    ShardedPSClient,
    plan_shards,
)
from distkeras_tpu.parallel.elastic_ps import (  # noqa: F401
    ElasticPSClient,
    ElasticPSGroup,
    ElasticPSServer,
    MigrationAborted,
    ShardMap,
)
from distkeras_tpu.parallel.moe import (  # noqa: F401
    MoEAux,
    MoEParams,
    init_moe_params,
    moe_apply,
    moe_pspecs,
)
from distkeras_tpu.parallel.pipeline import pipeline_apply  # noqa: F401
from distkeras_tpu.parallel.tensor_parallel import (  # noqa: F401
    TP_RULES,
    rules_for,
    shard_tree,
    tree_shardings,
)
from distkeras_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ring_attn_fn,
    sequence_sharded_apply,
)
from distkeras_tpu.parallel.update_rules import (  # noqa: F401
    RULES,
    AdagRule,
    DownpourRule,
    DynSGDRule,
    ElasticRule,
    PSState,
    UpdateRule,
    apply_commit_round,
    apply_commit_round_pulls,
)

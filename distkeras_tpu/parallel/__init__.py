from distkeras_tpu.parallel.update_rules import (  # noqa: F401
    RULES,
    AdagRule,
    DownpourRule,
    DynSGDRule,
    ElasticRule,
    PSState,
    UpdateRule,
    apply_commit_round,
    apply_commit_round_pulls,
)

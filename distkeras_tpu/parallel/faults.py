"""Chaos transport — deterministic, seed-scheduled socket fault
injection for the host-PS wire path.

The repo already had an in-process chaos hook (``fault_injector`` on
the host-arm trainers), but it raises from INSIDE the worker loop — it
never exercises the real transport failure modes the retry machinery
exists for: a peer resetting mid-exchange, a frame truncated between
header and body, a stalled link, a partition during reconnect.
``ChaosTransport`` wraps the module-level ``transport.connect`` /
``send_msg`` / ``recv_msg`` functions (the single choke point every
socket byte in the repo crosses: ``PSServer`` handlers, ``PSClient``,
``stop_server``) and injects those faults on a schedule drawn from a
pinned seed, so a chaos run is reproducible: the k-th transport
operation always draws the same fault decision.

Fault classes (SURVEY.md §5's failure-model rows, now executable):

* ``reset``    — the socket is closed and ``ConnectionResetError``
  raised before the operation touches the wire (peer died between
  exchanges);
* ``truncate`` — ``send_msg`` writes a strict prefix of the frame and
  closes the socket (peer died MID-frame; the receiver sees a framing
  error, the sender an I/O error — the lost-ack shape that commit-seq
  dedupe exists for);
* ``delay``    — the operation is stalled ``delay_s`` seconds first
  (congestion / GC pause; trips watchdogs, not retries);
* ``partition``— a one-shot window starting at a scheduled operation
  index during which every ``connect`` is refused (the reconnect path
  itself must survive, consuming backoff rather than retry budget).

Ops are counted globally under a lock, so the *schedule* of injected
faults is a pure function of the seed even though racing worker
threads interleave nondeterministically — the chaos sweep asserts
completion-within-budget, and ``counts`` reports exactly what fired.

Usage::

    with ChaosTransport(seed=7, reset_rate=0.05, truncate_rate=0.02,
                        delay_rate=0.1, max_injections=6):
        trainer.train(data)          # transport='socket'

Injections are visible as ``chaos_injected_total{kind}`` counters on
the telemetry registry and in ``.counts``.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from distkeras_tpu import flight_recorder, telemetry
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.parallel import transport

KINDS = ("reset", "truncate", "delay", "partition")

# serializes install/uninstall across instances and threads: the
# module-binding swap must be atomic with the "are the current
# bindings mine?" check in ``uninstall``
_install_lock = threading.Lock()


class ChaosTransport:
    """Installable fault injector over ``parallel.transport``.

    Args:
      seed: pins the whole fault schedule (same seed → same decisions
        at the same operation indices).
      reset_rate / truncate_rate / delay_rate: per-operation injection
        probabilities (truncation only applies to sends; the draw is
        made — and the schedule stays aligned — on every op).
      delay_s: stall length for ``delay`` faults.
      partition_at: global op index at which a ONE-SHOT partition
        begins (``None``: never); for the next ``partition_ops``
        operations every ``connect`` raises ``ConnectionRefusedError``.
      partition_ops: width of the partition window, in operations.
      partition_every: make the partition RECURRING: after
        ``partition_at``, a fresh ``partition_ops``-wide window opens
        every ``partition_every`` operations (``None``: the original
        one-shot).  The window function is arithmetic on the op index —
        no extra rng draws — so the schedule stays a pure function of
        (seed, op index) and a failover drill can flap the link
        repeatedly on an exact, reproducible cadence.
      partition_ports: restrict the PARTITION to connects whose peer
        port is in this set, independently of ``target_ports``
        (``None``: the partition hits every targeted connect).  This is
        the asymmetric-partition knob the failover drill needs: with
        ``partition_ports={primary_worker_port}`` the worker→primary
        hop is cut while the primary↔standby replication link (other
        ports) stays up, so the standby observes a live primary and
        correctly refuses to usurp.
      max_injections: hard cap on injected reset+truncate faults (so a
        seeded run provably fits a retry budget; delays and the
        partition window do not consume it — they cost time, not
        retries).
      skip_ops: operations at the very start of the run that are never
        faulted (lets the handshake/first pull establish a baseline).
      target_ports: restrict injection to operations whose PEER port is
        in this set (``None``: every operation is injectable — the
        original behavior).  The rng is still consumed on EVERY op, so
        the schedule stays a pure function of (seed, op index); only
        the *firing* is filtered.  This is how a process hosting both a
        PS and gateway replicas attacks ONE hop: e.g.
        ``target_ports={replica_port}`` chaoses the gateway→replica
        wire while the training exchange stays clean.
      windows: WALL-CLOCK fault phases beside the op-counter schedule:
        ``[(t_start, t_end, kinds)]`` with times in seconds on the
        injector's clock and ``kinds`` a subset of ``KINDS``.  While
        ``t_start <= t < t_end`` every transport op additionally draws
        a window fault: ``"partition"`` in ``kinds`` refuses every
        ``connect`` in the window deterministically (no rng); the other
        kinds fire with total probability ``window_rate`` per op, split
        evenly among the window's drawable kinds.  Window draws come
        from a SEPARATE rng stream seeded ``[seed, 7]``, so the base
        op-counter schedule is bit-identical with or without windows —
        the schedule stays a pure function of (seed, op index, clock
        readings).  Window ``reset``/``truncate`` fires share the
        ``max_injections`` budget.  This is the phase-aligned knob the
        traffic simulator uses: a ``ChaosSchedule`` hands in a sim-time
        clock so "faults during the flash crowd" is literally a window
        over the load curve.
      window_rate: per-op probability that an active window injects one
        of its non-partition kinds (default 0.25).
      clock: zero-arg callable returning seconds for window matching
        (``None``: wall seconds since ``install()``).  Inject a
        deterministic counter to make window decisions — not just the
        base schedule — a pure function of the constructor arguments.
    """

    def __init__(self, seed: int = 0, *, reset_rate: float = 0.0,
                 truncate_rate: float = 0.0, delay_rate: float = 0.0,
                 delay_s: float = 0.02,
                 partition_at: Optional[int] = None,
                 partition_ops: int = 4,
                 partition_every: Optional[int] = None,
                 partition_ports: Optional[set] = None,
                 max_injections: Optional[int] = None,
                 skip_ops: int = 0,
                 target_ports: Optional[set] = None,
                 windows=(),
                 window_rate: float = 0.25,
                 clock=None):
        for name, rate in (("reset_rate", reset_rate),
                           ("truncate_rate", truncate_rate),
                           ("delay_rate", delay_rate),
                           ("window_rate", window_rate)):
            if not 0.0 <= rate <= 1.0:
                raise ValueError(f"{name}={rate} outside [0, 1]")
        self.windows = _validate_windows(windows)
        self.window_rate = float(window_rate)
        self._clock = clock
        self._t0 = None  # wall anchor when no clock is injected
        self._rng = np.random.default_rng(seed)
        # window decisions draw from their own stream so adding (or
        # widening) windows never perturbs the base op-counter schedule
        self._wrng = np.random.default_rng([seed, 7])
        self._rates = {"reset": float(reset_rate),
                       "truncate": float(truncate_rate),
                       "delay": float(delay_rate)}
        self.delay_s = float(delay_s)
        self.partition_at = partition_at
        self.partition_ops = int(partition_ops)
        if partition_every is not None and (
                int(partition_every) <= int(partition_ops)):
            raise ValueError(
                f"partition_every={partition_every} must exceed "
                f"partition_ops={partition_ops} (the link must heal "
                f"between windows)")
        self.partition_every = (None if partition_every is None
                                else int(partition_every))
        self.partition_ports = (None if partition_ports is None
                                else {int(p) for p in partition_ports})
        self.max_injections = max_injections
        self.skip_ops = int(skip_ops)
        self.target_ports = (None if target_ports is None
                             else {int(p) for p in target_ports})
        self._lock = racecheck.lock("chaos")
        self._op = 0  # guarded-by: _lock
        self._injected = 0  # guarded-by: _lock
        self.counts: dict[str, int] = {k: 0 for k in KINDS}
        self._orig = None  # guarded-by: _install_lock
        self._installed = False  # guarded-by: _install_lock

    # -- schedule ----------------------------------------------------------

    def _note(self, kind: str, window: bool = False) -> None:
        self.counts[kind] += 1
        telemetry.metrics().counter("chaos_injected_total",
                                    kind=kind).inc()
        if window:
            telemetry.metrics().counter("chaos_window_injected_total",
                                        kind=kind).inc()
        # called under self._lock, so op index matches the draw that
        # scheduled this injection
        flight_recorder.record("chaos", fault=kind, op=self._op,
                               window=window)

    def _draw(self, op_kind: str, port: Optional[int] = None):
        """One scheduled decision; returns the fault to inject (or
        None).  Called under the lock so op indices — and therefore the
        rng stream — are globally ordered.  ``port`` is the operation's
        peer port (None when unknowable, e.g. an already-dead socket):
        with ``target_ports`` set, a non-targeted op still consumes its
        rng draw but never fires.  The base op-counter decision is made
        first; only when it declines does an active wall-clock window
        get its (separately-streamed) draw."""
        with self._lock:
            op = self._op
            self._op += 1
            # the rng is consumed on EVERY op, injectable or not, so
            # the schedule is a pure function of (seed, op index)
            u = float(self._rng.random())
            fault = self._base_decision(op, u, op_kind, port)
            if fault is not None or not self.windows:
                return fault
            return self._window_decision(op_kind, port)

    def _base_decision(self, op: int, u: float, op_kind: str,
                       port: Optional[int]):
        # guarded-by: _lock (via _draw)
        if op < self.skip_ops:
            return None
        targeted = (self.target_ports is None
                    or (port is not None
                        and port in self.target_ports))
        part_targeted = (targeted
                         and (self.partition_ports is None
                              or (port is not None
                                  and port
                                  in self.partition_ports)))
        if (part_targeted and op_kind == "connect"
                and self._in_partition_window(op)):
            self._note("partition")
            return "partition"
        budget_left = (self.max_injections is None
                       or self._injected < self.max_injections)
        edge = 0.0
        for kind in ("reset", "truncate", "delay"):
            edge += self._rates[kind]
            if u < edge:
                if kind == "truncate" and op_kind != "send":
                    return None  # only sends can truncate
                if not targeted:
                    return None  # drawn, but this hop is off-limits
                if kind in ("reset", "truncate"):
                    if not budget_left:
                        return None
                    # lint: allow(guarded-write) — under _lock via _draw
                    self._injected += 1
                self._note(kind)
                return kind
        return None

    def _window_decision(self, op_kind: str, port: Optional[int]):
        """The wall-clock side of the schedule.  Consumes the WINDOW
        rng stream only for ops that fall inside an active window, so
        the base stream stays untouched.  Guarded-by: _lock."""
        kinds = self._active_window_kinds()
        if kinds is None:
            return None
        targeted = (self.target_ports is None
                    or (port is not None
                        and port in self.target_ports))
        if "partition" in kinds and op_kind == "connect":
            # deterministic: the whole window is a refused link
            if not targeted:
                return None
            self._note("partition", window=True)
            return "partition"
        drawable = [k for k in ("reset", "truncate", "delay")
                    if k in kinds]
        if not drawable:
            return None
        w = float(self._wrng.random())
        if w >= self.window_rate:
            return None
        kind = drawable[min(int(w * len(drawable) / self.window_rate),
                            len(drawable) - 1)]
        if kind == "truncate" and op_kind != "send":
            return None  # only sends can truncate
        if not targeted:
            return None
        if kind in ("reset", "truncate"):
            # window fires share the retry budget with the base
            # schedule — a seeded drill still provably fits it
            if (self.max_injections is not None
                    and self._injected >= self.max_injections):
                return None
            # lint: allow(guarded-write) — under _lock via _draw
            self._injected += 1
        self._note(kind, window=True)
        return kind

    def _active_window_kinds(self):
        """Kinds of the first window covering the current clock
        reading, or None outside every window.  Guarded-by: _lock."""
        if self._clock is not None:
            t = float(self._clock())
        else:
            if self._t0 is None:
                self._t0 = telemetry.now()
            t = telemetry.now() - self._t0
        for t_start, t_end, kinds in self.windows:
            if t_start <= t < t_end:
                return kinds
        return None

    def _in_partition_window(self, op: int) -> bool:
        """Pure arithmetic on the op index (NO rng): is ``op`` inside a
        partition window?  One-shot by default; with
        ``partition_every`` a fresh window opens on that cadence."""
        if self.partition_at is None or op < self.partition_at:
            return False
        offset = op - self.partition_at
        if self.partition_every is None:
            return offset < self.partition_ops
        return offset % self.partition_every < self.partition_ops

    # -- wrapped operations ------------------------------------------------

    def _connect(self, host, port, timeout=None):
        fault = self._draw("connect", port=int(port))
        if fault == "partition":
            raise ConnectionRefusedError(
                "chaos: partitioned (scheduled one-shot window)")
        if fault == "delay":
            telemetry.instant("chaos_delay", op="connect")
            _sleep(self.delay_s)
        if fault == "reset":
            raise ConnectionResetError("chaos: connect reset")
        return self._orig[0](host, port, timeout=timeout)

    def _send_msg(self, sock, *parts):
        fault = self._draw("send", port=_peer_port(sock))
        if fault == "delay":
            telemetry.instant("chaos_delay", op="send")
            _sleep(self.delay_s)
        if fault == "reset":
            _hard_close(sock)
            raise ConnectionResetError("chaos: send reset")
        if fault == "truncate":
            data = transport.frame(*parts)
            cut = 1 + int(self._cut_fraction() * (len(data) - 1))
            cut = min(cut, len(data) - 1)  # ALWAYS a strict prefix
            try:
                sock.sendall(data[:cut])
            finally:
                _hard_close(sock)
            raise ConnectionError(
                f"chaos: frame truncated at {cut}/{len(data)} bytes")
        return self._orig[1](sock, *parts)

    def _cut_fraction(self) -> float:
        with self._lock:
            return float(self._rng.random())

    def _recv_msg(self, sock):
        fault = self._draw("recv", port=_peer_port(sock))
        if fault == "delay":
            telemetry.instant("chaos_delay", op="recv")
            _sleep(self.delay_s)
        if fault == "reset":
            _hard_close(sock)
            raise ConnectionResetError("chaos: recv reset")
        return self._orig[2](sock)

    def _send_msg_gather(self, sock, *parts):
        """The zero-copy scatter-gather send (the sharded-PS wire)
        crosses the same choke point: same fault classes, same
        schedule stream.  Truncation materializes the frame (a copy is
        fine on the chaos path) to cut a strict prefix."""
        fault = self._draw("send", port=_peer_port(sock))
        if fault == "delay":
            telemetry.instant("chaos_delay", op="send")
            _sleep(self.delay_s)
        if fault == "reset":
            _hard_close(sock)
            raise ConnectionResetError("chaos: send reset")
        if fault == "truncate":
            data = transport.frame(*parts)
            cut = 1 + int(self._cut_fraction() * (len(data) - 1))
            cut = min(cut, len(data) - 1)
            try:
                sock.sendall(data[:cut])
            finally:
                _hard_close(sock)
            raise ConnectionError(
                f"chaos: frame truncated at {cut}/{len(data)} bytes")
        return self._orig[3](sock, *parts)

    def _recv_msg_into(self, sock):
        fault = self._draw("recv", port=_peer_port(sock))
        if fault == "delay":
            telemetry.instant("chaos_delay", op="recv")
            _sleep(self.delay_s)
        if fault == "reset":
            _hard_close(sock)
            raise ConnectionResetError("chaos: recv reset")
        return self._orig[4](sock)

    # -- install / uninstall ----------------------------------------------

    def install(self) -> "ChaosTransport":
        with _install_lock:
            if self._installed:
                raise RuntimeError("ChaosTransport already installed")
            self._orig = (transport.connect, transport.send_msg,
                          transport.recv_msg,
                          transport.send_msg_gather,
                          transport.recv_msg_into)
            self._installed = True
            if self._clock is None and self._t0 is None:
                self._t0 = telemetry.now()  # window t=0 is install time
            transport.connect = self._connect
            transport.send_msg = self._send_msg
            transport.recv_msg = self._recv_msg
            transport.send_msg_gather = self._send_msg_gather
            transport.recv_msg_into = self._recv_msg_into
        return self

    def uninstall(self) -> None:
        """Restore the transport bindings.  Idempotent — a second (or
        concurrent) ``uninstall`` is a no-op, and an instance whose
        wrappers have already been replaced (another injector stacked
        on top, or a test monkeypatch) restores NOTHING rather than
        clobbering the newer bindings with its stale snapshot."""
        with _install_lock:
            if not self._installed:
                return
            self._installed = False
            mine = (self._connect, self._send_msg, self._recv_msg,
                    self._send_msg_gather, self._recv_msg_into)
            current = (transport.connect, transport.send_msg,
                       transport.recv_msg, transport.send_msg_gather,
                       transport.recv_msg_into)
            if current == mine:
                (transport.connect, transport.send_msg,
                 transport.recv_msg, transport.send_msg_gather,
                 transport.recv_msg_into) = self._orig
            # self._orig is deliberately KEPT: a daemon PS handler
            # thread may still be inside a wrapper (blocked on recv)
            # when the module bindings are restored — it must find the
            # originals, not a None

    def __enter__(self) -> "ChaosTransport":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    @property
    def total_injected(self) -> int:
        return sum(self.counts.values())


def _validate_windows(windows) -> tuple:
    """Normalize ``[(t_start, t_end, kinds)]`` to a tuple of
    ``(float, float, frozenset)`` triples, validating eagerly so a bad
    drill script fails at construction, not mid-run."""
    out = []
    for w in windows:
        try:
            t_start, t_end, kinds = w
        except (TypeError, ValueError):
            raise ValueError(
                f"window {w!r} is not (t_start, t_end, kinds)")
        t_start, t_end = float(t_start), float(t_end)
        if not (0.0 <= t_start < t_end):
            raise ValueError(
                f"window times ({t_start}, {t_end}) need "
                f"0 <= t_start < t_end")
        if isinstance(kinds, str):
            kinds = (kinds,)
        kinds = frozenset(kinds)
        if not kinds or not kinds <= set(KINDS):
            raise ValueError(
                f"window kinds {sorted(kinds)} must be a nonempty "
                f"subset of {KINDS}")
        out.append((t_start, t_end, kinds))
    return tuple(out)


def _peer_port(sock) -> Optional[int]:
    """Peer port of a connected socket (None when the socket is
    already dead — with ``target_ports`` set such an op never fires,
    the safe default for an unattributable operation)."""
    try:
        return int(sock.getpeername()[1])
    except (OSError, IndexError, TypeError):
        return None


def _hard_close(sock) -> None:
    try:
        sock.close()
    except OSError:
        pass


def _sleep(seconds: float) -> None:
    if seconds > 0:
        import time

        time.sleep(seconds)

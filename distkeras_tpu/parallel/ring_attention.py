"""Ring attention: exact attention over a sequence-sharded mesh axis.

The reference has no long-context story at all (SURVEY.md §5 "long-context
/ sequence parallelism: absent" — its longest-sequence workload, the IMDB
BiLSTM, handles sequences whole per worker).  The TPU rebuild makes
sequence parallelism first-class: shard the time axis of ``q``/``k``/``v``
across a mesh axis, keep the query block resident, and rotate the
key/value blocks around the ring with ``lax.ppermute`` — one hop per
scan step, N hops total (the final hop restores the original block
placement, keeping the scan carry uniform) — accumulating exact softmax
attention with the online (flash-style) running max / denominator.  The
ICI traffic per step is one K/V block, which overlaps with the block's
matmuls on TPU.

Memory: O(T_local) per device in both directions.  The forward pass
holds only the online-softmax accumulators and never materializes a
[T_local, T_global] attention matrix; the backward pass is a custom
reverse-ring VJP (the flash-attention backward) that saves just
``(q, k, v, out, logsumexp)`` and recomputes each block's probabilities
in a second ring pass, with the dK/dV accumulators traveling alongside
their K/V blocks so each arrives home after N hops.  No per-step
residual stacks anywhere — peak memory is independent of the ring size.

This is an SPMD op: call it inside ``jax.shard_map`` (or use
``ring_attn_fn`` as the ``attn_fn`` of a ``TransformerLM`` whose
``seq_axis`` names the mesh axis).  First-order differentiable; the
gradients are tested against dense attention (tests/test_ring_attention).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from distkeras_tpu.utils import axis_size, pcast, shard_map
import numpy as np
from jax import lax

# numpy, not jnp: a module-level jnp constant would initialize the XLA
# backend at import time, breaking jax.distributed.initialize callers
_NEG = np.float32(-1e30)


def _ring(axis_name: str | None):
    """The one-hop-backward permutation (block s lands on device s-1).
    ``axis_name=None`` is the DEVICE-LOCAL degenerate ring (n=1, no
    hops): the same online-softmax / recompute machinery runs as a
    single-chip blockwise (flash-style) attention."""
    if axis_name is None:
        return 1, 0, None
    n = axis_size(axis_name)
    return n, lax.axis_index(axis_name), [(i, (i - 1) % n)
                                          for i in range(n)]


def _vary(axis_name, trees):
    """Mark zero-initialized scan carries as device-varying (scan's
    carry typing must agree with the computed, varying outputs)."""
    if axis_name is None:
        return tuple(trees)
    return tuple(pcast(x, (axis_name,), to="varying") for x in trees)


def _block_mask(src, t_local, q_pos):
    k_pos = src * t_local + jnp.arange(t_local)
    return (q_pos[:, None] >= k_pos[None, :])[None, None]


def _chunks(q_chunk, t_local):
    """Validated (n_chunks, chunk_len) for within-device q blocking."""
    if q_chunk is None or q_chunk >= t_local:
        return 1, t_local
    if q_chunk < 1 or t_local % q_chunk:
        raise ValueError(
            f"q_chunk={q_chunk} must be a positive divisor of the "
            f"local sequence length {t_local}")
    return t_local // q_chunk, q_chunk


def _chunk_q_major(x, n_c, qc):
    """[B, T, ...] -> chunk-major [n_c, B, qc, ...]."""
    b = x.shape[0]
    return jnp.moveaxis(x.reshape(b, n_c, qc, *x.shape[2:]), 1, 0)


def _chunk_bh_major(x, n_c, qc):
    """[B, H, T] -> chunk-major [n_c, B, H, qc]."""
    b, h = x.shape[:2]
    return jnp.moveaxis(x.reshape(b, h, n_c, qc), 2, 0)


def _pos_chunks(me, t_local, n_c, qc):
    """Global q positions of this device's block, chunked [n_c, qc]."""
    return (me * t_local + jnp.arange(t_local)).reshape(n_c, qc)


def _forward_scan_flash(q, k, v, axis_name, scale, causal, block_q,
                        block_k):
    """Ring forward with the Pallas hop kernels (ops.attention.
    flash_hop_fwd): the online-softmax state (m, l, acc) lives in
    [B, H, T_local, ...] layout and is updated by ONE Mosaic kernel
    per hop while the K/V blocks rotate; only the final hop's state is
    normalized.  Same math as the XLA-composed scan up to reduction
    order (unit-tested both ways)."""
    from distkeras_tpu.ops.attention import flash_hop_fwd

    b, t_local, h, d = q.shape
    n, me, ring = _ring(axis_name)
    me = jnp.int32(me)
    qt = jnp.swapaxes(q, 1, 2)                      # [B, H, T, D]

    vma = None if axis_name is None else frozenset({axis_name})

    def body(carry, s):
        k_blk, v_blk, m, l, acc = carry             # k/v in BHTD
        src = (me + s) % n
        m, l, acc = flash_hop_fwd(
            qt, k_blk, v_blk, m, l, acc,
            q_offset=me * t_local, k_offset=src * t_local,
            scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, vma=vma)
        if ring is not None:
            k_blk = lax.ppermute(k_blk, axis_name, ring)
            v_blk = lax.ppermute(v_blk, axis_name, ring)
        return (k_blk, v_blk, m, l, acc), None

    init = (jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2),
            *_vary(axis_name, (
                jnp.full((b, h, t_local, 1), _NEG, jnp.float32),
                jnp.zeros((b, h, t_local, 1), jnp.float32),
                jnp.zeros((b, h, t_local, d), jnp.float32))))
    (_, _, m, l, acc), _ = lax.scan(body, init, jnp.arange(n))
    l = jnp.maximum(l, 1e-30)
    out = jnp.swapaxes(acc / l, 1, 2)               # [B, T, H, D]
    return out, (m + jnp.log(l))[..., 0]            # lse [B, H, T]


def _forward_scan(q, k, v, axis_name, scale, causal, q_chunk=None):
    """Online-softmax ring forward.  Returns ``(out32 [B,T,H,D],
    L [B,H,T])`` where ``L = m + log(l)`` is the per-row logsumexp the
    backward pass needs to re-normalize recomputed probabilities.

    ``q_chunk`` blocks the within-device q dimension (flash-style):
    each ring hop processes q in chunks of that length sequentially
    (``lax.map``), bounding the transient logits block to
    ``[B, H, q_chunk, T_local]`` instead of ``[B, H, T_local,
    T_local]``.  All accumulators stay chunk-major for the whole ring
    scan and are unblocked once at the end."""
    q32 = q.astype(jnp.float32)
    b, t_local, h, d = q32.shape
    n, me, ring = _ring(axis_name)
    n_c, qc = _chunks(q_chunk, t_local)
    # chunk-major layouts: q [n_c, B, qc, H, D]; bookkeeping
    # [n_c, B, H, qc(, D)]; positions [n_c, qc]
    q_ch = _chunk_q_major(q32, n_c, qc)
    pos_ch = _pos_chunks(me, t_local, n_c, qc)

    def body(carry, s):
        k_blk, v_blk, m, l, acc = carry
        k32 = k_blk.astype(jnp.float32)
        v32 = v_blk.astype(jnp.float32)
        src = (me + s) % n

        def chunk(args):
            q_c, pos_c, m_c, l_c, acc_c = args
            logits = jnp.einsum("bqhd,bkhd->bhqk", q_c, k32) * scale
            if causal:
                mask = _block_mask(src, t_local, pos_c)
                logits = jnp.where(mask, logits, _NEG)
            m_new = jnp.maximum(m_c, logits.max(axis=-1))
            p = jnp.exp(logits - m_new[..., None])
            if causal:
                p = p * mask  # exact zeros for masked entries
            corr = jnp.exp(m_c - m_new)
            l_c = l_c * corr + p.sum(axis=-1)
            acc_c = acc_c * corr[..., None] + jnp.einsum(
                "bhqk,bkhd->bhqd", p, v32)
            return m_new, l_c, acc_c

        m, l, acc = lax.map(chunk, (q_ch, pos_ch, m, l, acc))
        # Rotate (the hop after the last step restores the original
        # placement, which keeps the scan carry shape uniform).  The
        # device-local mode (ring=None, n=1) has nowhere to rotate to.
        if ring is not None:
            k_blk = lax.ppermute(k_blk, axis_name, ring)
            v_blk = lax.ppermute(v_blk, axis_name, ring)
        return (k_blk, v_blk, m, l, acc), None

    init = (k, v, *_vary(axis_name, (
        jnp.full((n_c, b, h, qc), _NEG, jnp.float32),
        jnp.zeros((n_c, b, h, qc), jnp.float32),
        jnp.zeros((n_c, b, h, qc, d), jnp.float32))))
    (_, _, m, l, acc), _ = lax.scan(body, init, jnp.arange(n))
    # un-chunk: [n_c, B, H, qc(, D)] -> [B, H, T(, D)]
    m = jnp.moveaxis(m, 0, 2).reshape(b, h, t_local)
    l = jnp.moveaxis(l, 0, 2).reshape(b, h, t_local)
    acc = jnp.moveaxis(acc, 0, 2).reshape(b, h, t_local, d)
    l = jnp.maximum(l, 1e-30)
    out = jnp.einsum("bhqd->bqhd", acc / l[..., None])
    return out, m + jnp.log(l)


def _bwd_flash(axis_name, scale, causal, block_q, block_k, residuals,
               dout):
    """Reverse ring with the Pallas hop kernels: per hop,
    ``flash_hop_bwd`` emits this (q block)x(visiting k/v block) pair's
    partial gradients; dq accumulates locally, dk/dv accumulate on
    f32 carries that rotate WITH their k/v blocks (home after n hops).
    """
    from distkeras_tpu.ops.attention import flash_hop_bwd

    q, k, v, out, lse = residuals
    b, t_local, h, d = q.shape
    n, me, ring = _ring(axis_name)
    me = jnp.int32(me)
    qt = jnp.swapaxes(q, 1, 2)
    dot = jnp.swapaxes(dout, 1, 2).astype(q.dtype)
    out_t = jnp.swapaxes(out, 1, 2).astype(jnp.float32)
    dsum = jnp.sum(dot.astype(jnp.float32) * out_t, axis=-1,
                   keepdims=True)                   # [B, H, T, 1]
    lse4 = lse[..., None]                           # [B, H, T, 1]

    vma = None if axis_name is None else frozenset({axis_name})

    def body(carry, s):
        k_blk, v_blk, dk, dv, dq = carry
        src = (me + s) % n
        dq_p, dk_p, dv_p = flash_hop_bwd(
            qt, k_blk, v_blk, dot, lse4, dsum,
            q_offset=me * t_local, k_offset=src * t_local,
            scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, vma=vma)
        dq = dq + dq_p
        dk = dk + dk_p
        dv = dv + dv_p
        if ring is not None:
            k_blk = lax.ppermute(k_blk, axis_name, ring)
            v_blk = lax.ppermute(v_blk, axis_name, ring)
            dk = lax.ppermute(dk, axis_name, ring)
            dv = lax.ppermute(dv, axis_name, ring)
        return (k_blk, v_blk, dk, dv, dq), None

    zeros = jnp.zeros((b, h, t_local, d), jnp.float32)
    init = (jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2),
            *_vary(axis_name, (zeros, zeros, zeros)))
    (_, _, dk, dv, dq), _ = lax.scan(body, init, jnp.arange(n))
    return (jnp.swapaxes(dq, 1, 2).astype(q.dtype),
            jnp.swapaxes(dk, 1, 2).astype(k.dtype),
            jnp.swapaxes(dv, 1, 2).astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8,
                                                    9))
def _ring_attention_f32(q, k, v, axis_name, scale, causal, q_chunk,
                        impl, block_q, block_k):
    if impl == "flash":
        out, _ = _forward_scan_flash(q, k, v, axis_name, scale,
                                     causal, block_q, block_k)
        return out
    out, _ = _forward_scan(q, k, v, axis_name, scale, causal, q_chunk)
    return out


def _fwd(q, k, v, axis_name, scale, causal, q_chunk, impl, block_q,
         block_k):
    if impl == "flash":
        out, lse = _forward_scan_flash(q, k, v, axis_name, scale,
                                       causal, block_q, block_k)
    else:
        out, lse = _forward_scan(q, k, v, axis_name, scale, causal,
                                 q_chunk)
    return out, (q, k, v, out, lse)


def _bwd_dispatch(axis_name, scale, causal, q_chunk, impl, block_q,
                  block_k, residuals, dout):
    if impl == "flash":
        return _bwd_flash(axis_name, scale, causal, block_q, block_k,
                          residuals, dout)
    return _bwd(axis_name, scale, causal, q_chunk, residuals, dout)


def _bwd(axis_name, scale, causal, q_chunk, residuals, dout):
    """Reverse ring: the flash-attention backward, with dK/dV
    accumulators traveling *with* their K/V blocks around the ring so
    each returns home after N hops having collected every device's
    contribution.  Per-device memory is O(T_local) — no per-step
    residual stacks (the motivation for the custom VJP).  ``q_chunk``
    blocks the q dimension within each hop exactly as the forward does
    (an inner ``lax.scan`` carrying the dK/dV accumulation across
    chunks)."""
    q, k, v, out, lse = residuals
    q32 = q.astype(jnp.float32)
    dout32 = dout.astype(jnp.float32)
    b, t_local, h, d = q32.shape
    n, me, ring = _ring(axis_name)
    n_c, qc = _chunks(q_chunk, t_local)
    # D_i = rowsum(dO_i * O_i), the softmax-jacobian diagonal term
    D = jnp.einsum("bqhd,bqhd->bhq", dout32, out.astype(jnp.float32))
    # chunk-major per-q tensors
    q_ch = _chunk_q_major(q32, n_c, qc)
    dout_ch = _chunk_q_major(dout32, n_c, qc)
    lse_ch = _chunk_bh_major(lse, n_c, qc)
    d_ch = _chunk_bh_major(D, n_c, qc)
    pos_ch = _pos_chunks(me, t_local, n_c, qc)

    def body(carry, s):
        k_blk, v_blk, dk, dv, dq = carry
        k32 = k_blk.astype(jnp.float32)
        v32 = v_blk.astype(jnp.float32)
        src = (me + s) % n

        def chunk(kv_carry, args):
            dk_a, dv_a = kv_carry
            q_c, pos_c, dout_c, lse_c, d_c, dq_c = args
            logits = jnp.einsum("bqhd,bkhd->bhqk", q_c, k32) * scale
            if causal:
                # mask BEFORE exp (as the forward does): a masked
                # future-key logit can exceed lse by enough to overflow
                # exp; relying on inf * False == 0 would pin
                # correctness to a lowering detail
                mask = _block_mask(src, t_local, pos_c)
                logits = jnp.where(mask, logits, _NEG)
            p = jnp.exp(logits - lse_c[..., None])  # normalized probs
            if causal:
                p = p * mask  # exact zeros
            dv_a = dv_a + jnp.einsum("bhqk,bqhd->bkhd", p, dout_c)
            dp = jnp.einsum("bqhd,bkhd->bhqk", dout_c, v32)
            ds = p * (dp - d_c[..., None]) * scale
            dq_c = dq_c + jnp.einsum("bhqk,bkhd->bqhd", ds, k32)
            dk_a = dk_a + jnp.einsum("bhqk,bqhd->bkhd", ds, q_c)
            return (dk_a, dv_a), dq_c

        (dk, dv), dq = lax.scan(
            chunk, (dk, dv),
            (q_ch, pos_ch, dout_ch, lse_ch, d_ch, dq))
        if ring is not None:
            k_blk = lax.ppermute(k_blk, axis_name, ring)
            v_blk = lax.ppermute(v_blk, axis_name, ring)
            dk = lax.ppermute(dk, axis_name, ring)
            dv = lax.ppermute(dv, axis_name, ring)
        return (k_blk, v_blk, dk, dv, dq), None

    zeros_kv = jnp.zeros((b, t_local, h, d), jnp.float32)
    dq0 = jnp.zeros((n_c, b, qc, h, d), jnp.float32)
    init = (k, v, *_vary(axis_name, (zeros_kv, zeros_kv, dq0)))
    (_, _, dk, dv, dq), _ = lax.scan(body, init, jnp.arange(n))
    dq = jnp.moveaxis(dq, 0, 1).reshape(b, t_local, h, d)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


_ring_attention_f32.defvjp(_fwd, _bwd_dispatch)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str, scale: float | None = None,
                   causal: bool = True,
                   q_chunk: int | None = None,
                   impl: str = "xla",
                   block_q: int | None = None,
                   block_k: int | None = None) -> jax.Array:
    """Exact (flash-accumulated) attention over a ring of devices.

    Args:
      q, k, v: local sequence blocks ``[B, T_local, H, D]`` — the global
        time axis is sharded over ``axis_name`` in mesh order, so device
        ``i`` holds global positions ``[i*T_local, (i+1)*T_local)``.
      axis_name: the mesh axis the sequence is sharded over.
      scale: logit scale; defaults to ``D ** -0.5``.
      causal: apply a causal mask in *global* positions.
      q_chunk: optional within-device q block length (must divide
        ``T_local``).  Default (None) computes each ring hop's full
        ``[T_local, T_local]`` logits block at once; setting it
        processes q in chunks of this length sequentially, bounding the
        transient block to ``[q_chunk, T_local]`` — the flash-style
        memory/throughput trade for long local sequences.  Numerics are
        identical up to f32 reduction order.

    Returns:
      Attention output ``[B, T_local, H, D]`` in ``q.dtype`` (all
      accumulation in f32).

    Differentiation uses a custom reverse-ring VJP (flash backward:
    probabilities recomputed from the saved logsumexp, dK/dV
    accumulators riding the ring) with O(T_local) residual memory per
    device, honoring ``q_chunk``.  First-order only — higher-order
    autodiff through this op is not defined.

    ``impl="flash"`` runs each hop's block computation as the Pallas
    hop kernels (``ops.attention.flash_hop_fwd``/``flash_hop_bwd``;
    ``block_q``/``block_k`` as in ``flash_attention``) instead of the
    XLA-composed online softmax — the kernel path's VMEM-resident
    accumulators and K/V streaming inside each hop, with the ring
    still carrying the state between devices.  Math is identical up
    to f32 reduction order; ``q_chunk`` applies to the XLA impl only.
    """
    if impl not in ("xla", "flash"):
        raise ValueError(f"impl must be 'xla' or 'flash'; got {impl!r}")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    out = _ring_attention_f32(
        q, k, v, axis_name, float(scale), bool(causal),
        None if q_chunk is None else int(q_chunk), impl,
        None if block_q is None else int(block_q),
        None if block_k is None else int(block_k))
    return out.astype(q.dtype)


def ring_attn_fn(axis_name: str, causal: bool = True,
                 q_chunk: int | None = None, impl: str = "xla",
                 block_q: int | None = None,
                 block_k: int | None = None):
    """An ``AttnFn`` (``TransformerLM.attn_fn`` signature) bound to a
    mesh axis: ``fn(q, k, v, *, scale)``."""
    return functools.partial(ring_attention, axis_name=axis_name,
                             causal=causal, q_chunk=q_chunk,
                             impl=impl, block_q=block_q,
                             block_k=block_k)


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        scale: float | None = None, causal: bool = True,
                        q_chunk: int | None = None) -> jax.Array:
    """Single-device flash-style attention: the ring machinery with no
    ring (n=1, no collectives).  The online-softmax q-chunking bounds
    the transient logits block to ``[B, H, q_chunk, T]`` and the custom
    VJP recomputes per-chunk probabilities from the saved logsumexp, so
    the ``[T, T]`` attention matrix is never materialized in either
    pass — the device-local answer to the dense path's quadratic HBM
    traffic at long T (PERF.md §13).  Numerics match
    ``dense_causal_attention`` up to f32 reduction order."""
    return ring_attention(q, k, v, axis_name=None, scale=scale,
                          causal=causal, q_chunk=q_chunk)


def blockwise_attn_fn(causal: bool = True, q_chunk: int | None = 128):
    """An ``AttnFn`` for ``TransformerLM(attn_fn=...)`` running
    device-local blockwise attention.  ``q_chunk=128`` is the measured
    optimum of the round-4 sweep on the v5e (PERF.md §13: 64/128/256/
    512 -> 0.370/0.388/0.325/0.231 6ND MFU at T=2048)."""
    return functools.partial(blockwise_attention, causal=causal,
                             q_chunk=q_chunk)


def sequence_sharded_apply(fn, mesh, seq_axis: str, *,
                           num_seq_args: int = 1):
    """Wrap ``fn(params, *arrays)`` in a ``shard_map`` that shards axis 1
    (time) of each array argument over ``seq_axis`` and replicates
    ``params`` — the standard harness for running a ``seq_axis``-enabled
    model (e.g. ``TransformerLM(seq_axis=...)``) sequence-parallel.

    ``num_seq_args`` array arguments follow ``params``; outputs are
    returned sequence-sharded (time axis 1).
    """
    from jax.sharding import PartitionSpec as P

    seq_spec = P(None, seq_axis)
    in_specs = (P(),) + (seq_spec,) * num_seq_args
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=seq_spec, check_vma=False)

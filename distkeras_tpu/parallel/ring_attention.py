"""Ring attention: exact attention over a sequence-sharded mesh axis.

The reference has no long-context story at all (SURVEY.md §5 "long-context
/ sequence parallelism: absent" — its longest-sequence workload, the IMDB
BiLSTM, handles sequences whole per worker).  The TPU rebuild makes
sequence parallelism first-class: shard the time axis of ``q``/``k``/``v``
across a mesh axis, keep the query block resident, and rotate the
key/value blocks around the ring with ``lax.ppermute`` — one hop per
scan step, N hops total (the final hop restores the original block
placement, keeping the scan carry uniform) — accumulating exact softmax
attention with the online (flash-style) running max / denominator.  The
ICI traffic per step is one K/V block, which overlaps with the block's
matmuls on TPU.

Memory: O(T_local) per device in both directions.  The forward pass
holds only the online-softmax accumulators and never materializes a
[T_local, T_global] attention matrix; the backward pass is a custom
reverse-ring VJP (the flash-attention backward) that saves just
``(q, k, v, out, logsumexp)`` and recomputes each block's probabilities
in a second ring pass, with the dK/dV accumulators traveling alongside
their K/V blocks so each arrives home after N hops.  No per-step
residual stacks anywhere — peak memory is independent of the ring size.

This is an SPMD op: call it inside ``jax.shard_map`` (or use
``ring_attn_fn`` as the ``attn_fn`` of a ``TransformerLM`` whose
``seq_axis`` names the mesh axis).  First-order differentiable; the
gradients are tested against dense attention (tests/test_ring_attention).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# numpy, not jnp: a module-level jnp constant would initialize the XLA
# backend at import time, breaking jax.distributed.initialize callers
_NEG = np.float32(-1e30)


def _ring(axis_name: str):
    """The one-hop-backward permutation (block s lands on device s-1)."""
    n = lax.axis_size(axis_name)
    return n, lax.axis_index(axis_name), [(i, (i - 1) % n)
                                          for i in range(n)]


def _vary(axis_name, trees):
    """Mark zero-initialized scan carries as device-varying (scan's
    carry typing must agree with the computed, varying outputs)."""
    return tuple(lax.pcast(x, (axis_name,), to="varying") for x in trees)


def _block_mask(src, t_local, q_pos):
    k_pos = src * t_local + jnp.arange(t_local)
    return (q_pos[:, None] >= k_pos[None, :])[None, None]


def _forward_scan(q, k, v, axis_name, scale, causal):
    """Online-softmax ring forward.  Returns ``(out32 [B,T,H,D],
    L [B,H,T])`` where ``L = m + log(l)`` is the per-row logsumexp the
    backward pass needs to re-normalize recomputed probabilities."""
    q32 = q.astype(jnp.float32)
    b, t_local, h, d = q32.shape
    n, me, ring = _ring(axis_name)
    q_pos = me * t_local + jnp.arange(t_local)

    def body(carry, s):
        k_blk, v_blk, m, l, acc = carry
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32,
                            k_blk.astype(jnp.float32)) * scale
        if causal:
            mask = _block_mask((me + s) % n, t_local, q_pos)
            logits = jnp.where(mask, logits, _NEG)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        if causal:
            p = p * mask  # exact zeros for masked entries
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
        # Rotate (the hop after the last step restores the original
        # placement, which keeps the scan carry shape uniform).
        k_blk = lax.ppermute(k_blk, axis_name, ring)
        v_blk = lax.ppermute(v_blk, axis_name, ring)
        return (k_blk, v_blk, m_new, l, acc), None

    init = (k, v, *_vary(axis_name, (
        jnp.full((b, h, t_local), _NEG, jnp.float32),
        jnp.zeros((b, h, t_local), jnp.float32),
        jnp.zeros((b, h, t_local, d), jnp.float32))))
    (_, _, m, l, acc), _ = lax.scan(body, init, jnp.arange(n))
    l = jnp.maximum(l, 1e-30)
    out = jnp.einsum("bhqd->bqhd", acc / l[..., None])
    return out, m + jnp.log(l)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_attention_f32(q, k, v, axis_name, scale, causal):
    out, _ = _forward_scan(q, k, v, axis_name, scale, causal)
    return out


def _fwd(q, k, v, axis_name, scale, causal):
    out, lse = _forward_scan(q, k, v, axis_name, scale, causal)
    return out, (q, k, v, out, lse)


def _bwd(axis_name, scale, causal, residuals, dout):
    """Reverse ring: the flash-attention backward, with dK/dV
    accumulators traveling *with* their K/V blocks around the ring so
    each returns home after N hops having collected every device's
    contribution.  Per-device memory is O(T_local) — no per-step
    residual stacks (the motivation for the custom VJP)."""
    q, k, v, out, lse = residuals
    q32 = q.astype(jnp.float32)
    dout32 = dout.astype(jnp.float32)
    b, t_local, h, d = q32.shape
    n, me, ring = _ring(axis_name)
    q_pos = me * t_local + jnp.arange(t_local)
    # D_i = rowsum(dO_i * O_i), the softmax-jacobian diagonal term
    D = jnp.einsum("bqhd,bqhd->bhq", dout32, out.astype(jnp.float32))

    def body(carry, s):
        k_blk, v_blk, dk, dv, dq = carry
        k32 = k_blk.astype(jnp.float32)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32, k32) * scale
        if causal:
            # mask BEFORE exp (as the forward does): a masked future-key
            # logit can exceed lse by enough to overflow exp; relying on
            # inf * False == 0 would pin correctness to a lowering detail
            mask = _block_mask((me + s) % n, t_local, q_pos)
            logits = jnp.where(mask, logits, _NEG)
        p = jnp.exp(logits - lse[..., None])  # normalized probs
        if causal:
            p = p * mask  # exact zeros
        dv = dv + jnp.einsum("bhqk,bqhd->bkhd", p, dout32)
        dp = jnp.einsum("bqhd,bkhd->bhqk", dout32,
                        v_blk.astype(jnp.float32))
        ds = p * (dp - D[..., None]) * scale
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds, k32)
        dk = dk + jnp.einsum("bhqk,bqhd->bkhd", ds, q32)
        k_blk = lax.ppermute(k_blk, axis_name, ring)
        v_blk = lax.ppermute(v_blk, axis_name, ring)
        dk = lax.ppermute(dk, axis_name, ring)
        dv = lax.ppermute(dv, axis_name, ring)
        return (k_blk, v_blk, dk, dv, dq), None

    zeros_kv = jnp.zeros((b, t_local, h, d), jnp.float32)
    init = (k, v, *_vary(axis_name, (zeros_kv, zeros_kv, zeros_kv)))
    (_, _, dk, dv, dq), _ = lax.scan(body, init, jnp.arange(n))
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


_ring_attention_f32.defvjp(_fwd, _bwd)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str, scale: float | None = None,
                   causal: bool = True) -> jax.Array:
    """Exact (flash-accumulated) attention over a ring of devices.

    Args:
      q, k, v: local sequence blocks ``[B, T_local, H, D]`` — the global
        time axis is sharded over ``axis_name`` in mesh order, so device
        ``i`` holds global positions ``[i*T_local, (i+1)*T_local)``.
      axis_name: the mesh axis the sequence is sharded over.
      scale: logit scale; defaults to ``D ** -0.5``.
      causal: apply a causal mask in *global* positions.

    Returns:
      Attention output ``[B, T_local, H, D]`` in ``q.dtype`` (all
      accumulation in f32).

    Differentiation uses a custom reverse-ring VJP (flash backward:
    probabilities recomputed from the saved logsumexp, dK/dV
    accumulators riding the ring) with O(T_local) residual memory per
    device.  First-order only — higher-order autodiff through this op
    is not defined.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    out = _ring_attention_f32(q, k, v, axis_name, float(scale),
                              bool(causal))
    return out.astype(q.dtype)


def ring_attn_fn(axis_name: str, causal: bool = True):
    """An ``AttnFn`` (``TransformerLM.attn_fn`` signature) bound to a
    mesh axis: ``fn(q, k, v, *, scale)``."""
    return functools.partial(ring_attention, axis_name=axis_name,
                             causal=causal)


def sequence_sharded_apply(fn, mesh, seq_axis: str, *,
                           num_seq_args: int = 1):
    """Wrap ``fn(params, *arrays)`` in a ``shard_map`` that shards axis 1
    (time) of each array argument over ``seq_axis`` and replicates
    ``params`` — the standard harness for running a ``seq_axis``-enabled
    model (e.g. ``TransformerLM(seq_axis=...)``) sequence-parallel.

    ``num_seq_args`` array arguments follow ``params``; outputs are
    returned sequence-sharded (time axis 1).
    """
    from jax.sharding import PartitionSpec as P

    seq_spec = P(None, seq_axis)
    in_specs = (P(),) + (seq_spec,) * num_seq_args
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=seq_spec, check_vma=False)

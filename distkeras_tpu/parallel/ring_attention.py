"""Ring attention: exact attention over a sequence-sharded mesh axis.

The reference has no long-context story at all (SURVEY.md §5 "long-context
/ sequence parallelism: absent" — its longest-sequence workload, the IMDB
BiLSTM, handles sequences whole per worker).  The TPU rebuild makes
sequence parallelism first-class: shard the time axis of ``q``/``k``/``v``
across a mesh axis, keep the query block resident, and rotate the
key/value blocks around the ring with ``lax.ppermute`` — one hop per
step, N-1 hops total — accumulating exact softmax attention with the
online (flash-style) running max / denominator.  The ICI traffic per
step is one K/V block, which overlaps with the block's matmuls on TPU.

Memory: the forward pass holds O(T_local) activations per device and
never materializes a [T_local, T_global] attention matrix.  The backward
pass is autodiff through the scan with a rematerialized body: scan
stores only the per-step carries (the rotating K/V blocks and f32
accumulators) and recomputes each block's logits/probabilities in the
backward sweep, so training memory is linear in sequence length, not
quadratic.  (A custom reverse-ring VJP that re-rotates K/V instead of
storing per-step carries would cut the stored-carry term from
O(T_global) to O(T_local) per device; future work.)

This is an SPMD op: call it inside ``jax.shard_map`` (or use
``ring_attn_fn`` as the ``attn_fn`` of a ``TransformerLM`` whose
``seq_axis`` names the mesh axis).  Differentiable (the backward pass is
autodiff through ``ppermute``, i.e. the reverse ring).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# numpy, not jnp: a module-level jnp constant would initialize the XLA
# backend at import time, breaking jax.distributed.initialize callers
_NEG = np.float32(-1e30)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str, scale: float | None = None,
                   causal: bool = True) -> jax.Array:
    """Exact (flash-accumulated) attention over a ring of devices.

    Args:
      q, k, v: local sequence blocks ``[B, T_local, H, D]`` — the global
        time axis is sharded over ``axis_name`` in mesh order, so device
        ``i`` holds global positions ``[i*T_local, (i+1)*T_local)``.
      axis_name: the mesh axis the sequence is sharded over.
      scale: logit scale; defaults to ``D ** -0.5``.
      causal: apply a causal mask in *global* positions.

    Returns:
      Attention output ``[B, T_local, H, D]`` in ``q.dtype`` (accumulation
      is always f32).
    """
    orig_dtype = q.dtype
    q32 = q.astype(jnp.float32)
    b, t_local, h, d = q32.shape
    if scale is None:
        scale = d ** -0.5
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    q_pos = me * t_local + jnp.arange(t_local)

    # Each step the K/V blocks hop one device backward, so at step s this
    # device sees the block originally on device (me + s) % n.
    ring = [(i, (i - 1) % n) for i in range(n)]

    def body(carry, s):
        k_blk, v_blk, m, l, acc = carry
        src = (me + s) % n
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32,
                            k_blk.astype(jnp.float32)) * scale
        if causal:
            k_pos = src * t_local + jnp.arange(t_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, _NEG)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        if causal:
            p = p * mask[None, None]  # exact zeros for masked entries
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
        # Rotate (the hop after the last step restores the original
        # placement, which keeps the scan carry shape uniform).
        k_blk = lax.ppermute(k_blk, axis_name, ring)
        v_blk = lax.ppermute(v_blk, axis_name, ring)
        return (k_blk, v_blk, m_new, l, acc), None

    # pvary: the accumulators are device-varying (they depend on this
    # device's q block), which scan's carry typing must see from step 0.
    init = (k, v, *map(
        lambda x: lax.pcast(x, (axis_name,), to="varying"),
        (jnp.full((b, h, t_local), _NEG, jnp.float32),
         jnp.zeros((b, h, t_local), jnp.float32),
         jnp.zeros((b, h, t_local, d), jnp.float32))))
    (_, _, _, l, acc), _ = lax.scan(jax.checkpoint(body), init,
                                    jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(orig_dtype)


def ring_attn_fn(axis_name: str, causal: bool = True):
    """An ``AttnFn`` (``TransformerLM.attn_fn`` signature) bound to a
    mesh axis: ``fn(q, k, v, *, scale)``."""
    return functools.partial(ring_attention, axis_name=axis_name,
                             causal=causal)


def sequence_sharded_apply(fn, mesh, seq_axis: str, *,
                           num_seq_args: int = 1):
    """Wrap ``fn(params, *arrays)`` in a ``shard_map`` that shards axis 1
    (time) of each array argument over ``seq_axis`` and replicates
    ``params`` — the standard harness for running a ``seq_axis``-enabled
    model (e.g. ``TransformerLM(seq_axis=...)``) sequence-parallel.

    ``num_seq_args`` array arguments follow ``params``; outputs are
    returned sequence-sharded (time axis 1).
    """
    from jax.sharding import PartitionSpec as P

    seq_spec = P(None, seq_axis)
    in_specs = (P(),) + (seq_spec,) * num_seq_args
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=seq_spec, check_vma=False)
